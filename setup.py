"""Packaging shim (reference: setup.py:1-12); metadata in pyproject.toml.

The native input-pipeline library (ray_lightning_tpu/native/src) is
intentionally NOT compiled at install time: it builds lazily on first use
with the system toolchain and degrades to the pure-Python path when no
compiler is available (native/__init__.py), so the wheel stays pure.
"""

from setuptools import setup

setup()
