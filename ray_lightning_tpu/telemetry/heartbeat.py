"""Worker heartbeats: periodic liveness beats over the worker→driver
queue channel, consumed by the driver watchdog
(telemetry/aggregator.py).

Two start sites share this one sender:

- ``worker_main`` (built-in backend) starts a process-level sender the
  moment the actor connects — before jax ever imports — so a worker
  that wedges during backend/tunnel init is already visible to the
  watchdog.  Gated by ``RLT_TELEMETRY=1`` in the worker env.
- ``plugins/xla._worker_run`` starts one under backends with no
  process-level sender (real Ray actors), after the queue proxy exists.

Each beat carries rank (re-read from the environment every beat — the
built-in backend assigns ranks after spawn), pid, host, actor id, the
most recently entered span and the span ring's drop count, so the
watchdog can report "rank 2, last span 'step', heartbeat 34s old"
instead of a silent hang.

Beats also FLUSH the span recorder first: span batches otherwise wait
for ``flush_every`` records, and a rank that dies mid-batch takes its
most recent spans with it — the exact evidence the driver's crash
flight recorder (telemetry/flight.py) exists to keep.  Flushing at
heartbeat cadence bounds that loss window to ``heartbeat_interval``
seconds instead of up to ``flush_every`` records.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Optional

from ray_lightning_tpu.telemetry import spans
from ray_lightning_tpu.telemetry.aggregator import TELEMETRY_KEY

_process_sender: "Optional[HeartbeatSender]" = None


def make_heartbeat(rank: int, actor_id: Optional[str] = None) -> dict:
    beat = {
        TELEMETRY_KEY: 1,
        "kind": "heartbeat",
        "rank": rank,
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "actor_id": actor_id,
        "wall": time.time(),
        "last_span": spans.last_span(),
        "dropped": spans.dropped(),
    }
    # latest metrics brief (step, HBM, last collective) so a wedged
    # rank's watchdog diagnosis says WHAT it was doing when it went
    # silent, not just that it did (telemetry/metrics.py)
    from ray_lightning_tpu.telemetry.metrics import (metrics_brief,
                                                     sample_tail)
    brief = metrics_brief()
    if brief is not None:
        beat["metrics"] = brief
    # rolling sample tail (step wall / cadence / data wait): the
    # incident detectors dedupe by timestamp watermark, so carrying the
    # tail on every beat keeps them ticking even when span batches are
    # dropped under backpressure (incident-plane satellite)
    tail = sample_tail()
    if tail:
        beat["samples"] = tail
    return beat


def _env_rank() -> int:
    try:
        return int(os.environ.get("RLT_PROCESS_ID", "-1"))
    except ValueError:
        return -1


class HeartbeatSender:
    """Daemon thread beating every ``interval`` seconds via ``send``
    (a callable taking the beat dict).  A send failure (driver gone)
    ends the thread quietly — heartbeats must never crash a worker."""

    def __init__(self, send: Callable[[dict], None],
                 rank: Optional[int] = None, interval: float = 5.0,
                 actor_id: Optional[str] = None):
        self._send = send
        self._rank = rank
        self._interval = max(0.05, float(interval))
        self._actor_id = actor_id
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rlt-heartbeat")

    def start(self) -> "HeartbeatSender":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            rank = self._rank if self._rank is not None else _env_rank()
            try:
                # span batches first: bound the crash-loss window to one
                # heartbeat interval (module docstring).  The recorder's
                # sink is the same thread-safe queue this beat rides.
                spans.flush()
                self._send(make_heartbeat(rank, self._actor_id))
            except Exception:
                return
            self._stop.wait(self._interval)


def start_process_heartbeat(send: Callable[[dict], None],
                            interval: float = 5.0,
                            actor_id: Optional[str] = None
                            ) -> HeartbeatSender:
    """Start (once) the per-process sender used by worker_main; rank is
    re-read from ``RLT_PROCESS_ID`` each beat."""
    global _process_sender
    if _process_sender is None:
        _process_sender = HeartbeatSender(
            send, rank=None, interval=interval, actor_id=actor_id).start()
    return _process_sender


def process_heartbeat_active() -> bool:
    """True when the per-process (worker_main) sender is running — the
    plugin-level start site then skips starting a duplicate."""
    return _process_sender is not None
