"""Driver-side telemetry aggregation, watchdog and trace export.

Workers stream batched span/counter records and heartbeats through the
existing worker→driver queue (``{type: queue}`` frames under the
built-in backend, ``ray.util.queue`` under Ray — cluster/protocol.py);
``process_results`` routes every telemetry-marked item here.  The
aggregator:

- merges all ranks into one timeline and exports a Chrome/Perfetto
  ``trace.json`` (one Perfetto "process" per rank) plus a
  ``telemetry.jsonl`` record stream next to the CSVLogger output;
- computes per-rank step-time percentiles and straggler skew
  (max/min of per-rank mean step time);
- ingests per-rank cumulative metrics windows (telemetry/metrics.py),
  keeps the window stream for ``metrics.jsonl``, and derives per-rank /
  per-op collective achieved bandwidth (GiB/s) and HBM peaks into the
  summary — the numbers the live ``/metrics`` exposition
  (telemetry/exporter.py) serves while the run is still going;
- runs the heartbeat watchdog: a rank that was beating and stopped for
  longer than ``heartbeat_timeout`` gets a driver log line naming the
  rank, its last span and heartbeat age — the "which worker wedged"
  diagnosis the reference never had (a straggling host was invisible
  until the whole job stalled, SURVEY.md §5);
- mirrors every ingested batch into the crash flight recorder
  (telemetry/flight.py): bounded per-rank rings dumped as
  ``flight_<rank>.json`` on a wedge verdict, at elastic
  death-classification time, or when the failure diagnosis finds a
  dead process — the black box the normal export path cannot be;
- reassembles per-request span trees from the trace ids the serve
  plane's plan broadcast propagates (telemetry/tracing.py):
  ``request_trees`` groups driver + worker spans by trace id, and
  ``tenant_breakdown`` summarizes per-tenant TTFT/TPOT with queue vs
  prefill vs decode attribution for ``/status``.

The active aggregator is THREAD-local (``set_active``): the builtin
tune runner executes trials on threads, and each trial's
``process_results`` loop must feed its own aggregator.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Optional

_log = logging.getLogger(__name__)

#: marker key on queue items that belong to telemetry, not user relays
TELEMETRY_KEY = "__rlt_telemetry__"


def spans_item(rank: int, records: list[dict], host: Optional[str] = None,
               pid: Optional[int] = None) -> dict:
    """Wire item carrying a batch of span/counter records."""
    return {TELEMETRY_KEY: 1, "kind": "spans", "rank": rank,
            "host": host, "pid": pid or os.getpid(), "records": records}


_local = threading.local()


def set_active(agg: "Optional[TelemetryAggregator]") -> None:
    _local.agg = agg


def get_active() -> "Optional[TelemetryAggregator]":
    return getattr(_local, "agg", None)


class WorkerHeartbeatTimeout(RuntimeError):
    """Raised by the watchdog when ``hard_timeout`` is configured and a
    rank's heartbeats have been silent that long."""


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (numpy-free:
    this package must stay importable before heavy deps load)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class TelemetryAggregator:
    """Merge per-rank telemetry; diagnose dead/wedged workers."""

    def __init__(self, out_dir: str, heartbeat_timeout: float = 60.0,
                 hard_timeout: Optional[float] = None,
                 clock=time.monotonic, flight_capacity: int = 256,
                 incident_cfg=None, run_kind: str = "fit"):
        from ray_lightning_tpu.telemetry.flight import FlightRecorder
        from ray_lightning_tpu.telemetry.incident import IncidentManager
        self.out_dir = out_dir
        self.heartbeat_timeout = heartbeat_timeout
        self.hard_timeout = hard_timeout
        self._clock = clock
        #: crash black box: bounded per-rank rings of the most recent
        #: ingested spans/heartbeats, dumpable independently of export
        self.flight = FlightRecorder(out_dir,
                                     span_capacity=flight_capacity)
        #: incident plane (telemetry/incident.py): live timelines +
        #: rolling anomaly detectors + auto-RCA reports, fed from every
        #: ingest path below and ticked at sample arrival (driver-side
        #: poll loops, never a worker hot path)
        self.incidents = IncidentManager(
            out_dir, cfg=incident_cfg, run_kind=run_kind, clock=clock,
            flight_hook=lambda rank, cause: self.flight.dump(
                rank, cause, handle=self._workers.get(rank)))
        #: per-rank (start_ts, k) of the previous step span — the
        #: step_interval_s series (start-to-start cadence) catches a
        #: straggler whose sleep lands BETWEEN its own step spans
        self._prev_step_span: dict[int, tuple] = {}
        self._lock = threading.Lock()
        #: /status memoization: sections recompute only when the ingest
        #: epoch moved (every mutation bumps it); scrapes between
        #: ingests are dictionary lookups
        self._epoch = 0
        self._memo: dict[str, tuple] = {}
        self.memo_recomputes: dict[str, int] = {}
        self._records: list[dict] = []
        #: pid -> {"at": driver clock, "beat": latest beat dict}; keyed
        #: by pid because the backend-level sender may beat before the
        #: worker learns its rank (the beat itself carries the rank)
        self._hb: dict[int, dict] = {}
        self._workers: dict[int, Any] = {}   # rank -> ActorHandle
        self._warned: set[int] = set()
        self._diagnosed = False
        #: metrics plane (telemetry/metrics.py): full window stream for
        #: metrics.jsonl (bounded) + latest cumulative window per rank
        self._metric_windows: list[dict] = []
        self._metric_windows_cap = 20000
        self._metric_windows_dropped = 0
        self._metrics_latest: dict[int, dict] = {}
        self._metrics_first_ts: dict[int, float] = {}
        #: anatomy plane (telemetry/anatomy.py): latest measured
        #: per-step breakdown per rank + total windows ingested
        self._anatomy_latest: dict[int, dict] = {}
        self._anatomy_windows = 0
        #: elastic plane: per-rank liveness verdicts + the cumulative
        #: shrink-to-continue restart count, exported as driver-side
        #: (rank -1) series so /metrics shows FLEET health, not just
        #: driver-log text (rlt_worker_alive / rlt_restarts_total)
        self._fleet_alive: dict[int, int] = {}
        self._restarts = 0
        #: recovery route + driver-side decision seconds of the current
        #: elastic attempt (parity | replay | scratch — elastic/driver)
        self._recovery_mode: Optional[str] = None
        self._recovery_seconds: Optional[float] = None
        #: goodput plane (telemetry/goodput.py): latest finalized run
        #: ledger per rank + the driver-side recovery attribution that
        #: folds into the fleet aggregate (replayed steps become the
        #: ``replay`` badput bucket, decision seconds the ``recovery``
        #: bucket)
        self._goodput_latest: dict[int, dict] = {}
        self._replayed_steps = 0

    # -- memoized section assembly ---------------------------------------

    def _memoized(self, key: str, fn):
        """Recompute ``fn`` only when the ingest epoch moved since its
        last computation — /status scrapes of an idle aggregator cost
        one dict lookup per section, not a full re-aggregation."""
        with self._lock:
            epoch = self._epoch
            hit = self._memo.get(key)
        if hit is not None and hit[0] == epoch:
            return hit[1]
        val = fn()
        with self._lock:
            self._memo[key] = (epoch, val)
            self.memo_recomputes[key] = \
                self.memo_recomputes.get(key, 0) + 1
        return val

    # -- ingestion -------------------------------------------------------

    def register_worker(self, rank: int, handle: Any = None) -> None:
        self._workers[rank] = handle

    def maybe_ingest(self, item: Any) -> bool:
        """Consume a queue payload if it is telemetry; False otherwise
        (the caller then treats it as a normal relay item)."""
        if not (isinstance(item, dict) and item.get(TELEMETRY_KEY)):
            return False
        kind = item.get("kind")
        if kind == "spans":
            self.ingest_records(item.get("rank", -1), item["records"])
        elif kind == "heartbeat":
            self._note_heartbeat(item)
        elif kind == "metrics":
            self.ingest_metrics(item)
        elif kind == "anatomy":
            self.ingest_anatomy(item)
        elif kind == "goodput":
            self.ingest_goodput(item)
        return True

    def ingest_goodput(self, item: dict) -> None:
        """One rank's finalized run-ledger doc (telemetry/goodput.py):
        keep the latest per rank for /status + the export summary, and
        mirror a brief into the flight recorder so a crash's black box
        says where THAT rank's run wall was going."""
        rank = item.get("rank", -1)
        doc = item.get("goodput") or {}
        with self._lock:
            self._goodput_latest[rank] = dict(doc)
            self._epoch += 1
        self.flight.note_goodput(rank, doc)
        self.incidents.note_goodput(doc)

    def set_replayed_steps(self, n: int) -> None:
        """Steps the resumed attempt re-executed after a snapshot-replay
        recovery (elastic/driver.py) — re-attributed from the fleet
        aggregate's ``step`` bucket into ``replay`` badput."""
        with self._lock:
            self._replayed_steps = max(0, int(n))
            self._epoch += 1
        if n:
            self.incidents.note_event("replay", steps=int(n))

    def goodput_stats(self) -> dict:
        """Per-rank run-ledger docs + the fleet aggregate (identity
        ``sum(buckets) == run_wall`` holds on both levels) — the
        ``goodput`` section of /status and the export summary.
        Memoized per ingest epoch."""
        return self._memoized("goodput_stats", self._compute_goodput_stats)

    def _compute_goodput_stats(self) -> dict:
        from ray_lightning_tpu.telemetry import goodput as _goodput
        with self._lock:
            latest = {r: dict(d)
                      for r, d in sorted(self._goodput_latest.items())}
            replayed = self._replayed_steps
            rec_s = self._recovery_seconds
        if not latest:
            return {}
        docs = list(latest.values())
        extra = {}
        if rec_s and docs[0].get("kind") == "fit":
            extra["recovery"] = float(rec_s)
        fleet = _goodput.aggregate(docs, extra_buckets=extra)
        if replayed and fleet:
            fleet = _goodput.reattribute_replay(fleet, replayed)
        return {"per_rank": {str(r): d for r, d in latest.items()},
                "fleet": fleet}

    def ingest_anatomy(self, item: dict) -> None:
        """One rank's compact step anatomy (telemetry/anatomy.py): keep
        the latest per rank for /status + the export summary, and
        mirror it into the flight recorder so a crash's black box
        carries where THAT rank's device time was going."""
        rank = item.get("rank", -1)
        anatomy = item.get("anatomy") or {}
        with self._lock:
            self._anatomy_latest[rank] = dict(anatomy)
            self._anatomy_windows += 1
            self._epoch += 1
        self.flight.note_anatomy(rank, anatomy)
        # incident evidence: a window arriving while an incident is
        # open is exactly the capture that incident armed; the carried
        # dir (incident-armed windows keep theirs) becomes the link
        self.incidents.note_anatomy(rank, anatomy,
                                    capture_dir=item.get("dir"))
        self.incidents.note_event("anatomy", rank=rank,
                                  dir=item.get("dir"))

    def anatomy_stats(self) -> dict:
        """Per-rank measured step anatomy + straggler skew (slowest
        rank's measured step wall / fastest's) — the ``anatomy``
        section of /status and the export summary.  Memoized per
        ingest epoch."""
        return self._memoized("anatomy_stats", self._compute_anatomy_stats)

    def _compute_anatomy_stats(self) -> dict:
        with self._lock:
            latest = {str(r): dict(a)
                      for r, a in sorted(self._anatomy_latest.items())}
            windows = self._anatomy_windows
        if not latest:
            return {}
        out: dict[str, Any] = {"per_rank": latest, "windows": windows}
        walls = [a.get("wall_s", 0.0) for a in latest.values()]
        if len(walls) >= 2 and min(walls) > 0:
            out["straggler_skew"] = round(max(walls) / min(walls), 3)
        return out

    def ingest_metrics(self, item: dict) -> None:
        """One cumulative metrics window from a rank: keep the stream
        (for metrics.jsonl) and the latest state (for /metrics)."""
        rank = item.get("rank", -1)
        with self._lock:
            if len(self._metric_windows) >= self._metric_windows_cap:
                self._metric_windows.pop(0)
                self._metric_windows_dropped += 1
            self._metric_windows.append(item)
            self._metrics_latest[rank] = item
            self._metrics_first_ts.setdefault(
                rank, item.get("ts", time.time()))
            self._epoch += 1
        if self.incidents.cfg.enabled:
            peaks = [float(m.get("value", 0.0))
                     for m in item.get("metrics", ())
                     if m.get("name") == "rlt_hbm_peak_bytes"]
            if peaks:
                self.incidents.note_sample(
                    "hbm_peak_bytes", rank, max(peaks),
                    ts=item.get("ts"))

    def latest_metrics(self) -> dict[int, dict]:
        """rank -> latest cumulative metrics window (exporter surface).
        A synthetic rank ``-1`` window carries the driver's own series
        (fleet liveness, restart count) when any exist — merged with an
        ingested rank ``-1`` window (the serve plane's driver registry)
        rather than clobbering it."""
        with self._lock:
            out = dict(self._metrics_latest)
        drv = self._driver_metrics()
        if drv:
            base = out.get(-1)
            out[-1] = {
                TELEMETRY_KEY: 1, "kind": "metrics", "rank": -1,
                "ts": time.time(),
                "metrics": (list(base.get("metrics", ()))
                            if base else []) + drv,
            }
        return out

    # -- fleet health (elastic plane) ------------------------------------

    def set_restarts(self, n: int) -> None:
        """Cumulative shrink-to-continue restart count — set by the
        plugin on every attempt so the counter survives the per-attempt
        aggregator rebuild (elastic/driver.py)."""
        with self._lock:
            self._restarts = int(n)
            self._epoch += 1

    def set_recovery(self, mode: Optional[str],
                     seconds: Optional[float] = None) -> None:
        """The recovery route the elastic driver chose for this attempt
        (``parity``/``replay``/``scratch``) plus its classification+
        reconstruction seconds — exported as ``rlt_recovery_mode`` /
        ``rlt_recovery_seconds`` driver-side series so the zero-replay
        path is visible on ``/metrics``, not just in the report."""
        with self._lock:
            self._recovery_mode = mode
            self._recovery_seconds = seconds
            self._epoch += 1
        if mode is not None:
            self.incidents.note_event("recovery", mode=mode,
                                      seconds=seconds)

    def note_event(self, name: str, **detail: Any) -> None:
        """One correlated run event (compile, snapshot, snapshot_stall,
        autoscale, plan, …) onto the incident timeline — the log a
        fresh incident pulls as evidence."""
        self.incidents.note_event(name, **detail)
        with self._lock:
            self._epoch += 1

    def note_serve_signals(self, queue_depth: Optional[float] = None,
                           ttft_p99_s: Optional[float] = None,
                           tpot_p99_s: Optional[float] = None) -> None:
        """Serve-plane driver signals (pump peek / fleet autoscaler
        tick): the fleetwide TTFT/TPOT/queue-depth detector feed."""
        if not self.incidents.cfg.enabled:
            return
        if queue_depth is not None:
            self.incidents.note_sample("queue_depth", -1,
                                       float(queue_depth))
        if ttft_p99_s is not None:
            self.incidents.note_sample("ttft_p99_s", -1,
                                       float(ttft_p99_s))
        if tpot_p99_s is not None:
            self.incidents.note_sample("tpot_p99_s", -1,
                                       float(tpot_p99_s))

    def incident_stats(self) -> dict:
        """The ``incidents`` section of /status and the export summary."""
        return self.incidents.stats()

    def timeline_window(self, series: Optional[str] = None,
                        rank: Optional[int] = None,
                        window_s: Optional[float] = None,
                        downsample: int = 0) -> dict:
        """The ``GET /timeline`` document (telemetry/exporter.py)."""
        return self.incidents.timeline.window(
            series=series, rank=rank, window_s=window_s,
            downsample=downsample)

    def note_worker_alive(self, rank: int, alive: bool) -> None:
        v = 1 if alive else 0
        with self._lock:
            # epoch-bump only on a real change: the watchdog re-probes
            # liveness every poll iteration, and an unchanged verdict
            # must not invalidate the memoized /status sections
            if self._fleet_alive.get(rank) != v:
                self._fleet_alive[rank] = v
                self._epoch += 1

    def _update_fleet_health(self, now: float) -> None:
        """Refresh the per-rank liveness gauges: the backend's process
        probe when it can answer, heartbeat age otherwise."""
        with self._lock:
            handles = dict(self._workers)
            beats = {b["beat"].get("rank", -1): now - b["at"]
                     for b in self._hb.values()}
        for rank, handle in handles.items():
            alive = getattr(handle, "alive", lambda: None)() \
                if handle is not None else None
            if alive is None:
                age = beats.get(rank)
                if age is None:
                    continue   # never beat, nothing to say yet
                alive = age <= self.heartbeat_timeout
            self.note_worker_alive(rank, bool(alive))

    def _driver_metrics(self) -> list[dict]:
        goodput = self.goodput_stats()
        incident_samples = self.incidents.metric_samples()
        # a lone all-zero incident gauge is not worth synthesizing a
        # driver window for — only count the plane once it has news
        if len(incident_samples) == 1 \
                and not incident_samples[0]["value"]:
            incident_samples = []
        with self._lock:
            fleet = dict(self._fleet_alive)
            restarts = self._restarts
            rec_mode = self._recovery_mode
            rec_s = self._recovery_seconds
        if not fleet and not restarts and rec_mode is None \
                and not goodput and not incident_samples:
            return []
        out = [{"name": "rlt_worker_alive", "type": "gauge",
                "labels": {"worker": str(rank)}, "value": v}
               for rank, v in sorted(fleet.items())]
        out.append({"name": "rlt_restarts_total", "type": "counter",
                    "labels": {}, "value": restarts})
        if rec_mode is not None:
            out.append({"name": "rlt_recovery_mode", "type": "gauge",
                        "labels": {"mode": rec_mode}, "value": 1})
            if rec_s is not None:
                out.append({"name": "rlt_recovery_seconds",
                            "type": "gauge", "labels": {},
                            "value": rec_s})
        fleet_gp = (goodput or {}).get("fleet") or {}
        if fleet_gp:
            kind = fleet_gp.get("kind", "fit")
            for bucket, seconds in (fleet_gp.get("buckets") or {}).items():
                out.append({"name": "rlt_goodput_seconds",
                            "type": "gauge",
                            "labels": {"bucket": bucket, "kind": kind,
                                       "scope": "fleet"},
                            "value": seconds})
            out.append({"name": "rlt_goodput_fraction", "type": "gauge",
                        "labels": {"kind": kind, "scope": "fleet"},
                        "value": fleet_gp.get("goodput_fraction", 0.0)})
            if fleet_gp.get("mfu") is not None:
                out.append({"name": "rlt_mfu", "type": "gauge",
                            "labels": {"scope": "fleet"},
                            "value": fleet_gp["mfu"]})
        # incident plane: rlt_incident_total{series,verdict} +
        # rlt_incident_active ride the same driver-side rank -1 window
        out.extend(incident_samples)
        return out

    def fleet_health(self) -> dict[int, int]:
        """rank -> 1/0 liveness verdict (tests/status surface)."""
        with self._lock:
            return dict(self._fleet_alive)

    def ingest_records(self, rank: int, records: list[dict]) -> None:
        for r in records:
            r.setdefault("rank", rank)
        with self._lock:
            self._records.extend(records)
            self._epoch += 1
        self.flight.note_records(rank, records)
        if self.incidents.cfg.enabled:
            self._feed_timeline(records)

    def _feed_timeline(self, records: list[dict]) -> None:
        """Span-path timeline feed: per-step wall and data-wait samples
        plus the step-cadence (start-to-start interval) series — the
        interval catches a straggler whose stall lands BETWEEN its own
        step spans (a sleep in a callback inflates no span, but the
        whole fleet's cadence)."""
        inc = self.incidents
        for r in records:
            if r.get("t") != "span":
                continue
            name = r.get("name")
            rk = r.get("rank", -1)
            ts = float(r.get("ts", 0.0))
            dur = float(r.get("dur", 0.0))
            if name == "step":
                k = max(1, int((r.get("attrs") or {}).get("k", 1)))
                inc.note_sample("step_wall_s", rk, dur / k, ts=ts + dur)
                prev = self._prev_step_span.get(rk)
                self._prev_step_span[rk] = (ts, k)
                if prev is not None and ts > prev[0]:
                    inc.note_sample("step_interval_s", rk,
                                    (ts - prev[0]) / prev[1], ts=ts)
            elif name == "data_wait":
                inc.note_sample("data_wait_s", rk, dur, ts=ts + dur)
            elif name == "compile":
                inc.note_event("compile", ts=ts, rank=rk,
                               seconds=round(dur, 6))

    def _note_heartbeat(self, beat: dict) -> None:
        key = beat.get("pid") or beat.get("rank", -1)
        with self._lock:
            self._hb[key] = {"at": self._clock(), "beat": beat}
            # a recovered worker (e.g. un-wedged) re-arms its warning
            self._warned.discard(key)
        self.flight.note_heartbeat(beat)
        self.flight.note_metrics_brief(beat.get("rank", -1),
                                       beat.get("metrics"))
        # detector backstop: the beat's rolling sample tail keeps the
        # timelines ticking when span batches are dropped under
        # backpressure (entries the span path already fed are skipped
        # by timestamp watermark inside note_tail)
        if self.incidents.cfg.enabled and beat.get("samples"):
            self.incidents.note_tail(beat.get("rank", -1),
                                     beat.get("samples"))

    def heartbeats(self) -> dict:
        """Latest beat per worker process, with its current age on the
        driver clock (tests/diagnostics/status endpoint)."""
        now = self._clock()
        with self._lock:
            return {k: {**v, "age": now - v["at"]}
                    for k, v in self._hb.items()}

    def metrics_briefs(self) -> dict[int, dict]:
        """rank -> {step, hbm_bytes, last_collective}: the latest
        heartbeat-carried brief, falling back to values derivable from
        the rank's latest metrics window (in-process runs have metrics
        but no heartbeats)."""
        out: dict[int, dict] = {}
        for rank, item in self.latest_metrics().items():
            brief: dict = {}
            for m in item.get("metrics", ()):
                if m["name"] == "rlt_steps_total":
                    brief["step"] = int(m.get("value", 0))
                elif m["name"] == "rlt_hbm_bytes" and \
                        (m.get("labels") or {}).get("device") == "0":
                    brief["hbm_bytes"] = int(m.get("value", 0))
            if brief:
                out[rank] = brief
        with self._lock:
            beats = [v["beat"] for v in self._hb.values()]
        for beat in beats:
            brief = beat.get("metrics")
            rank = beat.get("rank", -1)
            if brief:
                out.setdefault(rank, {}).update(
                    {k: v for k, v in brief.items() if v is not None})
        return out

    # -- watchdog --------------------------------------------------------

    @staticmethod
    def _describe(beat: dict, age: float) -> str:
        rank = beat.get("rank", -1)
        who = f"rank {rank}" if rank >= 0 else \
            f"unranked worker (actor {beat.get('actor_id')!r})"
        # the heartbeat-carried metrics brief turns "went silent" into
        # "went silent at step N during a reduce_scatter with X GiB HBM
        # in use" — what the rank was doing, not just that it stopped
        extra = ""
        brief = beat.get("metrics") or {}
        if brief.get("step") is not None:
            extra += f", step {brief['step']}"
        if brief.get("hbm_bytes"):
            extra += f", hbm {brief['hbm_bytes'] / 2**30:.2f} GiB"
        if brief.get("last_collective"):
            extra += f", last collective {brief['last_collective']!r}"
        return (f"{who}: last heartbeat {age:.1f}s ago, last span "
                f"{beat.get('last_span')!r}{extra}, "
                f"pid {beat.get('pid')}, host {beat.get('host')}")

    def _alive_note(self, rank: int) -> str:
        handle = self._workers.get(rank)
        alive = getattr(handle, "alive", lambda: None)() \
            if handle is not None else None
        if alive is None:
            return ""
        return ", process alive" if alive else ", process DEAD"

    def watchdog_check(self) -> None:
        """Called from the driver's poll loop: log a diagnosis line the
        first time a rank's heartbeats go silent past the timeout (and
        raise once past ``hard_timeout`` when configured, so a wedged
        collective cannot hang the driver forever)."""
        now = self._clock()
        self._update_fleet_health(now)
        with self._lock:
            snapshot = [(k, v["at"], v["beat"]) for k, v in self._hb.items()]
        for key, at, beat in snapshot:
            age = now - at
            if age <= self.heartbeat_timeout:
                continue
            if key not in self._warned:
                self._warned.add(key)
                _log.warning(
                    "telemetry watchdog: %s%s — worker is dead or wedged "
                    "(heartbeat timeout %.1fs)",
                    self._describe(beat, age),
                    self._alive_note(beat.get("rank", -1)),
                    self.heartbeat_timeout)
                # wedge verdict: dump the rank's black box NOW — a
                # wedged worker will never flush again, so the ring is
                # the only record of what it was doing
                rank = beat.get("rank", -1)
                self.flight.dump(
                    rank,
                    f"watchdog wedge verdict: heartbeat silent "
                    f"{age:.1f}s (timeout {self.heartbeat_timeout:.1f}s)"
                    f"{self._alive_note(rank)}",
                    handle=self._workers.get(rank))
            if self.hard_timeout is not None and age > self.hard_timeout:
                raise WorkerHeartbeatTimeout(
                    f"telemetry watchdog: {self._describe(beat, age)} "
                    f"exceeded hard timeout {self.hard_timeout:.1f}s")

    def log_failure_diagnosis(self) -> None:
        """On a worker failure, log every worker's last-known state once
        — turns 'a future errored' into 'rank 2 died mid-step'."""
        if self._diagnosed:
            return
        self._diagnosed = True
        now = self._clock()
        with self._lock:
            snapshot = [(v["at"], v["beat"]) for v in self._hb.values()]
        if not snapshot:
            return
        lines = [self._describe(beat, now - at) for at, beat in snapshot]
        _log.warning("telemetry: worker state at failure:\n  %s",
                     "\n  ".join(lines))
        # black-box dumps for every rank whose process probe reads dead:
        # the failure that just surfaced on the driver is about to tear
        # the fleet down, and these rings are the last evidence
        for rank, handle in sorted(self._workers.items()):
            alive = getattr(handle, "process_alive", lambda: None)() \
                if handle is not None else None
            if alive is False:
                self.flight.dump(rank, "worker failure: process dead "
                                 "at failure diagnosis", handle=handle)

    def dump_flights(self, ranks, cause: str) -> list:
        """Dump ``flight_<rank>.json`` for each given rank (the elastic
        driver's death-classification hook).  Returns the paths."""
        out = []
        for rank in ranks:
            path = self.flight.dump(rank, cause,
                                    handle=self._workers.get(rank))
            if path:
                out.append(path)
        return out

    # -- analysis --------------------------------------------------------

    def step_stats(self) -> dict:
        """Per-rank step-time percentiles + straggler skew.  Chunked
        dispatch (k steps per span) is normalized to per-step time.
        Memoized per ingest epoch."""
        return self._memoized("step_stats", self._compute_step_stats)

    def _compute_step_stats(self) -> dict:
        per_rank: dict[int, list[float]] = {}
        with self._lock:
            records = list(self._records)
        for r in records:
            if r.get("t") == "span" and r.get("name") == "step":
                k = max(1, int((r.get("attrs") or {}).get("k", 1)))
                per_rank.setdefault(r.get("rank", -1), []).append(
                    r["dur"] * 1000.0 / k)
        out: dict[str, Any] = {"per_rank": {}}
        means = []
        for rank in sorted(per_rank):
            ds = sorted(per_rank[rank])
            mean = sum(ds) / len(ds)
            means.append(mean)
            out["per_rank"][str(rank)] = {
                "steps": len(ds),
                "mean_ms": round(mean, 3),
                "p50_ms": round(_percentile(ds, 50), 3),
                "p90_ms": round(_percentile(ds, 90), 3),
                "p95_ms": round(_percentile(ds, 95), 3),
                "max_ms": round(ds[-1], 3),
            }
        if len(means) >= 2 and min(means) > 0:
            # straggler skew: how much slower the slowest rank's mean
            # step is than the fastest rank's (1.0 = perfectly even)
            out["straggler_skew"] = round(max(means) / min(means), 3)
        return out

    # -- per-request tracing (telemetry/tracing.py) ----------------------

    @staticmethod
    def _span_trace_ids(record: dict) -> list:
        """Trace ids a span belongs to: its own ``trace`` attr plus
        every id in a shared span's ``traces`` map (the serve decode
        advances many requests in one program — the span fans out to
        each of their trees)."""
        attrs = record.get("attrs") or {}
        ids = []
        tid = attrs.get("trace")
        if tid:
            ids.append(str(tid))
        shared = attrs.get("traces")
        if isinstance(shared, dict):
            ids.extend(str(t) for t in shared.values() if t)
        elif isinstance(shared, (list, tuple)):
            ids.extend(str(t) for t in shared if t)
        return ids

    def request_trees(self) -> dict[str, list[dict]]:
        """trace id -> that request's spans (driver + every rank),
        time-ordered: the reassembled queue→prefill→decode→complete
        tree of each request's life."""
        with self._lock:
            records = list(self._records)
        trees: dict[str, list[dict]] = {}
        for r in records:
            if r.get("t") != "span":
                continue
            for tid in self._span_trace_ids(r):
                trees.setdefault(tid, []).append(r)
        for spans_ in trees.values():
            spans_.sort(key=lambda r: (r.get("ts", 0.0),
                                       r.get("depth", 0)))
        return trees

    def tenant_breakdown(self) -> dict[str, dict]:
        """Per-tenant request-latency attribution from the driver-side
        ``request`` summary spans (+ worker ``prefill`` spans joined by
        trace id): TTFT split into queue wait vs prefill, decode time
        and TPOT — the "which phase is slow for WHICH tenant" surface
        on ``/status`` and in the exported summary.  Memoized per
        ingest epoch."""
        return self._memoized("tenant_breakdown",
                              self._compute_tenant_breakdown)

    def _compute_tenant_breakdown(self) -> dict[str, dict]:
        with self._lock:
            records = list(self._records)
        prefill_by_trace: dict[str, float] = {}
        requests: list[tuple[dict, dict]] = []
        for r in records:
            if r.get("t") != "span":
                continue
            attrs = r.get("attrs") or {}
            if r.get("name") == "prefill" and attrs.get("trace") \
                    and r.get("rank", -1) >= 0:
                prefill_by_trace[str(attrs["trace"])] = float(
                    r.get("dur", 0.0))
            elif r.get("name") == "request":
                requests.append((r, attrs))
        out: dict[str, dict] = {}
        acc: dict[str, dict[str, list]] = {}
        for r, attrs in requests:
            tenant = str(attrs.get("tenant", "default"))
            entry = out.setdefault(tenant, {"requests": 0, "failed": 0,
                                            "tokens": 0})
            a = acc.setdefault(tenant, {"queue_wait": [], "ttft": [],
                                        "prefill": [], "decode": [],
                                        "tpot": []})
            entry["requests"] += 1
            entry["tokens"] += int(attrs.get("tokens", 0) or 0)
            if attrs.get("status") == "failed":
                entry["failed"] += 1
            ttft = attrs.get("ttft_s")
            queue = attrs.get("queue_s")
            tpot = attrs.get("tpot_s")
            if queue is not None:
                a["queue_wait"].append(float(queue))
            if ttft is not None:
                a["ttft"].append(float(ttft))
                # decode attribution: everything after the first token
                a["decode"].append(
                    max(0.0, float(r.get("dur", 0.0)) - float(ttft)))
            if tpot is not None:
                a["tpot"].append(float(tpot))
            pf = prefill_by_trace.get(str(attrs.get("trace")))
            if pf is not None:
                a["prefill"].append(pf)
        for tenant, phases in acc.items():
            entry = out[tenant]
            for phase, vals in phases.items():
                vals.sort()
                if not vals:
                    continue
                entry[f"{phase}_p50_ms"] = round(
                    _percentile(vals, 50) * 1e3, 3)
                entry[f"{phase}_p99_ms"] = round(
                    _percentile(vals, 99) * 1e3, 3)
        return out

    # -- metrics derivations ---------------------------------------------

    def _rank_step_seconds(self) -> dict[int, float]:
        """Total recorded step-span time per rank — the bandwidth
        denominator for collectives compiled into the step program."""
        out: dict[int, float] = {}
        with self._lock:
            records = list(self._records)
        for r in records:
            if r.get("t") == "span" and r.get("name") == "step":
                rank = r.get("rank", -1)
                out[rank] = out.get(rank, 0.0) + float(r.get("dur", 0.0))
        return out

    @staticmethod
    def _window_values(item: dict, name: str) -> list[tuple[dict, float]]:
        return [((m.get("labels") or {}), float(m.get("value", 0.0)))
                for m in item.get("metrics", ()) if m["name"] == name]

    def collective_stats(self) -> dict:
        """Per-op byte totals and achieved GiB/s, per rank and summed.

        Denominator preference per (rank, op): measured op seconds
        (host-dispatched collectives record them) → the rank's total
        step-span time (traced in-step collectives overlap with the
        step) → elapsed wall time between the rank's first and latest
        metrics window.  The step/wall denominators make the figure a
        lower bound on fabric bandwidth — the transfer shares the
        denominator with compute — which is exactly the "achieved"
        number a comms optimization must move."""
        step_secs = self._rank_step_seconds()
        latest = self.latest_metrics()
        with self._lock:
            first_ts = dict(self._metrics_first_ts)
        per_op: dict[str, dict] = {}
        for rank, item in latest.items():
            secs_by_op = {labels.get("op"): v for labels, v in
                          self._window_values(
                              item, "rlt_collective_seconds_total")}
            elapsed = max(0.0, item.get("ts", 0.0)
                          - first_ts.get(rank, item.get("ts", 0.0)))
            for labels, nbytes in self._window_values(
                    item, "rlt_collective_bytes_total"):
                op = labels.get("op", "?")
                if nbytes <= 0:
                    continue
                denom = secs_by_op.get(op) or step_secs.get(rank) \
                    or elapsed
                gibs = round(nbytes / denom / 2**30, 6) if denom else None
                entry = per_op.setdefault(
                    op, {"bytes": 0, "gibs": 0.0, "per_rank": {}})
                entry["bytes"] += int(nbytes)
                entry["per_rank"][str(rank)] = {
                    "bytes": int(nbytes), "gibs": gibs}
                if gibs:
                    # ranks move their shares concurrently: job-level
                    # achieved bandwidth is the sum of per-rank rates
                    entry["gibs"] = round(entry["gibs"] + gibs, 6)
        return per_op

    def hbm_stats(self) -> dict[str, int]:
        """Per-rank peak HBM bytes (device 0) from the latest windows."""
        out: dict[str, int] = {}
        for rank, item in self.latest_metrics().items():
            peaks = [v for labels, v in self._window_values(
                item, "rlt_hbm_peak_bytes")]
            if peaks:
                out[str(rank)] = int(max(peaks))
        return out

    def dropped_stats(self) -> dict[str, int]:
        """Per-rank telemetry ring-buffer drop counts — silent data loss
        the summary must surface (a trace with holes must say so)."""
        out: dict[str, int] = {}
        for rank, item in self.latest_metrics().items():
            for _labels, v in self._window_values(
                    item, "rlt_telemetry_dropped_total"):
                if v > 0:
                    out[str(rank)] = int(v)
        if self._metric_windows_dropped:
            out["driver_windows"] = self._metric_windows_dropped
        return out

    # -- export ----------------------------------------------------------

    def _trace_events(self, records: list[dict]) -> list[dict]:
        spans = [r for r in records if r.get("t") in ("span", "counter")]
        if not spans:
            return []
        t0 = min(r["ts"] for r in spans)
        events: list[dict] = []
        for rank in sorted({r.get("rank", -1) for r in spans}):
            events.append({"ph": "M", "name": "process_name", "pid": rank,
                           "args": {"name": f"rank {rank}"}})
        for r in spans:
            base = {"pid": r.get("rank", -1), "tid": 0,
                    "ts": round((r["ts"] - t0) * 1e6, 1)}
            if r["t"] == "span":
                events.append({**base, "ph": "X", "cat": "rlt",
                               "name": r["name"],
                               "dur": round(r["dur"] * 1e6, 1),
                               "args": r.get("attrs") or {}})
            else:
                events.append({**base, "ph": "C", "name": r["name"],
                               "args": {r["name"]: r["value"]}})
        return events

    def export(self) -> dict:
        """Write ``trace.json`` (Chrome/Perfetto), ``telemetry.jsonl``
        and — when any metrics windows arrived — ``metrics.jsonl``
        under ``out_dir``; returns their paths plus the summary dict."""
        os.makedirs(self.out_dir, exist_ok=True)
        trace_path = os.path.join(self.out_dir, "trace.json")
        jsonl_path = os.path.join(self.out_dir, "telemetry.jsonl")
        # an incident whose series simply stopped (the run ended) closes
        # with the reason on record before the summary freezes
        self.incidents.close_all(reason="run_end")
        with self._lock:
            records = list(self._records)
            windows = list(self._metric_windows)
        stats = self.step_stats()
        summary = {
            "t": "summary",
            "records": len(records),
            "ranks": sorted({r.get("rank", -1) for r in records}),
            "step_stats": stats,
        }
        trees = self.request_trees()
        if trees:
            # per-request trace plane: every traced request's span count
            # (the full trees are in trace.json via their trace attrs)
            # plus the per-tenant latency attribution
            summary["requests"] = {
                "traced": len(trees),
                "tenants": self.tenant_breakdown(),
            }
        if self.flight.dumped:
            summary["flight_dumps"] = dict(self.flight.dumped)
        anatomy = self.anatomy_stats()
        if anatomy:
            # measured step-time truth (telemetry/anatomy.py): where
            # device time went per rank, from real profiler captures
            summary["anatomy"] = anatomy
        goodput = self.goodput_stats()
        if goodput:
            # run-time truth (telemetry/goodput.py): the full-run
            # wall-clock partition + measured MFU, per rank and fleet
            summary["goodput"] = goodput
            fleet_gp = goodput.get("fleet") or {}
            # scalar conveniences for bench JSON lines / quick greps
            if "goodput_fraction" in fleet_gp:
                summary["goodput_fraction"] = fleet_gp["goodput_fraction"]
            if fleet_gp.get("mfu") is not None:
                summary["mfu"] = fleet_gp["mfu"]
        incidents = self.incident_stats()
        if incidents.get("total"):
            # incident plane (telemetry/incident.py): detected
            # anomalies with their cause rankings + evidence links
            summary["incidents"] = incidents
        collectives = self.collective_stats()
        hbm = self.hbm_stats()
        dropped = self.dropped_stats()
        if windows:
            summary["metrics"] = {
                "windows": len(windows),
                "collectives": collectives,
                "hbm_peak_bytes": hbm,
                "dropped_records": dropped,
            }
            # scalar conveniences for bench JSON lines / quick greps
            summary["hbm_peak_bytes"] = max(hbm.values()) if hbm else 0
            summary["collective_gibs"] = round(
                sum(v.get("gibs") or 0.0 for v in collectives.values()),
                6)
        if dropped:
            # data loss must be loud: a trace/metrics stream with holes
            # silently reads as "nothing happened there"
            _log.warning(
                "telemetry: ring buffers dropped records (per rank: %s) "
                "— raise TelemetryConfig.capacity or lower flush_every "
                "to capture the full stream", dropped)
        tmp = trace_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": self._trace_events(records),
                       "displayTimeUnit": "ms"}, f)
        os.replace(tmp, trace_path)
        tmp = jsonl_path + ".tmp"
        with open(tmp, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
            f.write(json.dumps(summary) + "\n")
        os.replace(tmp, jsonl_path)
        out = {"trace": trace_path, "jsonl": jsonl_path,
               "summary": summary}
        if windows:
            metrics_path = os.path.join(self.out_dir, "metrics.jsonl")
            tmp = metrics_path + ".tmp"
            with open(tmp, "w") as f:
                for w in windows:
                    f.write(json.dumps(w) + "\n")
                f.write(json.dumps(summary) + "\n")
            os.replace(tmp, metrics_path)
            out["metrics"] = metrics_path
        skew = stats.get("straggler_skew")
        _log.info(
            "telemetry: %d records from ranks %s -> %s%s", len(records),
            summary["ranks"], trace_path,
            f" (straggler skew {skew})" if skew else "")
        return out
