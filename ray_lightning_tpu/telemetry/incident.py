"""Incident plane: live timelines, rolling anomaly detectors, auto-RCA.

Every prior observability plane is either instantaneous (``/metrics``,
``/status`` serve the *current* value) or post-hoc (flight rings dump at
death, ``bench.py --compare`` gates at merge time).  This module makes
the run watch itself:

- :class:`TimelineStore` — bounded per-(series, rank) ring buffers of
  time-stamped samples for the load-bearing series (step wall, step
  interval, data wait, exposed comm, TTFT/TPOT p99, queue depth,
  goodput fraction, HBM peak), fed from the existing span / heartbeat /
  anatomy / goodput ingest paths and served as ``GET /timeline``
  (telemetry/exporter.py).  Memory is invariant by construction
  (``deque(maxlen=...)`` — the flight.py discipline), including a cap
  on the number of distinct (series, rank) keys.
- :class:`Detector` — rolling-baseline anomaly detection per series:
  median + MAD band over a warmup window, *consecutive*-breach patience
  and post-clear cooldown — the same debounce vocabulary as the serve
  autoscaler (serve/fleet/autoscale.py), because both answer "is this
  signal really moving or just noisy".  Breached samples never enter
  the baseline, so a spike cannot normalize itself.
- :class:`IncidentManager` — a tripped detector opens an
  :class:`Incident` that *arms its own evidence*: it writes the
  incident arm file (workers poll it inside ``anatomy_tick`` and force
  an off-cadence anatomy window — evidence captured AFTER detection,
  not luckily-before), snapshots the goodput ledger, dumps the tripping
  rank's flight ring, pulls the correlated event log (compile,
  snapshot/snapshot_stall, recovery/replay, autoscale, plan), ranks
  probable causes with a named rule per verdict (straggler-rank,
  data-starvation, exposed-comm-growth, compile-storm,
  autoscale-thrash, snapshot-stall, replan-recommended) and dumps
  ``incident_<id>.json``.  Open/closed incidents surface on ``/status``
  and in the export summary; ``rlt_incident_total{series,verdict}`` /
  ``rlt_incident_active`` ride the driver-side metric series.

The detectors run DRIVER-side (ticked from the same poll loops that
call ``watchdog_check``); the arm file is the driver→worker channel —
the same shared-filesystem control-file idiom as the on-demand profile
window (telemetry/tracing.py ``RLT_PROFILE_CONTROL``).

No numpy/jax at module import: this package must stay importable in
worker bootstrap before heavy deps load.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

_log = logging.getLogger(__name__)

#: master switch ("0"/"false" disables the whole plane)
INCIDENT_ENV = "RLT_INCIDENT"
#: per-(series, rank) timeline ring capacity
INCIDENT_CAPACITY_ENV = "RLT_INCIDENT_CAPACITY"
#: baseline samples required before a detector may trip
INCIDENT_WARMUP_ENV = "RLT_INCIDENT_WARMUP"
#: consecutive breached samples required to open (and clear) an incident
INCIDENT_PATIENCE_ENV = "RLT_INCIDENT_PATIENCE"
#: seconds after an incident closes before the same detector may re-trip
INCIDENT_COOLDOWN_ENV = "RLT_INCIDENT_COOLDOWN"
#: MAD band multiplier (bigger = less sensitive)
INCIDENT_MAD_K_ENV = "RLT_INCIDENT_MAD_K"
#: path of the incident arm file workers poll (set by the plugin, like
#: RLT_PROFILE_CONTROL — shared-filesystem backends only)
INCIDENT_CONTROL_ENV = "RLT_INCIDENT_CONTROL"

#: the incident_<id>.json top-level schema (pinned by
#: telemetry/selfcheck.py so the report format cannot drift silently)
INCIDENT_SCHEMA_KEYS = (
    "id", "run_kind", "series", "rank", "state", "verdict",
    "opened_ts", "closed_ts", "trigger", "causes", "evidence",
)

#: detector direction + per-series overrides, armed per run kind.
#: exposed_comm_s and goodput_fraction sample at anatomy/ledger cadence
#: (orders of magnitude sparser than steps), so their warmup/patience
#: are proportionally shorter.
FIT_SERIES: dict[str, tuple[str, dict]] = {
    "step_wall_s": ("high", {}),
    "step_interval_s": ("high", {}),
    "data_wait_s": ("high", {"abs_floor": 0.05}),
    "exposed_comm_s": ("high", {"warmup": 3, "patience": 1}),
    "goodput_fraction": ("low", {"warmup": 4, "patience": 2}),
    "hbm_peak_bytes": ("high", {"rel_floor": 0.10}),
}
SERVE_SERIES: dict[str, tuple[str, dict]] = {
    "ttft_p99_s": ("high", {}),
    "tpot_p99_s": ("high", {}),
    "queue_depth": ("high", {"abs_floor": 4.0}),
    "goodput_fraction": ("low", {"warmup": 4, "patience": 2}),
    "hbm_peak_bytes": ("high", {"rel_floor": 0.10}),
}

#: how far back (seconds) the event log correlates with a fresh incident
EVENT_WINDOW_S = 120.0


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# -- timelines -----------------------------------------------------------

class TimelineStore:
    """Bounded per-(series, rank) rings of ``(ts, value)`` samples plus
    one bounded event ring.  ``ts`` is wall-clock (``time.time()``,
    matching span timestamps) so worker- and driver-fed series land on
    one timeline.  Memory is invariant: each ring is a
    ``deque(maxlen=capacity)`` and the number of distinct rings is
    capped (a metric-label-cardinality explosion cannot grow the
    driver)."""

    def __init__(self, capacity: int = 512, max_keys: int = 256,
                 event_capacity: int = 256):
        self.capacity = max(8, int(capacity))
        self.max_keys = max(1, int(max_keys))
        self._lock = threading.Lock()
        self._rings: dict[tuple[str, int], deque] = {}
        self._events: deque = deque(maxlen=max(16, int(event_capacity)))
        self.dropped_keys = 0

    def note(self, series: str, rank: int, value: float,
             ts: Optional[float] = None) -> None:
        key = (str(series), int(rank))
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                if len(self._rings) >= self.max_keys:
                    self.dropped_keys += 1
                    return
                ring = self._rings[key] = deque(maxlen=self.capacity)
            ring.append((float(ts if ts is not None else time.time()),
                         float(value)))

    def note_event(self, name: str, ts: Optional[float] = None,
                   **detail: Any) -> None:
        ev = {"ts": float(ts if ts is not None else time.time()),
              "event": str(name)}
        clean = {k: v for k, v in detail.items() if v is not None}
        if clean:
            ev["detail"] = clean
        with self._lock:
            self._events.append(ev)

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({s for s, _ in self._rings})

    def latest(self, series: str, rank: int) -> Optional[tuple]:
        with self._lock:
            ring = self._rings.get((series, int(rank)))
            return ring[-1] if ring else None

    def samples(self, series: str, rank: int,
                since: Optional[float] = None) -> list[tuple]:
        with self._lock:
            ring = self._rings.get((series, int(rank)))
            out = list(ring) if ring else []
        if since is not None:
            out = [p for p in out if p[0] >= since]
        return out

    def events(self, since: Optional[float] = None) -> list[dict]:
        with self._lock:
            out = list(self._events)
        if since is not None:
            out = [e for e in out if e["ts"] >= since]
        return out

    @staticmethod
    def _downsample(points: list[tuple], limit: int) -> list[list]:
        """At most ``limit`` points, stride-sampled, always keeping the
        newest sample (the one a live dashboard cares about most)."""
        if limit <= 0 or len(points) <= limit:
            return [[round(t, 6), v] for t, v in points]
        stride = -(-len(points) // limit)          # ceil division
        kept = points[::stride]
        if kept[-1] is not points[-1]:
            kept.append(points[-1])
        return [[round(t, 6), v] for t, v in kept]

    def window(self, series: Optional[str] = None,
               rank: Optional[int] = None,
               window_s: Optional[float] = None,
               downsample: int = 0) -> dict:
        """The ``GET /timeline`` document: per-series per-rank sample
        arrays (``[[ts, value], ...]``) plus the event log, optionally
        restricted to one series/rank, the trailing ``window_s``
        seconds, and at most ``downsample`` points per ring."""
        since = time.time() - float(window_s) if window_s else None
        with self._lock:
            keys = sorted(self._rings)
        doc: dict[str, Any] = {"series": {}, "events": []}
        for s, r in keys:
            if series is not None and s != series:
                continue
            if rank is not None and r != int(rank):
                continue
            pts = self.samples(s, r, since=since)
            if not pts:
                continue
            doc["series"].setdefault(s, {})[str(r)] = \
                self._downsample(pts, int(downsample))
        doc["events"] = self.events(since=since)
        doc["dropped_keys"] = self.dropped_keys
        return doc

    def stats(self) -> dict:
        with self._lock:
            return {"keys": len(self._rings), "capacity": self.capacity,
                    "max_keys": self.max_keys,
                    "events": len(self._events),
                    "dropped_keys": self.dropped_keys}


# -- detectors -----------------------------------------------------------

@dataclass
class DetectorConfig:
    """One series' anomaly policy (autoscale.py vocabulary: a breach
    must hold ``patience`` CONSECUTIVE samples to open, a clear must
    hold ``patience`` samples to close, and after closing the detector
    is quiet for ``cooldown_s``)."""

    direction: str = "high"          # "high": spikes are bad; "low": dips
    warmup: int = 16                 # baseline samples before arming
    baseline: int = 64               # rolling baseline window size
    patience: int = 3
    cooldown_s: float = 30.0
    mad_k: float = 6.0               # band = mad_k * 1.4826 * MAD
    rel_floor: float = 0.25          # band >= rel_floor * |median|
    abs_floor: float = 0.0           # band >= abs_floor

    def __post_init__(self):
        if self.direction not in ("high", "low"):
            raise ValueError(f"detector direction {self.direction!r}")
        if self.warmup < 1 or self.patience < 1 or self.baseline < 2:
            raise ValueError("detector warmup/patience/baseline too small")


class Detector:
    """Rolling median+MAD anomaly detector over one (series, rank).

    The breach predicate is monotone by construction (selfcheck pins
    it): for a fixed baseline, if ``v`` breaches a "high" detector then
    every ``v' > v`` breaches too — the band is a threshold, not a
    window, so a worse regression can never be judged healthier."""

    def __init__(self, series: str, rank: int, cfg: DetectorConfig,
                 clock=time.monotonic):
        self.series = series
        self.rank = int(rank)
        self.cfg = cfg
        self._clock = clock
        self._baseline: deque = deque(maxlen=cfg.baseline)
        self._streak = 0
        self._clear_streak = 0
        self._cooldown_until = 0.0
        self.tripped = False
        self.trips = 0

    def band(self) -> Optional[tuple[float, float, float]]:
        """(median, lo, hi) of the current healthy band, or None while
        warming up."""
        vals = list(self._baseline)
        if len(vals) < self.cfg.warmup:
            return None
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        half = max(self.cfg.mad_k * 1.4826 * mad,
                   self.cfg.rel_floor * abs(med), self.cfg.abs_floor)
        return med, med - half, med + half

    def breaches(self, value: float) -> bool:
        b = self.band()
        if b is None:
            return False
        med, lo, hi = b
        return value > hi if self.cfg.direction == "high" else value < lo

    def observe(self, value: float,
                ts: Optional[float] = None) -> Optional[dict]:
        """Feed one sample.  Returns ``{"transition": "opened", ...}``
        when the patience streak fills, ``{"transition": "closed", ...}``
        when a tripped detector sees ``patience`` healthy samples, else
        None.  Breached samples never enter the baseline — an anomaly
        must not normalize itself into the definition of healthy."""
        value = float(value)
        now = self._clock()
        breach = self.breaches(value)
        b = self.band()
        if not breach:
            self._baseline.append(value)
        if not self.tripped:
            if breach and now >= self._cooldown_until:
                self._streak += 1
                if self._streak >= self.cfg.patience:
                    self.tripped = True
                    self.trips += 1
                    self._streak = 0
                    self._clear_streak = 0
                    med, lo, hi = b
                    return {"transition": "opened", "value": value,
                            "ts": ts, "median": med,
                            "band": [lo, hi],
                            "direction": self.cfg.direction,
                            "patience": self.cfg.patience}
            else:
                self._streak = 0
            return None
        # tripped: wait for the signal to actually recover
        if breach:
            self._clear_streak = 0
            return None
        self._clear_streak += 1
        if self._clear_streak < self.cfg.patience:
            return None
        self.tripped = False
        self._clear_streak = 0
        self._cooldown_until = now + self.cfg.cooldown_s
        out = {"transition": "closed", "value": value, "ts": ts}
        if b is not None:
            out["median"] = b[0]
            out["band"] = [b[1], b[2]]
        return out

    @property
    def in_cooldown(self) -> bool:
        return self._clock() < self._cooldown_until

    def stats(self) -> dict:
        return {"series": self.series, "rank": self.rank,
                "tripped": self.tripped, "trips": self.trips,
                "samples": len(self._baseline),
                "streak": self._streak,
                "in_cooldown": self.in_cooldown}


# -- incidents -----------------------------------------------------------

@dataclass
class IncidentConfig:
    """Driver-side incident-plane knobs (TelemetryConfig fields merged
    with the ``RLT_INCIDENT*`` env — env wins, the TelemetryConfig
    precedence rule)."""

    enabled: bool = True
    capacity: int = 512
    warmup: int = 16
    patience: int = 3
    cooldown_s: float = 30.0
    mad_k: float = 6.0
    #: steps of the evidence anatomy window an open incident arms
    arm_steps: int = 4
    #: retained incident objects (oldest closed evicted past this)
    max_incidents: int = 64

    @classmethod
    def from_env(cls, base: "Optional[IncidentConfig]" = None) \
            -> "IncidentConfig":
        cfg = base if base is not None else cls()
        env = os.environ
        if env.get(INCIDENT_ENV, "").strip().lower() in ("0", "false"):
            cfg = IncidentConfig(**{**cfg.__dict__, "enabled": False})
            return cfg
        kw = dict(cfg.__dict__)
        for env_name, key, cast in (
                (INCIDENT_CAPACITY_ENV, "capacity", int),
                (INCIDENT_WARMUP_ENV, "warmup", int),
                (INCIDENT_PATIENCE_ENV, "patience", int),
                (INCIDENT_COOLDOWN_ENV, "cooldown_s", float),
                (INCIDENT_MAD_K_ENV, "mad_k", float)):
            raw = env.get(env_name, "").strip()
            if not raw:
                continue
            try:
                kw[key] = cast(raw)
            except ValueError:
                _log.warning("%s=%r is not a %s; ignored",
                             env_name, raw, cast.__name__)
        return IncidentConfig(**kw)


@dataclass
class Incident:
    """One detected anomaly with its armed evidence and cause ranking."""

    id: str
    run_kind: str
    series: str
    rank: int
    opened_ts: float
    trigger: dict
    state: str = "open"
    closed_ts: Optional[float] = None
    verdict: str = "unattributed"
    causes: list = field(default_factory=list)
    evidence: dict = field(default_factory=dict)
    path: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id, "run_kind": self.run_kind,
            "series": self.series, "rank": self.rank,
            "state": self.state, "verdict": self.verdict,
            "opened_ts": round(self.opened_ts, 6),
            "closed_ts": (round(self.closed_ts, 6)
                          if self.closed_ts is not None else None),
            "trigger": self.trigger, "causes": self.causes,
            "evidence": self.evidence,
        }

    def brief(self) -> dict:
        return {"id": self.id, "series": self.series, "rank": self.rank,
                "state": self.state, "verdict": self.verdict,
                "opened_ts": round(self.opened_ts, 3),
                "closed_ts": (round(self.closed_ts, 3)
                              if self.closed_ts is not None else None),
                "path": self.path}


# -- arm file: the driver→worker "capture evidence NOW" channel ----------

def write_arm_file(path: str, incident_id: str, steps: int) -> bool:
    """Atomically write the incident arm file (driver side).  Workers
    polling it (:class:`ArmWatcher` inside ``anatomy_tick``) force an
    off-cadence anatomy window.  Never raises."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"id": incident_id, "steps": int(steps),
                       "ts": time.time()}, f)
        os.replace(tmp, path)
        return True
    except OSError:
        _log.debug("incident arm file write failed", exc_info=True)
        return False


class ArmWatcher:
    """Worker-side throttled poll of the arm file: yields each arm
    request exactly once per id (the tracing.py _FilePoller idiom)."""

    def __init__(self, path: str, min_poll: float = 0.25,
                 clock=time.monotonic):
        self.path = path
        self.min_poll = min_poll
        self._clock = clock
        self._next_poll = 0.0
        self._seen: set[str] = set()

    def poll(self) -> Optional[dict]:
        now = self._clock()
        if now < self._next_poll:
            return None
        self._next_poll = now + self.min_poll
        try:
            with open(self.path) as f:
                ctl = json.load(f)
        except (OSError, ValueError):
            return None
        iid = str(ctl.get("id", ""))
        if not iid or iid in self._seen:
            return None
        self._seen.add(iid)
        return ctl


# -- cause rules ---------------------------------------------------------

def _recent_vs_prior(samples: list[tuple], split_ts: float) \
        -> Optional[tuple[float, float]]:
    """(prior median, recent median) of a series around ``split_ts``."""
    prior = [v for t, v in samples if t < split_ts]
    recent = [v for t, v in samples if t >= split_ts]
    if len(prior) < 2 or not recent:
        return None
    return _median(prior), _median(recent)


def rule_straggler_rank(incident: Incident, timeline: TimelineStore,
                        events: list[dict]) -> Optional[dict]:
    """Measured (anatomy-backed) straggler attribution: when the armed
    window shows one rank with markedly LESS exposed-comm share than
    its peers, that rank is the one everyone else waits for — a slow
    rank never waits in the collective, its peers do.  High host share
    on the named rank corroborates (the stall is host-side)."""
    per_rank = (incident.evidence.get("anatomy") or {})
    if len(per_rank) < 2:
        return None
    shares = {}
    hosts = {}
    for r, a in per_rank.items():
        wall = float(a.get("wall_s") or 0.0)
        if wall <= 0:
            continue
        shares[int(r)] = float(a.get("exposed_s") or 0.0) / wall
        hosts[int(r)] = float(a.get("host_s") or 0.0) / wall
    if len(shares) < 2:
        return None
    straggler = min(shares, key=shares.get)
    skew = max(shares.values()) - shares[straggler]
    if skew < 0.05:
        return None
    return {"rule": "straggler-rank", "score": round(2.0 + skew, 4),
            "detail": {"rank": straggler,
                       "exposed_share": {str(r): round(v, 4)
                                         for r, v in shares.items()},
                       "host_share": {str(r): round(v, 4)
                                      for r, v in hosts.items()}}}


def rule_data_starvation(incident: Incident, timeline: TimelineStore,
                         events: list[dict]) -> Optional[dict]:
    """data_wait grew vs its pre-incident level on some rank: the input
    pipeline, not the device, is the bottleneck."""
    best = None
    for series, rank in [("data_wait_s", r) for r in range(-1, 64)]:
        samples = timeline.samples(series, rank)
        if not samples:
            continue
        split = _recent_vs_prior(samples, incident.opened_ts - 1.0)
        if split is None:
            continue
        prior, recent = split
        if recent > max(2.0 * prior, prior + 0.05):
            score = 1.0 + min(4.0, recent / max(prior, 1e-6)) / 4.0
            if best is None or score > best["score"]:
                best = {"rule": "data-starvation",
                        "score": round(score, 4),
                        "detail": {"rank": rank,
                                   "prior_median_s": round(prior, 6),
                                   "recent_median_s": round(recent, 6)}}
    return best


def rule_exposed_comm_growth(incident: Incident, timeline: TimelineStore,
                             events: list[dict]) -> Optional[dict]:
    """Measured exposed-comm grew vs its pre-incident level — the
    collectives stopped hiding behind compute."""
    best = None
    for rank in range(-1, 64):
        samples = timeline.samples("exposed_comm_s", rank)
        if not samples:
            continue
        split = _recent_vs_prior(samples, incident.opened_ts - 1.0)
        if split is None:
            continue
        prior, recent = split
        if recent > max(1.5 * prior, prior + 1e-4):
            score = 0.9 + min(4.0, recent / max(prior, 1e-9)) / 5.0
            if best is None or score > best["score"]:
                best = {"rule": "exposed-comm-growth",
                        "score": round(score, 4),
                        "detail": {"rank": rank,
                                   "prior_median_s": round(prior, 6),
                                   "recent_median_s": round(recent, 6)}}
    return best


def rule_compile_storm(incident: Incident, timeline: TimelineStore,
                       events: list[dict]) -> Optional[dict]:
    """Repeated recompiles inside the correlation window: shape churn /
    cache misses are eating the step budget."""
    compiles = [e for e in events if e["event"] == "compile"]
    if len(compiles) < 3:
        return None
    return {"rule": "compile-storm",
            "score": round(1.2 + 0.1 * len(compiles), 4),
            "detail": {"compiles": len(compiles),
                       "window_s": EVENT_WINDOW_S}}


def rule_autoscale_thrash(incident: Incident, timeline: TimelineStore,
                          events: list[dict]) -> Optional[dict]:
    """Opposing autoscale actuations inside the window: the fleet is
    oscillating, and every actuation pays a spawn/drain tax."""
    acts = [((e.get("detail") or {}).get("action") or "")
            for e in events if e["event"] == "autoscale"]
    if len(acts) < 2 or len({a for a in acts if a}) < 2:
        return None
    return {"rule": "autoscale-thrash",
            "score": round(1.1 + 0.1 * len(acts), 4),
            "detail": {"actuations": len(acts), "actions": acts[-6:]}}


def rule_snapshot_stall(incident: Incident, timeline: TimelineStore,
                        events: list[dict]) -> Optional[dict]:
    """A snapshot write stalled the step loop inside the window."""
    stalls = [e for e in events if e["event"] == "snapshot_stall"]
    if not stalls:
        return None
    seconds = sum(float((e.get("detail") or {}).get("seconds") or 0.0)
                  for e in stalls)
    return {"rule": "snapshot-stall",
            "score": round(1.3 + min(1.0, seconds), 4),
            "detail": {"stalls": len(stalls),
                       "stall_seconds": round(seconds, 6)}}


CAUSE_RULES = (
    rule_straggler_rank,
    rule_data_starvation,
    rule_exposed_comm_growth,
    rule_compile_storm,
    rule_autoscale_thrash,
    rule_snapshot_stall,
)


# -- the manager ---------------------------------------------------------

class IncidentManager:
    """Driver-resident incident lifecycle: detectors over the timeline
    feed, evidence arming on open, cause ranking, ``incident_<id>.json``
    dumps, and the /status + /metrics surfaces.  Owned by the
    :class:`~ray_lightning_tpu.telemetry.aggregator.TelemetryAggregator`
    and ticked from the driver poll loops (never from a hot step)."""

    def __init__(self, out_dir: str, cfg: Optional[IncidentConfig] = None,
                 run_kind: str = "fit", clock=time.monotonic,
                 timeline: Optional[TimelineStore] = None,
                 flight_hook: Optional[Callable[[int, str],
                                               Optional[str]]] = None):
        self.cfg = cfg if cfg is not None else IncidentConfig.from_env()
        self.out_dir = out_dir
        self.run_kind = run_kind
        self._clock = clock
        self.timeline = timeline if timeline is not None else \
            TimelineStore(capacity=self.cfg.capacity)
        #: called with (rank, cause) to dump that rank's flight ring
        self.flight_hook = flight_hook
        #: arm-file path (plugins set this; None = in-process arm only)
        self.arm_path: Optional[str] = None
        self._lock = threading.Lock()
        self._detectors: dict[tuple[str, int], Detector] = {}
        self._incidents: list[Incident] = []
        self._counts: dict[tuple[str, str], int] = {}   # (series, verdict)
        self._last_sample_ts: dict[tuple[str, int], float] = {}
        self._goodput_latest: Optional[dict] = None
        self._series = FIT_SERIES if run_kind == "fit" else SERVE_SERIES

    # -- feeds ----------------------------------------------------------

    def _detector(self, series: str, rank: int) -> Optional[Detector]:
        spec = self._series.get(series)
        if spec is None:
            return None
        key = (series, int(rank))
        det = self._detectors.get(key)
        if det is None:
            direction, over = spec
            det = Detector(series, rank, DetectorConfig(
                direction=direction,
                warmup=over.get("warmup", self.cfg.warmup),
                patience=over.get("patience", self.cfg.patience),
                cooldown_s=over.get("cooldown_s", self.cfg.cooldown_s),
                mad_k=over.get("mad_k", self.cfg.mad_k),
                rel_floor=over.get("rel_floor", 0.25),
                abs_floor=over.get("abs_floor", 0.0),
            ), clock=self._clock)
            self._detectors[key] = det
        return det

    def note_sample(self, series: str, rank: int, value: float,
                    ts: Optional[float] = None) -> None:
        """One timeline sample: record it and tick that series' detector
        (opening/closing incidents on transitions).  The single entry
        point every aggregator ingest path calls."""
        if not self.cfg.enabled:
            return
        ts = float(ts if ts is not None else time.time())
        self.timeline.note(series, rank, value, ts=ts)
        with self._lock:
            self._last_sample_ts[(series, int(rank))] = ts
            det = self._detector(series, rank)
            if det is None:
                return
            transition = det.observe(value, ts=ts)
        if transition is None:
            return
        if transition.pop("transition") == "opened":
            self._open(series, int(rank), transition)
        else:
            self._close(series, int(rank), transition)

    def note_tail(self, rank: int, samples: Any) -> None:
        """Heartbeat-carried rolling sample tail (telemetry/heartbeat.py)
        — the backstop feed that keeps detectors ticking when span
        batches are dropped under backpressure.  Entries already seen
        via the span path are skipped by timestamp watermark (the span
        feed and the tail describe the same underlying steps)."""
        if not isinstance(samples, (list, tuple)):
            return
        for s in samples:
            try:
                series = str(s["s"])
                ts = float(s["ts"])
                value = float(s["v"])
            except (KeyError, TypeError, ValueError):
                continue
            with self._lock:
                seen = self._last_sample_ts.get((series, int(rank)), 0.0)
            # 50ms slack: a span's end timestamp and the worker-side
            # hook's own clock read for the same step differ by the
            # code between them, not by a real new sample
            if ts <= seen + 0.05:
                continue
            self.note_sample(series, rank, value, ts=ts)

    def note_event(self, name: str, ts: Optional[float] = None,
                   **detail: Any) -> None:
        if not self.cfg.enabled:
            return
        self.timeline.note_event(name, ts=ts, **detail)

    def note_anatomy(self, rank: int, anatomy: dict,
                     capture_dir: Optional[str] = None) -> None:
        """Anatomy window evidence: feed the exposed-comm series and
        attach the per-rank breakdown to every open incident (windows
        arriving after open are exactly the evidence the incident
        armed)."""
        if not self.cfg.enabled or not anatomy:
            return
        exposed = anatomy.get("exposed_s")
        if exposed is not None:
            self.note_sample("exposed_comm_s", rank, float(exposed))
        with self._lock:
            open_incidents = [i for i in self._incidents
                              if i.state == "open"]
        for inc in open_incidents:
            ev = inc.evidence
            ev.setdefault("anatomy", {})[str(rank)] = dict(anatomy)
            if capture_dir:
                ev["anatomy_dir"] = capture_dir
            self._rank_causes(inc)
            self._dump(inc)

    def note_goodput(self, doc: dict) -> None:
        if not self.cfg.enabled or not isinstance(doc, dict):
            return
        with self._lock:
            self._goodput_latest = dict(doc)
        frac = doc.get("goodput_fraction")
        if frac is not None:
            self.note_sample("goodput_fraction", -1, float(frac))

    def note_divergence(self, observed: dict,
                        band: float = 0.5) -> Optional[Incident]:
        """ROADMAP 5(a) leg: the plan's modeled comm diverged from the
        anatomy-measured exposed comm past ``band`` (relative) — open a
        ``replan-recommended`` incident so the operator (or a future
        re-planning loop) knows the placement decision is stale."""
        if not self.cfg.enabled:
            return None
        ratio = observed.get("ratio")
        if ratio is None:
            return None
        if abs(float(ratio) - 1.0) <= band:
            return None
        inc = self._open("plan_divergence", -1, {
            "value": float(ratio), "median": 1.0,
            "band": [1.0 - band, 1.0 + band], "direction": "high",
            "patience": 1},
            verdict="replan-recommended",
            causes=[{"rule": "replan-recommended",
                     "score": round(abs(float(ratio) - 1.0), 4),
                     "detail": dict(observed)}])
        return inc

    # -- lifecycle ------------------------------------------------------

    def _open(self, series: str, rank: int, trigger: dict,
              verdict: Optional[str] = None,
              causes: Optional[list] = None) -> Incident:
        now_wall = time.time()
        inc = Incident(
            id=uuid.uuid4().hex[:8], run_kind=self.run_kind,
            series=series, rank=rank, opened_ts=now_wall,
            trigger={k: v for k, v in trigger.items() if v is not None})
        with self._lock:
            self._incidents.append(inc)
            # bounded retention: evict oldest CLOSED incidents first
            while len(self._incidents) > self.cfg.max_incidents:
                closed = next((i for i in self._incidents
                               if i.state == "closed"), None)
                self._incidents.remove(closed or self._incidents[0])
        # evidence arming, in order of perishability:
        # 1. flight ring of the tripping rank (it is overwriting itself)
        if self.flight_hook is not None and rank >= 0:
            try:
                path = self.flight_hook(
                    rank, f"incident {inc.id}: {series} anomaly")
                if path:
                    inc.evidence["flight_dumps"] = {str(rank): path}
            except Exception:
                _log.debug("incident flight dump failed", exc_info=True)
        # 2. an anatomy window (captured AFTER detection — the arm file
        #    forces the workers' next anatomy_tick off-cadence; an
        #    in-process controller is armed directly)
        if verdict is None:
            inc.evidence["anatomy_armed"] = self._arm_anatomy(inc.id)
        # 3. goodput ledger snapshot (closed incidents report the delta)
        with self._lock:
            if self._goodput_latest is not None:
                inc.evidence["goodput_open"] = dict(self._goodput_latest)
        # 4. the correlated event log
        inc.evidence["events"] = self.timeline.events(
            since=now_wall - EVENT_WINDOW_S)
        if causes is not None:
            inc.causes = causes
            inc.verdict = verdict or "unattributed"
            # explicit verdict (note_divergence): the cause IS the
            # trigger — rule re-ranking must never clobber it
            inc.pinned = True
            self._count(inc)
        else:
            # count first under the provisional verdict; _rank_causes
            # moves the count when a rule names a better one
            self._count(inc)
            self._rank_causes(inc)
        self.note_event("incident_open", id=inc.id, series=series,
                        rank=rank)
        self._dump(inc)
        _log.warning(
            "incident %s OPEN: %s anomaly on rank %d (value %.6g vs "
            "healthy median %.6g) -> %s", inc.id, series, rank,
            trigger.get("value", float("nan")),
            trigger.get("median", float("nan")), inc.path)
        return inc

    def _close(self, series: str, rank: int, transition: dict) -> None:
        with self._lock:
            inc = next((i for i in reversed(self._incidents)
                        if i.state == "open" and i.series == series
                        and i.rank == rank), None)
        if inc is None:
            return
        self._finalize(inc, transition)

    def _finalize(self, inc: Incident, transition: dict) -> None:
        inc.state = "closed"
        inc.closed_ts = time.time()
        inc.trigger["cleared"] = {k: v for k, v in transition.items()
                                 if v is not None}
        with self._lock:
            gp = self._goodput_latest
        opened_gp = inc.evidence.get("goodput_open")
        if gp and opened_gp:
            delta = {}
            for bucket, v in (gp.get("buckets") or {}).items():
                before = (opened_gp.get("buckets") or {}).get(bucket, 0.0)
                d = float(v) - float(before)
                if abs(d) > 1e-9:
                    delta[bucket] = round(d, 6)
            inc.evidence["goodput_delta"] = delta
        self._rank_causes(inc)
        self.note_event("incident_close", id=inc.id, series=inc.series,
                        rank=inc.rank)
        self._dump(inc)
        _log.warning("incident %s CLOSED after %.1fs (verdict %s)",
                     inc.id, inc.closed_ts - inc.opened_ts, inc.verdict)

    def _arm_anatomy(self, incident_id: str) -> bool:
        armed = False
        if self.arm_path:
            armed = write_arm_file(self.arm_path, incident_id,
                                   self.cfg.arm_steps)
        try:
            from ray_lightning_tpu.telemetry.anatomy import (
                get_anatomy_controller)
            ctl = get_anatomy_controller()
            if ctl is not None:
                ctl.arm_now(tag=f"incident-{incident_id}")
                armed = True
        except Exception:
            _log.debug("in-process anatomy arm failed", exc_info=True)
        return armed

    def _rank_causes(self, inc: Incident) -> None:
        if getattr(inc, "pinned", False):
            return
        events = inc.evidence.get("events", []) + self.timeline.events(
            since=inc.opened_ts)
        ranked = []
        for rule in CAUSE_RULES:
            try:
                hit = rule(inc, self.timeline, events)
            except Exception:
                _log.debug("cause rule %s failed", rule.__name__,
                           exc_info=True)
                hit = None
            if hit is not None:
                ranked.append(hit)
        ranked.sort(key=lambda c: -c["score"])
        inc.causes = ranked
        new_verdict = ranked[0]["rule"] if ranked else "unattributed"
        if new_verdict != inc.verdict:
            with self._lock:
                key = (inc.series, inc.verdict)
                if self._counts.get(key):
                    self._counts[key] -= 1
                self._counts[(inc.series, new_verdict)] = \
                    self._counts.get((inc.series, new_verdict), 0) + 1
            inc.verdict = new_verdict

    def _count(self, inc: Incident) -> None:
        with self._lock:
            key = (inc.series, inc.verdict)
            self._counts[key] = self._counts.get(key, 0) + 1

    def _dump(self, inc: Incident) -> None:
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, f"incident_{inc.id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(inc.to_dict(), f, indent=1)
            os.replace(tmp, path)
            inc.path = path
        except OSError:
            _log.debug("incident dump failed", exc_info=True)

    def close_all(self, reason: str = "run_end") -> None:
        """Export-time sweep: an incident whose series simply stopped
        arriving (the run ended) closes with the reason on record."""
        with self._lock:
            open_incidents = [i for i in self._incidents
                              if i.state == "open"]
        for inc in open_incidents:
            self._finalize(inc, {"reason": reason})

    # -- surfaces -------------------------------------------------------

    @property
    def open_incidents(self) -> list[Incident]:
        with self._lock:
            return [i for i in self._incidents if i.state == "open"]

    @property
    def incidents(self) -> list[Incident]:
        with self._lock:
            return list(self._incidents)

    def stats(self) -> dict:
        """The ``incidents`` section of /status and the export summary."""
        with self._lock:
            incidents = list(self._incidents)
            counts = dict(self._counts)
        if not self.cfg.enabled:
            return {"enabled": False}
        return {
            "enabled": True,
            "open": [i.brief() for i in incidents if i.state == "open"],
            "recent": [i.brief() for i in incidents[-8:]],
            "total": len(incidents),
            "by_verdict": {f"{s}/{v}": n
                           for (s, v), n in sorted(counts.items()) if n},
            "detectors": [d.stats() for d in self._detectors.values()
                          if d.stats()["samples"] or d.tripped],
            "timeline": self.timeline.stats(),
        }

    def metric_samples(self) -> list[dict]:
        """Driver-side metric series merged into the aggregator's rank
        ``-1`` window: ``rlt_incident_total{series,verdict}`` and
        ``rlt_incident_active``."""
        if not self.cfg.enabled:
            return []
        with self._lock:
            counts = dict(self._counts)
            active = sum(1 for i in self._incidents if i.state == "open")
        out = [{"name": "rlt_incident_total", "type": "counter",
                "labels": {"series": s, "verdict": v}, "value": n}
               for (s, v), n in sorted(counts.items()) if n]
        out.append({"name": "rlt_incident_active", "type": "gauge",
                    "labels": {}, "value": active})
        return out


__all__ = [
    "INCIDENT_ENV",
    "INCIDENT_CAPACITY_ENV",
    "INCIDENT_WARMUP_ENV",
    "INCIDENT_PATIENCE_ENV",
    "INCIDENT_COOLDOWN_ENV",
    "INCIDENT_MAD_K_ENV",
    "INCIDENT_CONTROL_ENV",
    "INCIDENT_SCHEMA_KEYS",
    "FIT_SERIES",
    "SERVE_SERIES",
    "TimelineStore",
    "DetectorConfig",
    "Detector",
    "IncidentConfig",
    "Incident",
    "IncidentManager",
    "ArmWatcher",
    "write_arm_file",
]
