"""Per-request distributed tracing + on-demand profiling control.

The span plane (spans.py) times *phases*; this module ties phases to
*requests*.  Every ``Server.submit`` mints a trace id that propagates
driver→worker inside the scheduler's plan broadcast (each prefill entry
carries ``trace=...``, the decode entry carries a slot→trace map) and
worker→driver through the ordinary span batches on the queue channel —
worker spans simply carry the id as a ``trace`` attr.  The driver-side
request phases (queue wait, admission, completion/failure) are recorded
as synthetic rank ``-1`` span records fed straight to the active
aggregator, which reassembles one span tree per request
(``TelemetryAggregator.request_trees``) and summarizes per-tenant
TTFT/TPOT breakdowns for ``/status``
(``TelemetryAggregator.tenant_breakdown``).

The second half is the on-demand ``jax.profiler`` window — replacing
"restart with JaxProfilerCallback configured":

- :class:`ServeProfileController` — driver side of the serve plane's
  ``POST /debug/profile?steps=N``: the pump attaches the armed window to
  the next plan broadcast (the same driver→worker control path the
  trace ids ride) and counts the steps; every worker runs the capture
  through a :class:`WorkerProfiler`.
- :class:`FileProfileController` / :func:`profile_tick` — the fit
  path's equivalent: the exporter POST writes a control file under the
  telemetry dir (location shipped to workers via the
  ``RLT_PROFILE_CONTROL`` env var — shared-filesystem backends only),
  and the loop engine polls it once per dispatch at a bounded rate.

No jax at module import (worker_main touches this package before jax
exists); ``jax.profiler`` is imported inside the capture calls, which
never raise into serving/training.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Optional

_log = logging.getLogger(__name__)

#: span-record attribute carrying the request trace id (a single id on
#: request-scoped spans; ``traces`` carries a slot→id map on the shared
#: decode span, which the aggregator fans out to every live request)
TRACE_ATTR = "trace"
TRACES_ATTR = "traces"

#: env var pointing fit workers at the profile control file
PROFILE_CONTROL_ENV = "RLT_PROFILE_CONTROL"

#: ceiling on one capture window — an unbounded window would trace
#: until the run ends and write an unbounded xplane file
MAX_PROFILE_STEPS = 10_000


def mint_trace_id() -> str:
    """One request's trace id: 16 hex chars, unique per process fleet."""
    return uuid.uuid4().hex[:16]


def span_record(name: str, t0: float, t1: Optional[float] = None,
                rank: int = -1, **attrs: Any) -> dict:
    """A synthetic span record in the spans.py wire schema.  ``t0``/
    ``t1`` are wall-clock seconds (``time.time()``), matching the
    offset-corrected timestamps worker recorders emit, so driver and
    worker spans merge onto one timeline."""
    if t1 is None:
        t1 = time.time()
    rec = {"t": "span", "name": name, "ts": float(t0),
           "dur": max(0.0, float(t1) - float(t0)), "rank": rank,
           "depth": 0}
    clean = {k: v for k, v in attrs.items() if v is not None}
    if clean:
        rec["attrs"] = clean
    return rec


def record_request_span(name: str, t0: float, t1: Optional[float] = None,
                        **attrs: Any) -> None:
    """Feed one driver-side request span to the active aggregator
    (thread-local — the serve pump binds the fleet's aggregator).
    No-op without an aggregator so the scheduler stays unit-testable
    and tracing stays free when telemetry is off."""
    from ray_lightning_tpu.telemetry.aggregator import get_active
    agg = get_active()
    if agg is None:
        return
    try:
        agg.ingest_records(-1, [span_record(name, t0, t1, **attrs)])
    except Exception:   # tracing must never break the pump
        _log.debug("request span dropped", exc_info=True)


def _attach_window_anatomy(controller, out: dict) -> None:
    """Link the parsed per-rank step anatomy (telemetry/anatomy.py)
    next to a completed window's ``last_dir`` in a controller's status
    dict.  Parsed once per window dir and cached on the controller —
    /status polls must not re-read a multi-MB trace each scrape."""
    last_dir = out.get("last_dir")
    if not last_dir:
        return
    cached = getattr(controller, "_anatomy_cache", None)
    if cached is None or cached[0] != last_dir:
        from ray_lightning_tpu.telemetry.anatomy import profile_dir_anatomy
        cached = (last_dir, profile_dir_anatomy(last_dir))
        controller._anatomy_cache = cached
    if cached[1] is not None:
        out["anatomy"] = cached[1]


# -- on-demand profiling: serve plane (plan-broadcast control) -----------

class ServeProfileController:
    """Driver-side state machine for ``POST /debug/profile?steps=N``.

    States: idle → pending (POST accepted) → active (window attached to
    a plan broadcast; the driver counts dispatched steps) → done (trace
    dir linkable from ``/status``).  One window at a time; a POST while
    one is pending/active is rejected with its current state.
    """

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        self._lock = threading.Lock()
        self._state = "idle"
        self._req: Optional[dict] = None
        self.last_dir: Optional[str] = None
        self.windows = 0

    def request(self, steps: int) -> dict:
        steps = max(1, min(int(steps), MAX_PROFILE_STEPS))
        with self._lock:
            if self._state in ("pending", "active"):
                return {"accepted": False, "state": self._state,
                        "error": "a profile window is already "
                                 f"{self._state}"}
            pid = uuid.uuid4().hex[:8]
            out_dir = os.path.join(self.base_dir, "profile", pid)
            self._req = {"id": pid, "steps": steps, "dir": out_dir,
                         "remaining": steps}
            self._state = "pending"
        _log.info("profile: window armed (%d steps) -> %s", steps, out_dir)
        return {"accepted": True, "state": "pending", "id": pid,
                "steps": steps, "dir": out_dir}

    def take_pending(self) -> Optional[dict]:
        """Pump hook: claim the armed window for the next plan broadcast
        (pending → active).  Returns the picklable control dict workers
        act on, or None."""
        with self._lock:
            if self._state != "pending":
                return None
            self._state = "active"
            req = self._req
        return {"id": req["id"], "steps": req["steps"], "dir": req["dir"]}

    def note_step(self) -> None:
        """Pump hook: one plan dispatched while a window is active."""
        with self._lock:
            if self._state != "active":
                return
            self._req["remaining"] -= 1
            if self._req["remaining"] > 0:
                return
            self._state = "done"
            self.last_dir = self._req["dir"]
            self.windows += 1
        from ray_lightning_tpu.telemetry import metrics as _metrics
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter("rlt_profile_windows_total").inc(1)
        _log.info("profile: window complete -> %s", self.last_dir)

    def status(self) -> dict:
        with self._lock:
            out = {"state": self._state}
            if self._req is not None:
                out["id"] = self._req["id"]
                out["dir"] = self._req["dir"]
                out["steps"] = self._req["steps"]
                if self._state == "active":
                    out["remaining"] = self._req["remaining"]
            if self.last_dir is not None:
                out["last_dir"] = self.last_dir
        _attach_window_anatomy(self, out)
        return out


class WorkerProfiler:
    """Worker-side capture window: start on the plan's control dict,
    count serve steps, stop after N.  Each rank writes its own subdir
    so multi-host captures never collide.  Failures log and disarm —
    profiling must never fail a serve step."""

    def __init__(self, rank: int = 0):
        self.rank = rank
        self._remaining = 0
        self._active = False
        self._seen: set[str] = set()

    def maybe_start(self, ctl: Optional[dict]) -> None:
        if not ctl or ctl.get("id") in self._seen or self._active:
            return
        self._seen.add(ctl.get("id", ""))
        out_dir = os.path.join(ctl["dir"], f"rank{self.rank}")
        try:
            os.makedirs(out_dir, exist_ok=True)
            import jax
            jax.profiler.start_trace(out_dir)
        except Exception as e:
            _log.warning("profile: start_trace failed: %s", e)
            return
        self._active = True
        self._remaining = int(ctl["steps"])
        _log.info("profile: rank %d capturing %d steps -> %s",
                  self.rank, self._remaining, out_dir)

    def note_step(self) -> None:
        if not self._active:
            return
        self._remaining -= 1
        if self._remaining <= 0:
            self.stop()

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            _log.warning("profile: stop_trace failed: %s", e)


# -- on-demand profiling: fit plane (control-file arm) -------------------

class FileProfileController:
    """Fit-path driver side: ``POST /debug/profile`` writes a control
    file the workers poll (:func:`profile_tick`).  Only meaningful when
    the backend shares a filesystem with the workers — the plugin only
    wires this controller up when it does."""

    def __init__(self, control_path: str):
        self.control_path = control_path
        self._last: Optional[dict] = None

    def request(self, steps: int) -> dict:
        steps = max(1, min(int(steps), MAX_PROFILE_STEPS))
        pid = uuid.uuid4().hex[:8]
        out_dir = os.path.join(os.path.dirname(self.control_path), pid)
        ctl = {"id": pid, "steps": steps, "dir": out_dir}
        os.makedirs(os.path.dirname(self.control_path), exist_ok=True)
        tmp = self.control_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ctl, f)
        os.replace(tmp, self.control_path)   # workers see complete JSON
        self._last = ctl
        _log.info("profile: fit window armed (%d steps) -> %s",
                  steps, out_dir)
        return {"accepted": True, "state": "armed", **ctl}

    def status(self) -> dict:
        if self._last is None:
            return {"state": "idle"}
        out = {"state": "armed", **self._last}
        try:
            done = sorted(fn for fn in os.listdir(self._last["dir"])
                          if fn.endswith(".done"))
        except OSError:
            done = []
        if done:
            out["state"] = "done"
            out["ranks_done"] = [fn[:-len(".done")] for fn in done]
            out["last_dir"] = self._last["dir"]
            _attach_window_anatomy(self, out)
        return out


class _FilePoller:
    """Per-process fit-side poller: reads the control file at most every
    ``min_poll`` seconds (one monotonic compare per step otherwise),
    runs the capture window, and drops a ``rank<k>.done`` marker so the
    driver's ``/status`` can report completion."""

    def __init__(self, control_path: str, min_poll: float = 0.5):
        self.control_path = control_path
        self.min_poll = min_poll
        self._next_poll = 0.0
        self._profiler: Optional[WorkerProfiler] = None
        self._ctl: Optional[dict] = None

    def _rank(self) -> int:
        try:
            return int(os.environ.get("RLT_PROCESS_ID", "0"))
        except ValueError:
            return 0

    def tick(self) -> None:
        prof = self._profiler
        if prof is not None and prof._active:
            prof.note_step()
            if not prof._active:     # window just closed: drop marker
                try:
                    with open(os.path.join(
                            self._ctl["dir"],
                            f"rank{self._rank()}.done"), "w") as f:
                        f.write("1")
                except OSError:
                    pass
            return
        now = time.monotonic()
        if now < self._next_poll:
            return
        self._next_poll = now + self.min_poll
        try:
            with open(self.control_path) as f:
                ctl = json.load(f)
        except (OSError, ValueError):
            return
        if prof is None:
            prof = self._profiler = WorkerProfiler(rank=self._rank())
        self._ctl = ctl
        prof.maybe_start(ctl)


_poller: "Optional[_FilePoller]" = None
_poller_checked = False


def profile_tick() -> None:
    """Loop-engine hook, called once per dispatch.  Free (one global
    check) unless ``RLT_PROFILE_CONTROL`` is set in this process."""
    global _poller, _poller_checked
    if _poller is None:
        if _poller_checked:
            return
        _poller_checked = True
        path = os.environ.get(PROFILE_CONTROL_ENV, "").strip()
        if not path:
            return
        _poller = _FilePoller(path)
    try:
        _poller.tick()
    except Exception:    # profiling must never break the train loop
        _log.debug("profile tick failed", exc_info=True)


def reset_profile_tick() -> None:
    """Re-read the env on the next tick (tests / respawned workers)."""
    global _poller, _poller_checked
    if _poller is not None and _poller._profiler is not None:
        _poller._profiler.stop()
    _poller = None
    _poller_checked = False


__all__ = [
    "TRACE_ATTR",
    "TRACES_ATTR",
    "PROFILE_CONTROL_ENV",
    "mint_trace_id",
    "span_record",
    "record_request_span",
    "ServeProfileController",
    "FileProfileController",
    "WorkerProfiler",
    "profile_tick",
    "reset_profile_tick",
]
