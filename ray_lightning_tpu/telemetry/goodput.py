"""Goodput plane: full-run wall-clock attribution + measured MFU.

PR 14's anatomy plane made *step-time* truth measured (``wall ==
compute + exposed + host`` from real profiler captures); this module
answers the *run-time* question the operator actually asks: of the
whole fit or serve run's wall-clock, how much was useful work — and
what MFU did the useful part achieve.  TorchTitan reports MFU as the
headline training metric and veScale-style systems treat end-to-end
goodput as the primary dial (PAPERS.md); here both become measured,
scrapeable, and regression-gated.

The core contract is a strict partition: a :class:`GoodputLedger`
attributes **every second of run wall-clock to exactly one bucket**,

===========  ==========================================================
kind         buckets (disjoint, exhaustive)
===========  ==========================================================
``fit``      ``step`` (useful: measured train dispatch wall),
             ``compile`` (trace+jit build, PR 3 counters),
             ``init`` (state init / restore), ``data_wait`` (host
             input-pipeline stall), ``snapshot`` (blocking host time
             of async saves) + ``snapshot_stall`` (multi-process
             wait-for-previous-save, PR 7), ``recovery``
             (driver-side route decision, PR 13) + ``replay``
             (re-executed steps after a snapshot resume — the measured
             badput that parity recovery avoids), ``other`` (residual)
``serve``    ``decode`` (useful: token-producing dispatch wall),
             ``prefill``, ``queue_idle`` (pump waiting for work),
             ``autoscale`` (fleet actuation seconds, PR 15),
             ``other`` (residual)
===========  ==========================================================

with the identity ``sum(buckets) == run_wall`` EXACT by construction:
the residual lands in ``other``, and if instrumented time ever
overshoots the measured wall (clock skew between overlapping
accumulators) every bucket is scaled down proportionally so the
partition still closes.  Tests and ``telemetry/selfcheck.py`` pin the
identity; ``benchmarks/ledger.py`` gates goodput-fraction and MFU
regressions between rounds.

The useful bucket additionally carries a *sub-split* (``useful_split``,
deliberately outside the top-level identity): the anatomy plane's
measured compute / exposed-comm / host / bubble shares when
``RLT_ANATOMY`` armed a window during the run, a wall proxy otherwise.

MFU pairs with the partition: ``flops_per_step`` (the
``LightningModule.flops_per_step()`` hook, or the default pricing of
the train-step jaxpr via the PR 12 dot-counting machinery) divided by
the measured mean step wall × ``devices`` × ``device_tflops``
(``PlanConfig.device_tflops`` / ``RLT_GOODPUT_TFLOPS``).

Like every plane here: disabled is the default, entry points are
one-global-check no-ops, and nothing heavy imports at module load.
Arm/disarm rides ``TelemetryConfig`` (on whenever telemetry is on,
``RLT_GOODPUT=0`` disarms; knobs ship through ``worker_env()``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Optional

from ray_lightning_tpu.telemetry.aggregator import TELEMETRY_KEY

_log = logging.getLogger(__name__)

#: arm/disarm: goodput is on whenever telemetry is on unless this is 0
GOODPUT_ENV = "RLT_GOODPUT"
#: per-device peak TFLOPs override for the MFU denominator (defaults
#: to PlanConfig.device_tflops / RLT_PLAN_TFLOPS)
GOODPUT_TFLOPS_ENV = "RLT_GOODPUT_TFLOPS"

#: the partition, per run kind: disjoint, exhaustive (``other`` is the
#: residual), pinned by telemetry/selfcheck.py
FIT_BUCKETS = ("step", "compile", "init", "data_wait", "snapshot",
               "snapshot_stall", "recovery", "replay", "other")
SERVE_BUCKETS = ("decode", "prefill", "draft", "kv_ship", "kv_fed",
                 "queue_idle", "autoscale", "other")
BUCKETS = {"fit": FIT_BUCKETS, "serve": SERVE_BUCKETS}
#: which bucket is "useful" (the goodput-fraction numerator) per kind
USEFUL_BUCKET = {"fit": "step", "serve": "decode"}

#: identity tolerance: the partition closes to float roundoff, not to
#: a sloppy epsilon (the selfcheck asserts this exact bound)
IDENTITY_TOL = 1e-6


class GoodputLedger:
    """One run's wall-clock partition + MFU accumulator.

    Feed it seconds (:meth:`add` / :meth:`note_step`), then
    :meth:`finalize` against the measured run wall; :meth:`peek` gives
    the same doc mid-run without closing the ledger (the live /status
    surface)."""

    def __init__(self, kind: str = "fit",
                 device_tflops: Optional[float] = None,
                 devices: int = 1, clock: Callable[[], float] = None):
        if kind not in BUCKETS:
            raise ValueError(f"unknown goodput kind {kind!r}; "
                             f"expected one of {sorted(BUCKETS)}")
        self.kind = kind
        self.buckets: dict[str, float] = {b: 0.0 for b in BUCKETS[kind]}
        self.devices = max(1, int(devices))
        self.device_tflops = device_tflops
        self.steps = 0
        self.flops_per_step: Optional[float] = None
        self._anatomy: Optional[dict] = None
        self._clock = clock or time.monotonic
        self._t0: Optional[float] = None
        self.doc: Optional[dict] = None

    # -- feeding ---------------------------------------------------------

    def start(self) -> "GoodputLedger":
        self._t0 = self._clock()
        return self

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in self.buckets:
            raise KeyError(
                f"bucket {bucket!r} is not in the {self.kind!r} "
                f"partition {tuple(self.buckets)}")
        if seconds > 0:
            self.buckets[bucket] += float(seconds)

    def note_step(self, seconds: float, k: int = 1) -> None:
        """One train/decode dispatch: ``k`` steps in ``seconds`` wall."""
        self.add(USEFUL_BUCKET[self.kind], seconds)
        self.steps += max(1, int(k))

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        self.flops_per_step = None if flops is None else float(flops)

    def set_anatomy(self, anatomy: Optional[dict]) -> None:
        """Latest measured step anatomy (telemetry/anatomy.py compact
        dict) — the useful bucket's measured sub-split source."""
        if anatomy:
            self._anatomy = dict(anatomy)

    # -- composition -----------------------------------------------------

    def _useful_split(self, useful_s: float) -> dict:
        """Sub-split of the useful bucket: anatomy-measured shares when
        a window landed, wall proxy otherwise.  Deliberately OUTSIDE
        the top-level identity (it re-describes one bucket)."""
        a = self._anatomy
        wall = float(a.get("wall_s", 0.0)) if a else 0.0
        if not a or wall <= 0:
            return {"source": "wall", "wall_s": round(useful_s, 6)}
        bubble = float(a.get("bubble_fraction") or 0.0)
        split = {"source": "anatomy"}
        for key, out in (("compute_s", "compute_s"),
                         ("exposed_s", "exposed_comm_s"),
                         ("host_s", "host_s")):
            split[out] = round(
                useful_s * float(a.get(key, 0.0)) / wall, 6)
        if bubble:
            # the bubble share is carved out of compute (the anatomy
            # identity has no separate bubble term; bubble_fraction is
            # the schedule-idle share of device time)
            split["bubble_s"] = round(useful_s * bubble, 6)
            split["compute_s"] = round(
                max(0.0, split["compute_s"] - split["bubble_s"]), 6)
        return split

    def _compose(self, wall: float) -> dict:
        wall = max(0.0, float(wall))
        buckets = dict(self.buckets)
        known = sum(buckets.values())
        if known <= wall:
            buckets["other"] += wall - known
        elif known > 0:
            # instrumented time overshot the measured wall (overlapping
            # accumulators / clock skew): scale the whole partition down
            # so the identity still closes exactly
            scale = wall / known
            buckets = {b: s * scale for b, s in buckets.items()}
        useful = buckets[USEFUL_BUCKET[self.kind]]
        doc: dict[str, Any] = {
            "kind": self.kind,
            "run_wall_s": round(wall, 6),
            "buckets": {b: round(s, 6) for b, s in buckets.items()},
            "goodput_fraction": round(useful / wall, 6) if wall else 0.0,
            "steps": self.steps,
            "devices": self.devices,
        }
        # rounding must not break the identity: re-close on the residual
        drift = doc["run_wall_s"] - sum(doc["buckets"].values())
        doc["buckets"]["other"] = round(
            max(0.0, doc["buckets"]["other"] + drift), 9)
        step_mean = useful / self.steps if self.steps else None
        if step_mean is not None:
            doc["step_wall_mean_s"] = round(step_mean, 6)
        doc["useful_split"] = self._useful_split(useful)
        if self.flops_per_step is not None:
            doc["flops_per_step"] = self.flops_per_step
        if self.device_tflops is not None:
            doc["device_tflops"] = self.device_tflops
        mfu = measured_mfu(self.flops_per_step, step_mean,
                           self.device_tflops, self.devices)
        if mfu is not None:
            doc["mfu"] = mfu
        return doc

    def peek(self) -> dict:
        """The doc as of now (ledger stays open) — live /status."""
        elapsed = (self._clock() - self._t0) if self._t0 is not None \
            else sum(self.buckets.values())
        return self._compose(elapsed)

    def finalize(self, wall: Optional[float] = None) -> dict:
        """Close the ledger against the measured run wall (default: the
        elapsed clock since :meth:`start`) and keep the doc."""
        if wall is None:
            wall = (self._clock() - self._t0) if self._t0 is not None \
                else sum(self.buckets.values())
        self.doc = self._compose(wall)
        return self.doc


def measured_mfu(flops_per_step: Optional[float],
                 step_wall_s: Optional[float],
                 device_tflops: Optional[float],
                 devices: int = 1) -> Optional[float]:
    """Model FLOPs Utilization: achieved FLOP/s of the measured step
    divided by the fleet's peak (``devices × device_tflops``).  None
    when any input is missing (MFU must never be fabricated)."""
    if not flops_per_step or not step_wall_s or not device_tflops:
        return None
    peak = float(device_tflops) * 1e12 * max(1, int(devices))
    if peak <= 0 or step_wall_s <= 0:
        return None
    return round(float(flops_per_step) / float(step_wall_s) / peak, 8)


def check_identity(doc: dict, tol: float = IDENTITY_TOL) -> bool:
    """Does ``sum(buckets) == run_wall`` hold on a composed doc?"""
    buckets = doc.get("buckets") or {}
    return abs(sum(buckets.values())
               - float(doc.get("run_wall_s", 0.0))) <= tol


def reattribute_replay(doc: dict, replayed_steps: int) -> dict:
    """Move the measured cost of ``replayed_steps`` re-executed steps
    from the ``step`` bucket to ``replay`` — the driver-side badput
    attribution of a snapshot-resume recovery (PR 13's parity route
    keeps this at ~0).  Identity-preserving: seconds move between
    buckets, the wall is untouched."""
    out = dict(doc)
    buckets = dict(out.get("buckets") or {})
    steps = int(out.get("steps") or 0)
    mean = out.get("step_wall_mean_s")
    if replayed_steps <= 0 or not mean or "replay" not in buckets:
        return out
    moved = min(buckets.get("step", 0.0),
                min(replayed_steps, steps) * float(mean))
    buckets["step"] = round(buckets["step"] - moved, 9)
    buckets["replay"] = round(buckets.get("replay", 0.0) + moved, 9)
    out["buckets"] = buckets
    out["replayed_steps"] = int(replayed_steps)
    wall = float(out.get("run_wall_s") or 0.0)
    if wall:
        out["goodput_fraction"] = round(buckets["step"] / wall, 6)
    return out


def aggregate(docs: list, extra_buckets: Optional[dict] = None) -> dict:
    """Fleet-level doc from per-rank/per-replica docs of one kind:
    walls and buckets sum; ``extra_buckets`` (e.g. the router's
    autoscale actuation seconds or the driver's recovery decision)
    extend BOTH the wall and their bucket, so the identity holds on
    the aggregate by construction."""
    docs = [d for d in docs if d]
    if not docs:
        return {}
    kind = docs[0].get("kind", "fit")
    buckets = {b: 0.0 for b in BUCKETS.get(kind, FIT_BUCKETS)}
    wall = 0.0
    steps = 0
    flops_steps = 0.0
    useful_s = 0.0
    devices = 0
    tflops = None
    for d in docs:
        wall += float(d.get("run_wall_s") or 0.0)
        steps += int(d.get("steps") or 0)
        devices += int(d.get("devices") or 0)
        if d.get("device_tflops") is not None:
            tflops = float(d["device_tflops"])
        for b, s in (d.get("buckets") or {}).items():
            buckets[b] = buckets.get(b, 0.0) + float(s)
        if d.get("flops_per_step") and d.get("steps"):
            flops_steps += float(d["flops_per_step"]) * int(d["steps"])
            useful_s += float(
                (d.get("buckets") or {}).get(USEFUL_BUCKET[kind], 0.0))
    for b, s in (extra_buckets or {}).items():
        if s and b in buckets:
            buckets[b] += float(s)
            wall += float(s)
    useful = buckets.get(USEFUL_BUCKET[kind], 0.0)
    out: dict[str, Any] = {
        "kind": kind,
        "run_wall_s": round(wall, 6),
        "buckets": {b: round(s, 6) for b, s in buckets.items()},
        "goodput_fraction": round(useful / wall, 6) if wall else 0.0,
        "steps": steps,
        "ranks": len(docs),
    }
    drift = out["run_wall_s"] - sum(out["buckets"].values())
    out["buckets"]["other"] = round(
        max(0.0, out["buckets"].get("other", 0.0) + drift), 9)
    if steps:
        # fleet seconds one GLOBAL step costs: per-rank steps are summed
        # into ``steps`` (each rank counts the step it co-executed), so
        # the per-global-step quantum is useful x ranks / steps — what
        # :func:`reattribute_replay` moves per re-executed step
        out["step_wall_mean_s"] = round(useful * len(docs) / steps, 6)
    # fleet MFU: total achieved FLOP/s over total peak — equivalently
    # the steps-weighted flops over the summed useful seconds
    if flops_steps and useful_s and tflops and devices:
        out["mfu"] = measured_mfu(flops_steps / steps,
                                  useful_s / steps, tflops,
                                  max(1, devices // len(docs)))
        if out["mfu"] is None:
            out.pop("mfu")
    return out


def goodput_item(rank: int, doc: dict) -> dict:
    """Wire item carrying one finalized (or peeked) ledger doc over the
    worker→driver queue (aggregator kind ``goodput``)."""
    return {TELEMETRY_KEY: 1, "kind": "goodput", "rank": rank,
            "ts": time.time(), "goodput": doc}


def publish_metrics(doc: dict, registry=None) -> None:
    """Mirror a doc into the metrics plane: per-bucket
    ``rlt_goodput_seconds{bucket=...}``, ``rlt_goodput_fraction`` and
    ``rlt_mfu`` — the /metrics twin of the /status section."""
    if registry is None:
        from ray_lightning_tpu.telemetry import metrics as _metrics
        registry = _metrics.get_registry()
    if registry is None or not doc:
        return
    kind = doc.get("kind", "fit")
    for bucket, seconds in (doc.get("buckets") or {}).items():
        registry.gauge("rlt_goodput_seconds").set(
            float(seconds), bucket=bucket, kind=kind)
    registry.gauge("rlt_goodput_fraction").set(
        float(doc.get("goodput_fraction", 0.0)), kind=kind)
    if doc.get("mfu") is not None:
        registry.gauge("rlt_mfu").set(float(doc["mfu"]))


# -- plane state (plugins arm it; the trainer/loop engine feed it) -------

#: (rank, sink) when the plane is armed; sink consumes wire items
_plane: Optional[tuple] = None
#: the active fit-run ledger (module-global so the loop engine's
#: data-wait site feeds it without plumbing, like metrics.on_data_wait)
_run_ledger: Optional[GoodputLedger] = None


def goodput_armed() -> bool:
    return os.environ.get(GOODPUT_ENV, "") not in ("0", "false")


def enable_goodput(rank: int = 0,
                   sink: Optional[Callable[[dict], None]] = None) -> None:
    """Arm the plane for this process (the plugin's telemetry setup)."""
    global _plane
    _plane = (rank, sink)


def disable_goodput() -> None:
    global _plane, _run_ledger
    _plane = None
    _run_ledger = None


def goodput_enabled() -> bool:
    return _plane is not None


def start_run(kind: str = "fit",
              device_tflops: Optional[float] = None,
              devices: int = 1) -> Optional[GoodputLedger]:
    """Open the run ledger if the plane is armed (trainer _run_stage)."""
    global _run_ledger
    if _plane is None:
        return None
    _run_ledger = GoodputLedger(kind, device_tflops=device_tflops,
                                devices=devices).start()
    return _run_ledger


def get_run_ledger() -> Optional[GoodputLedger]:
    return _run_ledger


def on_data_wait(seconds: float) -> None:
    """Hot-path hook next to metrics.on_data_wait (loop engine)."""
    ledger = _run_ledger
    if ledger is not None and "data_wait" in ledger.buckets:
        ledger.add("data_wait", seconds)


def finish_run(wall: Optional[float] = None) -> Optional[dict]:
    """Close the run ledger: finalize, mirror into /metrics, ship the
    doc to the driver, return it (trainer stage teardown)."""
    global _run_ledger
    ledger, _run_ledger = _run_ledger, None
    if ledger is None:
        return None
    doc = ledger.finalize(wall)
    publish_metrics(doc)
    if _plane is not None:
        rank, sink = _plane
        if sink is not None:
            try:
                sink(goodput_item(rank, doc))
            except Exception:
                _log.warning("goodput sink failed; doc dropped",
                             exc_info=True)
    return doc


__all__ = [
    "BUCKETS",
    "FIT_BUCKETS",
    "GOODPUT_ENV",
    "GOODPUT_TFLOPS_ENV",
    "GoodputLedger",
    "SERVE_BUCKETS",
    "USEFUL_BUCKET",
    "aggregate",
    "check_identity",
    "disable_goodput",
    "enable_goodput",
    "finish_run",
    "get_run_ledger",
    "goodput_armed",
    "goodput_enabled",
    "goodput_item",
    "measured_mfu",
    "on_data_wait",
    "publish_metrics",
    "reattribute_replay",
    "start_run",
]
