"""Distributed run telemetry: per-worker spans, driver aggregation,
heartbeats, Perfetto trace export — and the trace plane on top.

One coherent observability layer replacing three disconnected ones
(rank-0-only ThroughputMonitor numbers, the CSVLogger, and external
profilers): every rank records spans/counters (``spans.py``), batches
stream to the driver over the existing worker→driver queue channel,
and the driver merges them into a Chrome/Perfetto ``trace.json`` +
``telemetry.jsonl`` with per-rank step percentiles and straggler skew
(``aggregator.py``).  Worker heartbeats (``heartbeat.py``) feed a
driver watchdog that names a dead or wedged rank instead of hanging
silently.  ``tracing.py`` ties spans to *requests* (per-request trace
ids through the serve plan broadcast, per-tenant latency attribution)
and arms on-demand ``jax.profiler`` windows; ``flight.py`` is the
crash black box dumped at death-classification time.

Enable with ``Trainer(telemetry=True)`` (or a config dict /
``TelemetryConfig``), or process-wide with ``RLT_TELEMETRY=1``.
Artifacts land under ``<default_root_dir>/telemetry/`` — or, inside a
builtin tune trial, under the trial's own logdir so concurrent trials
never interleave.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

from ray_lightning_tpu.telemetry.spans import (  # noqa: F401
    counter,
    disable,
    drain,
    dropped,
    enable,
    enabled,
    flush,
    last_span,
    span,
)
from ray_lightning_tpu.telemetry.aggregator import (  # noqa: F401
    TELEMETRY_KEY,
    TelemetryAggregator,
    WorkerHeartbeatTimeout,
    get_active,
    set_active,
    spans_item,
)
from ray_lightning_tpu.telemetry.flight import (  # noqa: F401
    FlightRecorder,
    flight_path,
)
from ray_lightning_tpu.telemetry.tracing import (  # noqa: F401
    mint_trace_id,
    profile_tick,
    record_request_span,
)
from ray_lightning_tpu.telemetry.anatomy import (  # noqa: F401
    AnatomyController,
    StepAnatomy,
    anatomy_item,
    anatomy_tick,
    disable_anatomy,
    enable_anatomy,
    get_anatomy_controller,
    parse_anatomy_or_none,
    parse_trace_anatomy,
)
from ray_lightning_tpu.telemetry.goodput import (  # noqa: F401
    GoodputLedger,
    disable_goodput,
    enable_goodput,
    finish_run,
    goodput_item,
    measured_mfu,
    start_run,
)
from ray_lightning_tpu.telemetry.incident import (  # noqa: F401
    Detector,
    DetectorConfig,
    Incident,
    IncidentConfig,
    IncidentManager,
    TimelineStore,
)
from ray_lightning_tpu.telemetry.metrics import (  # noqa: F401
    MetricsRegistry,
    disable_metrics,
    enable_metrics,
    flush_metrics,
    get_registry,
    metrics_enabled,
    metrics_item,
    note_step_collectives,
    note_traced_collective,
    on_compile,
    on_step,
    record_collective,
)

__all__ = [
    "TelemetryConfig",
    "TelemetryAggregator",
    "WorkerHeartbeatTimeout",
    "TELEMETRY_KEY",
    "span",
    "counter",
    "enable",
    "disable",
    "enabled",
    "flush",
    "drain",
    "dropped",
    "last_span",
    "get_active",
    "set_active",
    "spans_item",
    "FlightRecorder",
    "flight_path",
    "mint_trace_id",
    "record_request_span",
    "profile_tick",
    "MetricsRegistry",
    "enable_metrics",
    "disable_metrics",
    "metrics_enabled",
    "flush_metrics",
    "get_registry",
    "metrics_item",
    "record_collective",
    "note_traced_collective",
    "note_step_collectives",
    "on_step",
    "on_compile",
    "GoodputLedger",
    "enable_goodput",
    "disable_goodput",
    "start_run",
    "finish_run",
    "goodput_item",
    "measured_mfu",
    "StepAnatomy",
    "AnatomyController",
    "anatomy_item",
    "anatomy_tick",
    "enable_anatomy",
    "disable_anatomy",
    "get_anatomy_controller",
    "parse_trace_anatomy",
    "parse_anatomy_or_none",
    "Detector",
    "DetectorConfig",
    "Incident",
    "IncidentConfig",
    "IncidentManager",
    "TimelineStore",
]


@dataclass
class TelemetryConfig:
    """Picklable telemetry settings carried on the Trainer (the trainer
    ships to workers, so the config rides along for free)."""

    enabled: bool = False
    #: explicit output dir; None = <default_root_dir>/telemetry (or the
    #: tune trial's logdir when running inside a builtin tune trial)
    dir: Optional[str] = None
    heartbeat_interval: float = 5.0
    heartbeat_timeout: float = 60.0
    #: raise WorkerHeartbeatTimeout past this silence (None = log only)
    hard_timeout: Optional[float] = None
    flush_every: int = 256
    capacity: int = 65536
    #: crash flight recorder (telemetry/flight.py): per-rank ring of the
    #: most recent driver-ingested records, dumped as flight_<rank>.json
    #: on a wedge verdict / death classification.  Bounded by this many
    #: records per rank; 0 still keeps heartbeats (min ring is 1).
    flight_capacity: int = 256
    #: metrics plane (telemetry/metrics.py): per-rank typed instruments
    #: (HBM gauges, step-time histogram, collective byte counters)
    #: riding the same worker→driver channel as spans
    metrics: bool = True
    #: seconds between device-state samples / window flushes
    metrics_interval: float = 2.0
    #: driver HTTP endpoint (/metrics Prometheus exposition + /status
    #: JSON).  None = no server unless RLT_METRICS_PORT is set; 0 = an
    #: ephemeral port (read it back from the returned metrics_url)
    metrics_port: Optional[int] = None
    #: anatomy plane (telemetry/anatomy.py): every N dispatches each
    #: rank arms a short jax.profiler window, parses its own capture
    #: locally into a StepAnatomy (measured compute/collective/exposed/
    #: host split) and ships only the compact dict to the driver.
    #: None = disarmed unless RLT_ANATOMY / RLT_ANATOMY_EVERY_N_STEPS
    #: arm it (resolved_anatomy below)
    anatomy_every_n_steps: Optional[int] = None
    #: dispatches traced per anatomy window
    anatomy_steps: int = 4
    #: incident plane (telemetry/incident.py): driver-side timelines +
    #: rolling anomaly detectors + auto-RCA incident reports.  On by
    #: default whenever telemetry is enabled; RLT_INCIDENT=0 disarms
    incident: bool = True
    #: baseline samples per detector before it may trip
    incident_warmup: int = 16
    #: consecutive breached (healthy) samples to open (close)
    incident_patience: int = 3
    #: seconds after close before the same detector may re-trip
    incident_cooldown_s: float = 30.0
    #: per-(series, rank) timeline ring capacity
    incident_capacity: int = 512
    #: goodput plane (telemetry/goodput.py): the per-run wall-clock
    #: partition + measured MFU.  None = armed whenever telemetry is
    #: enabled unless RLT_GOODPUT=0 disarms; an explicit bool wins
    goodput: Optional[bool] = None
    #: per-device peak TFLOPs for the MFU denominator; None defers to
    #: RLT_GOODPUT_TFLOPS, then PlanConfig.device_tflops
    goodput_tflops: Optional[float] = None

    @classmethod
    def resolve(cls, value: Any) -> "TelemetryConfig":
        """Trainer's ``telemetry=`` argument → a config.  None defers to
        the ``RLT_TELEMETRY`` env var; True/False force; a dict supplies
        field overrides (enabled unless it says otherwise)."""
        if isinstance(value, cls):
            return value
        if value is None:
            return cls(enabled=os.environ.get("RLT_TELEMETRY", "")
                       in ("1", "true"))
        if isinstance(value, bool):
            return cls(enabled=value)
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        raise TypeError(
            f"telemetry must be None/bool/dict/TelemetryConfig; got "
            f"{type(value).__name__}")

    def resolved_metrics_port(self) -> Optional[int]:
        """Port for the driver's /metrics endpoint: the explicit config
        field, else the ``RLT_METRICS_PORT`` env var, else None (no
        server)."""
        if self.metrics_port is not None:
            return int(self.metrics_port)
        env = os.environ.get("RLT_METRICS_PORT", "").strip()
        if env:
            try:
                return int(env)
            except ValueError:
                import logging
                logging.getLogger(__name__).warning(
                    "RLT_METRICS_PORT=%r is not an integer; metrics "
                    "endpoint disabled", env)
        return None

    def resolved_anatomy(self) -> "tuple[Optional[int], int]":
        """(every_n_dispatches, window_dispatches) with the RLT_ANATOMY*
        env merged in: the explicit config field wins, else
        ``RLT_ANATOMY_EVERY_N_STEPS``, else bare ``RLT_ANATOMY=1`` arms
        the default cadence.  (None, window) = disarmed."""
        from ray_lightning_tpu.telemetry import anatomy as _anatomy
        every = self.anatomy_every_n_steps
        if every is None:
            env = os.environ.get(_anatomy.ANATOMY_EVERY_ENV, "").strip()
            if env:
                try:
                    every = int(env)
                except ValueError:
                    import logging
                    logging.getLogger(__name__).warning(
                        "%s=%r is not an integer; anatomy disarmed",
                        _anatomy.ANATOMY_EVERY_ENV, env)
            elif os.environ.get(_anatomy.ANATOMY_ENV, "") in ("1", "true"):
                every = _anatomy.DEFAULT_EVERY_N
        steps = self.anatomy_steps
        env = os.environ.get(_anatomy.ANATOMY_STEPS_ENV, "").strip()
        if env:
            try:
                steps = int(env)
            except ValueError:
                pass
        if every is not None and every <= 0:
            every = None
        return every, max(1, int(steps))

    def resolved_incident(self) -> "IncidentConfig":
        """Driver-side incident-plane config: these TelemetryConfig
        fields as the base, with the ``RLT_INCIDENT*`` env merged on
        top (env wins — the same precedence as every other knob)."""
        from ray_lightning_tpu.telemetry.incident import IncidentConfig
        base = IncidentConfig(
            enabled=bool(self.incident),
            capacity=int(self.incident_capacity),
            warmup=int(self.incident_warmup),
            patience=int(self.incident_patience),
            cooldown_s=float(self.incident_cooldown_s))
        return IncidentConfig.from_env(base=base)

    def resolved_goodput(self) -> bool:
        """Is the goodput ledger armed?  The explicit config bool wins;
        None defers to ``RLT_GOODPUT`` (unset = armed — goodput rides
        telemetry by default, so arming telemetry is opting in)."""
        if self.goodput is not None:
            return bool(self.goodput)
        from ray_lightning_tpu.telemetry import goodput as _goodput
        return _goodput.goodput_armed()

    def resolved_goodput_tflops(self) -> Optional[float]:
        """Per-device peak TFLOPs for MFU: the explicit config field,
        else ``RLT_GOODPUT_TFLOPS``, else None (the trainer falls back
        to ``PlanConfig.device_tflops``)."""
        if self.goodput_tflops is not None:
            return float(self.goodput_tflops)
        from ray_lightning_tpu.telemetry import goodput as _goodput
        env = os.environ.get(_goodput.GOODPUT_TFLOPS_ENV, "").strip()
        if env:
            try:
                return float(env)
            except ValueError:
                import logging
                logging.getLogger(__name__).warning(
                    "%s=%r is not a number; ignored",
                    _goodput.GOODPUT_TFLOPS_ENV, env)
        return None

    def worker_env(self) -> dict:
        """Env knobs actor fleets must inherit so every rank arms the
        same anatomy cadence and goodput plane the driver resolved
        (ships in the plugin's base worker env like the
        RLT_COMM*/RLT_PLAN* knobs)."""
        from ray_lightning_tpu.telemetry import anatomy as _anatomy
        from ray_lightning_tpu.telemetry import goodput as _goodput
        out = {}
        every, steps = self.resolved_anatomy()
        if every is not None:
            out[_anatomy.ANATOMY_EVERY_ENV] = str(every)
            out[_anatomy.ANATOMY_STEPS_ENV] = str(steps)
        if not self.resolved_goodput():
            out[_goodput.GOODPUT_ENV] = "0"
        if not self.resolved_incident().enabled:
            # detectors live on the driver, but workers gate their
            # heartbeat sample tail + arm-file polling on the same knob
            from ray_lightning_tpu.telemetry import incident as _incident
            out[_incident.INCIDENT_ENV] = "0"
        tflops = self.resolved_goodput_tflops()
        if tflops is not None:
            out[_goodput.GOODPUT_TFLOPS_ENV] = str(tflops)
        return out

    def resolve_dir(self, default_root_dir: str) -> str:
        if self.dir:
            return self.dir
        try:
            from ray_lightning_tpu.tune.session import get_trial_dir
            trial_dir = get_trial_dir()
        except Exception:
            trial_dir = None
        if trial_dir:
            return os.path.join(trial_dir, "telemetry")
        return os.path.join(default_root_dir, "telemetry")
