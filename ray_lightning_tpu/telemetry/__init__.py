"""Distributed run telemetry: per-worker spans, driver aggregation,
heartbeats and Perfetto trace export.

One coherent observability layer replacing three disconnected ones
(rank-0-only ThroughputMonitor numbers, the CSVLogger, and external
profilers): every rank records spans/counters (``spans.py``), batches
stream to the driver over the existing worker→driver queue channel,
and the driver merges them into a Chrome/Perfetto ``trace.json`` +
``telemetry.jsonl`` with per-rank step percentiles and straggler skew
(``aggregator.py``).  Worker heartbeats (``heartbeat.py``) feed a
driver watchdog that names a dead or wedged rank instead of hanging
silently.

Enable with ``Trainer(telemetry=True)`` (or a config dict /
``TelemetryConfig``), or process-wide with ``RLT_TELEMETRY=1``.
Artifacts land under ``<default_root_dir>/telemetry/`` — or, inside a
builtin tune trial, under the trial's own logdir so concurrent trials
never interleave.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

from ray_lightning_tpu.telemetry.spans import (  # noqa: F401
    counter,
    disable,
    drain,
    dropped,
    enable,
    enabled,
    flush,
    last_span,
    span,
)
from ray_lightning_tpu.telemetry.aggregator import (  # noqa: F401
    TELEMETRY_KEY,
    TelemetryAggregator,
    WorkerHeartbeatTimeout,
    get_active,
    set_active,
    spans_item,
)

__all__ = [
    "TelemetryConfig",
    "TelemetryAggregator",
    "WorkerHeartbeatTimeout",
    "TELEMETRY_KEY",
    "span",
    "counter",
    "enable",
    "disable",
    "enabled",
    "flush",
    "drain",
    "dropped",
    "last_span",
    "get_active",
    "set_active",
    "spans_item",
]


@dataclass
class TelemetryConfig:
    """Picklable telemetry settings carried on the Trainer (the trainer
    ships to workers, so the config rides along for free)."""

    enabled: bool = False
    #: explicit output dir; None = <default_root_dir>/telemetry (or the
    #: tune trial's logdir when running inside a builtin tune trial)
    dir: Optional[str] = None
    heartbeat_interval: float = 5.0
    heartbeat_timeout: float = 60.0
    #: raise WorkerHeartbeatTimeout past this silence (None = log only)
    hard_timeout: Optional[float] = None
    flush_every: int = 256
    capacity: int = 65536

    @classmethod
    def resolve(cls, value: Any) -> "TelemetryConfig":
        """Trainer's ``telemetry=`` argument → a config.  None defers to
        the ``RLT_TELEMETRY`` env var; True/False force; a dict supplies
        field overrides (enabled unless it says otherwise)."""
        if isinstance(value, cls):
            return value
        if value is None:
            return cls(enabled=os.environ.get("RLT_TELEMETRY", "")
                       in ("1", "true"))
        if isinstance(value, bool):
            return cls(enabled=value)
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        raise TypeError(
            f"telemetry must be None/bool/dict/TelemetryConfig; got "
            f"{type(value).__name__}")

    def resolve_dir(self, default_root_dir: str) -> str:
        if self.dir:
            return self.dir
        try:
            from ray_lightning_tpu.tune.session import get_trial_dir
            trial_dir = get_trial_dir()
        except Exception:
            trial_dir = None
        if trial_dir:
            return os.path.join(trial_dir, "telemetry")
        return os.path.join(default_root_dir, "telemetry")
