"""Crash flight recorder: bounded per-rank rings of recent telemetry,
dumped at death-classification time.

The normal telemetry path buffers everything in the aggregator and
exports once at teardown — which is exactly when a postmortem needs it
least: a rank that dies mid-run leaves its most recent spans either
un-flushed in the dead process or buried in a trace.json nobody
correlates with the failure.  The flight recorder is the black box:

- every span/counter batch, heartbeat and metrics brief the aggregator
  ingests is mirrored into a per-rank ring (``collections.deque`` with
  ``maxlen`` — the bounded-size invariant is structural, not policed);
- the rings survive OUTSIDE the flush/export path: dumping does not
  consume them, and they cost O(capacity) memory per rank regardless of
  run length;
- :meth:`FlightRecorder.dump` writes ``flight_<rank>.json`` — the
  rank's last spans/counters, heartbeat trail, latest metrics brief,
  the classified cause, and (when the backend can supply one) the
  worker's log tail — so a postmortem starts from evidence instead of a
  silent gap.

Dump sites: the elastic driver at death-classification time
(elastic/driver.py), the watchdog on a wedge verdict, and the generic
failure diagnosis for ranks whose process probe reads dead
(aggregator.log_failure_diagnosis).  Repeated dumps for the same rank
overwrite — last verdict wins, which is the one correlated with the
classified cause.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Any, Optional

_log = logging.getLogger(__name__)

#: default per-rank ring capacities (records, not bytes: span records
#: are small dicts, so 256 spans ≈ tens of KB per rank)
DEFAULT_SPANS = 256
DEFAULT_BEATS = 32


def flight_path(out_dir: str, rank: int) -> str:
    return os.path.join(out_dir, f"flight_{rank}.json")


class FlightRecorder:
    """Per-rank bounded rings + the ``flight_<rank>.json`` dumper."""

    def __init__(self, out_dir: str, span_capacity: int = DEFAULT_SPANS,
                 beat_capacity: int = DEFAULT_BEATS):
        self.out_dir = out_dir
        self.span_capacity = max(1, int(span_capacity))
        self.beat_capacity = max(1, int(beat_capacity))
        self._records: dict[int, deque] = {}
        self._beats: dict[int, deque] = {}
        self._briefs: dict[int, dict] = {}
        self._anatomy: dict[int, dict] = {}
        self._goodput: dict[int, dict] = {}
        #: rank -> path of the last dump (status/test surface)
        self.dumped: dict[int, str] = {}

    # -- ingestion mirrors (called under the aggregator's lock-free
    # ingest paths; deque appends are atomic) ---------------------------

    def note_records(self, rank: int, records: list) -> None:
        ring = self._records.get(rank)
        if ring is None:
            ring = self._records[rank] = deque(maxlen=self.span_capacity)
        ring.extend(records)

    def note_heartbeat(self, beat: dict) -> None:
        rank = beat.get("rank", -1)
        ring = self._beats.get(rank)
        if ring is None:
            ring = self._beats[rank] = deque(maxlen=self.beat_capacity)
        ring.append({k: beat.get(k) for k in
                     ("rank", "pid", "host", "wall", "last_span",
                      "metrics", "dropped")})

    def note_metrics_brief(self, rank: int, brief: Optional[dict]) -> None:
        if brief:
            self._briefs[rank] = dict(brief)

    def note_anatomy(self, rank: int, anatomy: Optional[dict]) -> None:
        """Latest measured step anatomy (telemetry/anatomy.py) — the
        black box then says where the rank's device time was going,
        not just which span it died in."""
        if anatomy:
            self._anatomy[rank] = dict(anatomy)

    def note_goodput(self, rank: int, doc: Optional[dict]) -> None:
        """Latest run-ledger doc (telemetry/goodput.py) — the black box
        then carries the rank's wall-clock partition up to the crash."""
        if doc:
            self._goodput[rank] = dict(doc)

    # -- evidence surface ------------------------------------------------

    def last_spans(self, rank: int) -> list[dict]:
        return [r for r in self._records.get(rank, ())
                if r.get("t") == "span"]

    def dump(self, rank: int, cause: str,
             handle: Any = None) -> Optional[str]:
        """Write ``flight_<rank>.json`` under ``out_dir``; returns the
        path (None only when the write itself fails — a flight dump
        must never raise into failure handling)."""
        records = list(self._records.get(rank, ()))
        beats = list(self._beats.get(rank, ()))
        doc = {
            "t": "flight",
            "rank": rank,
            "cause": cause,
            "dumped_at": time.time(),
            "records": records,
            "spans": [r for r in records if r.get("t") == "span"],
            "last_span": next(
                (r["name"] for r in reversed(records)
                 if r.get("t") == "span"), None),
            "heartbeats": beats,
            "last_heartbeat_wall": beats[-1]["wall"] if beats else None,
            "metrics_brief": self._briefs.get(rank),
            "anatomy": self._anatomy.get(rank),
            "goodput": self._goodput.get(rank),
            "capacity": {"spans": self.span_capacity,
                         "heartbeats": self.beat_capacity},
        }
        tail = None
        if handle is not None:
            # backend-supplied forensic context (cluster/backend.py
            # ActorHandle.log_tail): the built-in backend captures each
            # worker's stdout/stderr, so the flight file carries the
            # crash's own log lines next to its spans
            try:
                tail = handle.log_tail()
            except Exception:
                tail = None
        if tail:
            doc["log_tail"] = tail
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = flight_path(self.out_dir, rank)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError:
            _log.warning("flight recorder: dump for rank %d failed",
                         rank, exc_info=True)
            return None
        self.dumped[rank] = path
        _log.warning(
            "flight recorder: rank %d black box -> %s (%d spans, "
            "%d heartbeats; cause: %s)", rank, path,
            len(doc["spans"]), len(beats), cause.splitlines()[0][:200])
        return path

    def ranks(self) -> list[int]:
        return sorted(set(self._records) | set(self._beats))


__all__ = ["FlightRecorder", "flight_path", "DEFAULT_SPANS",
           "DEFAULT_BEATS"]
