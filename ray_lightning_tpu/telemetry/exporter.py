"""Driver-side live metrics endpoint: Prometheus exposition + status.

The aggregator (telemetry/aggregator.py) already holds every rank's
latest cumulative metrics window; this module makes that state
scrapable while the run is live:

- :func:`render_prometheus` — text exposition (format 0.0.4) of every
  per-rank instrument, each series carrying a ``rank`` label so one
  scrape covers the whole job (the TorchTitan-style per-rank
  throughput/memory surface, PAPERS.md).
- :class:`MetricsHTTPServer` — a stdlib ``http.server`` thread on the
  driver serving ``GET /metrics`` (exposition) and ``GET /status``
  (JSON: per-rank heartbeat age, current step, step p50/p95, HBM, last
  collective — the "is it healthy right now" complement to the
  post-hoc Perfetto trace).  With the trace plane live, ``/status``
  additionally carries per-tenant TTFT/TPOT breakdowns (queue vs
  prefill vs decode attribution), the flight-recorder dump paths, and
  the profile-window state.
- ``GET /timeline`` — the incident plane's ring buffers
  (telemetry/incident.py): time-stamped samples for the load-bearing
  series (step wall, data wait, exposed comm, TTFT/TPOT p99, queue
  depth, goodput fraction, HBM peak) per rank plus correlated events,
  with ``series``/``rank``/``window``/``downsample`` query params.
- ``POST /debug/profile?steps=N`` — on-demand ``jax.profiler`` capture
  (telemetry/tracing.py controllers): the serve plane arms a window on
  the next plan broadcast; the fit plane writes the control file its
  workers poll.  The resulting trace dir is linked from ``/status`` —
  no "restart with the callback configured".

No third-party client library: the exposition format is a few lines of
text, and the driver must stay dependency-free (ROADMAP constraint).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_log = logging.getLogger(__name__)


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(aggregator) -> str:
    """Text exposition of every rank's latest metrics window."""
    by_name: dict[str, list[tuple]] = {}   # name -> [(rank, metric)]
    types: dict[str, str] = {}
    for rank, item in sorted(aggregator.latest_metrics().items()):
        for m in item.get("metrics", ()):
            by_name.setdefault(m["name"], []).append((rank, m))
            types[m["name"]] = m.get("type", "gauge")
    lines: list[str] = []
    for name in sorted(by_name):
        lines.append(f"# TYPE {name} {types[name]}")
        for rank, m in by_name[name]:
            labels = dict(m.get("labels") or {})
            labels["rank"] = str(rank)
            if m.get("type") == "histogram":
                cum = 0
                bounds = list(m.get("buckets", ())) + ["+Inf"]
                for bound, count in zip(bounds, m.get("counts", ())):
                    cum += count
                    blabels = dict(labels)
                    blabels["le"] = (bound if bound == "+Inf"
                                     else _fmt_value(bound))
                    lines.append(f"{name}_bucket{_fmt_labels(blabels)} "
                                 f"{cum}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(m.get('sum', 0.0))}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{int(m.get('count', 0))}")
            else:
                lines.append(f"{name}{_fmt_labels(labels)} "
                             f"{_fmt_value(m.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


def render_status(aggregator, profile_controller=None) -> dict:
    """JSON status document: one entry per rank with liveness +
    progress + step latency percentiles, plus the trace plane's
    per-tenant latency breakdown, flight-recorder dumps and the
    on-demand profile-window state."""
    stats = aggregator.step_stats().get("per_rank", {})
    briefs = aggregator.metrics_briefs()
    ranks: dict[str, dict] = {}
    for key, hb in aggregator.heartbeats().items():
        beat = hb.get("beat", {})
        rank = beat.get("rank", key)
        entry = ranks.setdefault(str(rank), {})
        entry["heartbeat_age_s"] = round(hb.get("age", 0.0), 3)
        entry["last_span"] = beat.get("last_span")
    for rank, brief in briefs.items():
        entry = ranks.setdefault(str(rank), {})
        entry["step"] = brief.get("step")
        entry["hbm_bytes"] = brief.get("hbm_bytes")
        entry["last_collective"] = brief.get("last_collective")
    for rank, st in stats.items():
        entry = ranks.setdefault(str(rank), {})
        entry["step_p50_ms"] = st.get("p50_ms")
        entry["step_p95_ms"] = st.get("p95_ms")
        entry["steps_recorded"] = st.get("steps")
    doc: dict = {"ranks": ranks}
    anatomy = aggregator.anatomy_stats()
    if anatomy:
        # anatomy plane (telemetry/anatomy.py): per-rank MEASURED step
        # breakdown (compute/collective/exposed/host, collectives split
        # by op and ici/dcn link) parsed from real profiler captures on
        # the ranks themselves, plus straggler skew on measured wall
        doc["anatomy"] = anatomy
    goodput = aggregator.goodput_stats()
    if goodput:
        # goodput plane (telemetry/goodput.py): the full-run wall-clock
        # partition (sum(buckets) == run_wall exactly) + measured MFU,
        # per rank and fleet-aggregated
        doc["goodput"] = goodput
    tenants = aggregator.tenant_breakdown()
    if tenants:
        # per-request trace plane: TTFT/TPOT with queue vs prefill vs
        # decode attribution, per tenant (aggregator.tenant_breakdown)
        doc["tenants"] = tenants
        doc["traced_requests"] = len(aggregator.request_trees())
    if aggregator.flight.dumped:
        doc["flight_dumps"] = {str(r): p for r, p
                               in aggregator.flight.dumped.items()}
    incidents = aggregator.incident_stats()
    if incidents.get("enabled"):
        # incident plane (telemetry/incident.py): open/recent incidents
        # with cause ranking, plus detector + timeline state
        doc["incidents"] = incidents
    if profile_controller is not None:
        doc["profile"] = profile_controller.status()
    return doc


class MetricsHTTPServer:
    """`GET /metrics` + `GET /status` on the driver, backed by the live
    aggregator.  Port 0 binds an ephemeral port (read it back from
    ``.port``) — the default inside builtin-tune trials so concurrent
    trials never collide."""

    def __init__(self, aggregator, port: int = 0,
                 host: str = "127.0.0.1", profile_controller=None,
                 status_extra=None):
        agg = aggregator
        profiler = profile_controller

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 - stdlib API name
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = render_prometheus(agg).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif self.path.split("?")[0] == "/timeline":
                        # incident plane's ring buffers: time-stamped
                        # samples per (series, rank) + correlated
                        # events, windowed/downsampled server-side so
                        # dashboards never pull the full rings
                        from urllib.parse import parse_qs
                        q = parse_qs(self.path.partition("?")[2])

                        def _one(key):
                            v = q.get(key, [None])[0]
                            return v if v not in (None, "") else None

                        rank_s = _one("rank")
                        window_s = _one("window")
                        doc = agg.timeline_window(
                            series=_one("series"),
                            rank=int(rank_s) if rank_s is not None
                            else None,
                            window_s=float(window_s)
                            if window_s is not None else None,
                            downsample=int(_one("downsample") or 0))
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                    elif self.path.split("?")[0] == "/status":
                        doc = render_status(agg, profiler)
                        if status_extra is not None:
                            # caller-owned status block (the fleet
                            # router's replica/autoscale/failover view,
                            # serve/fleet/router.py)
                            doc.update(status_extra())
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:      # a scrape must never crash a run
                    _log.warning("metrics endpoint failed", exc_info=True)
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):         # noqa: N802 - stdlib API name
                path, _, query = self.path.partition("?")
                if path != "/debug/profile":
                    self.send_error(404)
                    return
                if profiler is None:
                    self.send_error(
                        501, "no profile controller on this run "
                        "(serve fleet / shared-filesystem fit only)")
                    return
                try:
                    from urllib.parse import parse_qs
                    steps = int(parse_qs(query).get("steps", ["8"])[0])
                    resp = profiler.request(steps)
                except (ValueError, OSError) as e:
                    self.send_error(400, str(e))
                    return
                except Exception:   # arming must never crash the run
                    _log.warning("profile arm failed", exc_info=True)
                    self.send_error(500)
                    return
                body = json.dumps(resp).encode()
                self.send_response(200 if resp.get("accepted") else 409)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log events
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="rlt-metrics-http")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsHTTPServer":
        self._thread.start()
        _log.info("metrics exporter: serving /metrics and /status at %s",
                  self.url)
        return self

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def start_metrics_server(aggregator, cfg,
                         profile_controller=None,
                         status_extra=None
                         ) -> Optional[MetricsHTTPServer]:
    """Start the driver endpoint when the config asks for one.

    Port resolution: ``TelemetryConfig.metrics_port`` or the
    ``RLT_METRICS_PORT`` env var; None = no server.  Inside a builtin
    tune trial an explicit non-zero port is downgraded to ephemeral —
    concurrent trials each get their own listener instead of one
    winning the bind and the rest crashing."""
    port = cfg.resolved_metrics_port()
    if port is None:
        return None
    trial = None
    try:
        from ray_lightning_tpu.tune.session import get_trial
        trial = get_trial()
    except Exception:
        pass
    if port != 0 and trial is not None:
        _log.info("metrics exporter: inside a tune trial; using "
                  "an ephemeral port instead of %d", port)
        port = 0
    try:
        server = MetricsHTTPServer(
            aggregator, port=port,
            profile_controller=profile_controller,
            status_extra=status_extra).start()
    except OSError as e:
        _log.warning("metrics exporter: could not bind port %s (%s); "
                     "run continues without a live endpoint", port, e)
        return None
    if trial is not None:
        # which port this trial landed on, for ExperimentAnalysis /
        # dashboards scraping a fleet of concurrent trials
        trial.metrics_url = server.url
    return server
