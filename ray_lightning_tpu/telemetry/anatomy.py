"""Trace→anatomy: measured step-time truth from XLA profiler captures.

Every perf claim since PR 10 has been modeled (declared bytes × modeled
bandwidth) or proxied (wall minus a floor measured in the same
process).  This module is the measurement side: parse a captured
``jax.profiler`` Chrome-trace into a per-rank, per-step
:class:`StepAnatomy` — where the device time actually went:

- ``compute_s``   — device seconds under non-collective ops (union of
  their intervals, so concurrent fusions don't double-count);
- ``collective_s`` — collective device seconds (overlap-INCLUSIVE sum,
  split ``by_op`` and ``by_link`` ici/dcn via comm/audit.py's
  collective-name / replica-group classification);
- ``exposed_s``   — the MEASURED exposed comm: collective interval
  time not covered by any compute interval on the same device
  timeline.  This is the number the wall-minus-floor proxy in
  bench_comm approximates; the divergence between the two is itself a
  finding (the proxy includes quantize/dequantize compute, the
  measured number is pure serialization);
- ``host_s``      — host-gap/dispatch time: window wall not covered by
  ANY device op (the tunnel, the python loop, a pipeline bubble).

The decomposition is an interval-algebra identity, not an estimate:

    wall_s == compute_s + exposed_s + host_s        (exactly)

because ``exposed = |collective ∖ compute|`` and ``host = wall −
|collective ∪ compute|``.  Tests and the selfcheck pin it.

ONE parser for every trace layout (`benchmarks/trace_tools.py` is a
thin wrapper over this module):

- TPU/device traces: processes named ``/device:TPU:k`` with nested
  "XLA Ops" (per-instruction) and "XLA Modules" (per-execution)
  tracks;
- CPU proxy traces: one ``/host:CPU`` process whose
  ``tf_XLATfrtCpuClient/<id>`` threads are the per-(virtual-)device
  timelines — HLO op events carry ``hlo_module``/``hlo_op`` args and
  collectives appear by name (``all-reduce`` …), so the same anatomy
  math runs on the 8-virtual-device CPU mesh the test suite audits.
  One honest caveat: the CPU thunk executor serializes ops per device
  thread, so measured exposed ≈ collective there — real overlap needs
  a real fabric (ROADMAP item 5).

The second half is auto-capture: :class:`AnatomyController` arms a
short profiler window on a step cadence through the same
``WorkerProfiler`` machinery the on-demand ``POST /debug/profile``
controllers drive (telemetry/tracing.py), parses the capture LOCALLY
on the rank that wrote it, and ships only the compact anatomy dict
over the worker→driver queue — never the multi-MB trace.  Arm with
``TelemetryConfig(anatomy_every_n_steps=…)`` or ``RLT_ANATOMY=1`` /
``RLT_ANATOMY_EVERY_N_STEPS=N`` / ``RLT_ANATOMY_STEPS=W``.

No jax at module import (worker_main touches this package before jax
exists); the profiler is reached only through tracing.WorkerProfiler
inside the capture window.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import logging
import os
import shutil
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_lightning_tpu.telemetry.aggregator import TELEMETRY_KEY

_log = logging.getLogger(__name__)

#: env knobs (TelemetryConfig.resolved_anatomy merges them): RLT_ANATOMY=1
#: arms the default cadence; the other two override cadence / window
ANATOMY_ENV = "RLT_ANATOMY"
ANATOMY_EVERY_ENV = "RLT_ANATOMY_EVERY_N_STEPS"
ANATOMY_STEPS_ENV = "RLT_ANATOMY_STEPS"

#: incident-plane arm channel (incident.py INCIDENT_CONTROL_ENV): when
#: set in the worker env, every AnatomyController polls the arm file and
#: forces an off-cadence evidence window on detector trip
INCIDENT_CONTROL_ENV = "RLT_INCIDENT_CONTROL"

#: default cadence when armed via bare RLT_ANATOMY=1 (dispatches between
#: windows) and default window length (dispatches traced per window)
DEFAULT_EVERY_N = 50
DEFAULT_WINDOW = 4


# -- trace file location + low-level parsing -------------------------------

def locate_trace_json(trace_dir: str) -> str:
    """Newest ``*.trace.json.gz`` under a profiler capture dir (the ONE
    locator — trace_tools delegates here)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    return paths[-1]


def read_trace(path: str) -> dict:
    """Load one Chrome-trace JSON (gzipped or plain)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def _meta_maps(events: list) -> tuple[dict, dict]:
    """(pid → process name, (pid, tid) → thread name) metadata maps."""
    procs: dict = {}
    threads: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    return procs, threads


def device_track_events(trace_path: str, track: str = "XLA Ops") -> list:
    """Complete ('X') events on one device-side track (TPU layout).

    Device processes are named ``/device:TPU:0`` etc. and carry nested
    tracks — "Steps" ⊃ "XLA Modules" ⊃ "XLA Ops" — so callers must pick
    ONE track or they double-count: per-op analysis wants "XLA Ops",
    per-step wall time wants "XLA Modules".
    """
    data = read_trace(trace_path)
    events = data.get("traceEvents", [])
    procs, threads = _meta_maps(events)

    def on_track(e) -> bool:
        pname = procs.get(e.get("pid"), "")
        tname = threads.get((e.get("pid"), e.get("tid")), "")
        return "/device:" in pname and tname == track

    return [e for e in events
            if e.get("ph") == "X" and e.get("dur") and on_track(e)]


def bucket_of(name: str) -> str:
    """Coarse op-category for a device event name (HLO-ish).  The ONE
    category-bucketing table (trace_tools delegates here)."""
    n = name.lower()
    if "pallas" in n or "custom-call" in n or "flash" in n:
        return "pallas/custom"
    if "convert" in n:
        return "convert-fusion"
    if "all-reduce" in n or "all-gather" in n or "reduce-scatter" in n \
            or "collective" in n or "permute" in n:
        return "collective"
    if "multiply" in n and ("reduce" in n or "subtract" in n):
        return "multiply-reduce-fusion"
    if n.startswith("fusion") or ".fusion" in n:
        return "generic-fusion"
    if "dot" in n or "dense" in n or "conv" in n:
        return "dot/conv"
    if "copy" in n or "bitcast" in n or "transpose" in n:
        return "copy/layout"
    if "dynamic" in n or "gather" in n or "scatter" in n or "slice" in n:
        return "gather/scatter"
    if "reduce" in n or "add" in n:
        return "reduce/add"
    return "other"


#: CPU-layout wrapper/bookkeeping events that are NOT device work
_CPU_NOISE = ("ThreadpoolListener", "ThunkExecutor", "ParseArguments")

#: CPU-layout per-execution dispatch wrapper (the "module event" analog)
_CPU_EXEC = "TfrtCpuExecutable::ExecuteHelper"


def device_timelines(trace_path: str) -> list[dict]:
    """Per-device op/module timelines from either trace layout.

    Returns ``[{"device": label, "ops": [events], "modules": [events]}]``
    — TPU: one entry per ``/device:`` process ("XLA Ops" / "XLA
    Modules" tracks).  CPU: the thunk executor runs HLO ops on one
    ``tf_XLATfrtCpuClient`` thread per virtual device — OR inline on
    the dispatching python thread for a lone device — so the op test
    is the ``hlo_op``/``hlo_module`` event args (only real HLO
    executions carry them), grouped by thread; the ExecuteHelper
    dispatch wrappers on the same thread stand in for module events.
    Timelines without any op event are dropped.
    """
    data = read_trace(trace_path)
    events = data.get("traceEvents", [])
    procs, threads = _meta_maps(events)
    device_pids = {pid for pid, name in procs.items() if "/device:" in name}
    out: dict[Any, dict] = {}
    for e in events:
        if e.get("ph") != "X" or not e.get("dur"):
            continue
        pid, tid = e.get("pid"), e.get("tid")
        if pid in device_pids:
            track = threads.get((pid, tid), "")
            tl = out.setdefault(pid, {
                "device": procs.get(pid, str(pid)),
                "ops": [], "modules": []})
            if track == "XLA Ops":
                tl["ops"].append(e)
            elif track == "XLA Modules":
                tl["modules"].append(e)
            continue
        name = e.get("name", "")
        args = e.get("args") or {}
        is_op = ("hlo_op" in args or "hlo_module" in args) \
            and not any(w in name for w in _CPU_NOISE)
        if is_op or name == _CPU_EXEC:
            tl = out.setdefault((pid, tid), {
                "device": threads.get((pid, tid), f"{pid}/{tid}"),
                "ops": [], "modules": []})
            (tl["ops"] if is_op else tl["modules"]).append(e)
    return [tl for tl in out.values() if tl["ops"]]


# -- interval algebra ------------------------------------------------------

def _union(intervals: list[tuple[float, float]]) -> list:
    """Merge overlapping [start, end) intervals (sorted, disjoint)."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [tuple(iv) for iv in out]


def _measure(merged: list) -> float:
    return sum(e - s for s, e in merged)


def _subtract(a_merged: list, b_merged: list) -> list:
    """Interval difference a ∖ b over already-merged interval lists."""
    out = []
    bi = 0
    for s, e in a_merged:
        cur = s
        while bi < len(b_merged) and b_merged[bi][1] <= cur:
            bi += 1
        j = bi
        while j < len(b_merged) and b_merged[j][0] < e:
            bs, be = b_merged[j]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


# -- the anatomy -----------------------------------------------------------

@dataclass
class StepAnatomy:
    """Per-step device-time breakdown of one rank's capture window.

    All ``*_s`` figures are seconds PER STEP PER DEVICE: timeline sums
    divided by ``devices`` × ``steps`` (SPMD lockstep), with ``wall_s``
    the window's global extent per step.  Identity (pinned by tests +
    selfcheck): ``wall_s == compute_s + exposed_s + host_s`` (up to the
    clamp of ``host_s`` at 0); ``collective_s`` is the
    overlap-inclusive total, so it can exceed ``exposed_s``.
    """

    steps: int = 0
    devices: int = 0
    wall_s: float = 0.0
    compute_s: float = 0.0
    collective_s: float = 0.0
    exposed_s: float = 0.0
    host_s: float = 0.0
    #: collective device seconds per step, split by op kind and by link
    collective_by_op: dict = field(default_factory=dict)
    collective_by_link: dict = field(default_factory=dict)
    #: host-gap share of the window — the measured (per-stage, for MPMD
    #: ranks) bubble fraction
    bubble_fraction: float = 0.0
    #: per-module device seconds per step (top modules; MPMD stage
    #: programs land here one entry per stage program)
    modules: dict = field(default_factory=dict)
    #: "xla-device" (TPU module/op tracks) | "cpu-host" (client threads)
    source: str = ""

    def as_dict(self) -> dict:
        """Compact JSON-safe dict (the wire/bench form)."""
        rd = lambda v: round(float(v), 9)   # noqa: E731
        return {
            "steps": int(self.steps),
            "devices": int(self.devices),
            "wall_s": rd(self.wall_s),
            "compute_s": rd(self.compute_s),
            "collective_s": rd(self.collective_s),
            "exposed_s": rd(self.exposed_s),
            "host_s": rd(self.host_s),
            "collective_by_op": {k: rd(v) for k, v
                                 in sorted(self.collective_by_op.items())},
            "collective_by_link": {k: rd(v) for k, v
                                   in sorted(self.collective_by_link.items())},
            "bubble_fraction": round(float(self.bubble_fraction), 6),
            "modules": {k: rd(v) for k, v in self.modules.items()},
            "source": self.source,
        }


def _infer_steps(tl: dict) -> int:
    """Executions of the dominant program in one timeline.

    TPU: count of the dominant "XLA Modules" event.  CPU: the
    ExecuteHelper wrappers dispatch EVERY module, so count per-op-name
    occurrences within the dominant ``hlo_module`` and take the median
    (each instruction runs once per execution; the median is robust to
    an op name repeated by unrelated modules).
    """
    mods = tl["modules"]
    ops = tl["ops"]
    by_mod_dur: dict[str, float] = collections.defaultdict(float)
    for e in ops:
        m = (e.get("args") or {}).get("hlo_module")
        if m:
            by_mod_dur[m] += e["dur"]
    if by_mod_dur:
        dom = max(by_mod_dur, key=by_mod_dur.get)
        counts = collections.Counter(
            e["name"] for e in ops
            if (e.get("args") or {}).get("hlo_module") == dom)
        ks = sorted(counts.values())
        if ks:
            return max(1, ks[len(ks) // 2])
    if mods:
        by_name: dict[str, list] = collections.defaultdict(list)
        for e in mods:
            by_name[e["name"]].append(e["dur"])
        dom_durs = max(by_name.values(), key=sum)
        return max(1, len(dom_durs))
    return 1


def _timeline_anatomy(tl: dict, ici_size: int,
                      multi_process: bool) -> dict:
    """One device timeline's window totals (µs) + inferred steps.

    Totals are NOT normalized here: the CPU thunk executor rotates its
    worker threads across dispatches, so one device's window can span
    several thread timelines — the caller sums timelines and divides
    by the real device count, never averages per thread.
    """
    from ray_lightning_tpu.comm import audit
    ops = tl["ops"]
    coll_iv, comp_iv = [], []
    by_op: dict[str, float] = collections.defaultdict(float)
    by_link: dict[str, float] = collections.defaultdict(float)
    for e in ops:
        iv = (e["ts"], e["ts"] + e["dur"])
        kind = audit.collective_kind(e.get("name", ""))
        if kind is not None:
            coll_iv.append(iv)
            by_op[kind] += e["dur"]
            by_link[audit.event_link(e.get("args"), ici_size,
                                     multi_process)] += e["dur"]
        else:
            comp_iv.append(iv)
    coll_u = _union(coll_iv)
    comp_u = _union(comp_iv)
    all_events = ops + tl["modules"]
    return {
        "steps": _infer_steps(tl),
        "t0": min(e["ts"] for e in all_events),
        "t1": max(e["ts"] + e["dur"] for e in all_events),
        "compute": _measure(comp_u),
        "collective": sum(by_op.values()),
        "exposed": _measure(_subtract(coll_u, comp_u)),
        "busy": _measure(_union(coll_u + comp_u)),
        "by_op": dict(by_op),
        "by_link": dict(by_link),
        "modules": _timeline_modules(tl),
    }


def _timeline_modules(tl: dict) -> dict:
    by_module: dict[str, float] = collections.defaultdict(float)
    for e in tl["ops"]:
        m = (e.get("args") or {}).get("hlo_module")
        if m:
            by_module[m] += e["dur"]
    if not by_module:
        for e in tl["modules"]:
            by_module[e["name"]] += e["dur"]
    return dict(by_module)


def parse_trace_anatomy(trace_dir: str, *, steps: Optional[int] = None,
                        ici_size: Optional[int] = None,
                        multi_process: Optional[bool] = None,
                        devices: Optional[int] = None) -> StepAnatomy:
    """Parse one rank's capture dir into a :class:`StepAnatomy`.

    ``steps``: dispatches the window covered (None = infer from the
    dominant program's execution count).  ``ici_size``: ranks per host
    block for the ici/dcn split (None = this process's local device
    count, the contiguous-block layout comm/audit.py assumes).
    ``multi_process``: group-less collectives cross DCN when True
    (None = ask jax, False when jax is unavailable).  ``devices``: the
    per-rank normalization denominator — TPU traces have one timeline
    per device process so it's the timeline count, but the CPU thunk
    executor rotates threads across dispatches, so there the local
    device count (asked of jax when None) is the truth and the
    timeline sums are divided by it.

    Raises ``FileNotFoundError`` (no trace file) / ``ValueError`` (no
    device events — e.g. a window that closed before any dispatch).
    """
    path = locate_trace_json(trace_dir) if os.path.isdir(trace_dir) \
        else trace_dir
    timelines = device_timelines(path)
    if not timelines:
        raise ValueError(f"no device op events in {path}")
    local_devices = None
    if ici_size is None or multi_process is None or devices is None:
        try:
            import jax
            local_devices = max(1, jax.local_device_count())
            if ici_size is None:
                ici_size = local_devices
            if multi_process is None:
                multi_process = jax.process_count() > 1
        except Exception:
            ici_size = ici_size or 1
            multi_process = bool(multi_process)
    source = "xla-device" if any("/device:" in tl["device"]
                                 for tl in timelines) else "cpu-host"
    rows = [_timeline_anatomy(tl, ici_size, multi_process)
            for tl in timelines]
    if devices is None:
        if source == "xla-device" or local_devices is None:
            devices = len(rows)
        else:
            devices = min(local_devices, len(rows))
    n_dev = max(1, int(devices))
    n_steps = steps or max(r["steps"] for r in rows)
    # per-device, per-step normalization: SUM over timelines (one
    # device's work may span several executor threads), divide by the
    # device count and the window's steps
    norm = 1e-6 / (n_dev * max(1, n_steps))

    def total(key: str) -> float:
        return sum(r[key] for r in rows)

    a = StepAnatomy(steps=n_steps, devices=n_dev, source=source)
    # wall: the window's global extent — SPMD devices run in lockstep,
    # so the extent per step IS the per-device step wall
    extent = max(r["t1"] for r in rows) - min(r["t0"] for r in rows)
    a.wall_s = extent * 1e-6 / max(1, n_steps)
    a.compute_s = total("compute") * norm
    a.collective_s = total("collective") * norm
    a.exposed_s = total("exposed") * norm
    a.host_s = max(0.0, a.wall_s - total("busy") * norm)
    a.bubble_fraction = (a.host_s / a.wall_s) if a.wall_s > 0 else 0.0
    for r in rows:
        for k, v in r["by_op"].items():
            a.collective_by_op[k] = a.collective_by_op.get(k, 0.0) \
                + v * norm
        for k, v in r["by_link"].items():
            a.collective_by_link[k] = a.collective_by_link.get(k, 0.0) \
                + v * norm
    mod_tot: dict[str, float] = collections.defaultdict(float)
    for r in rows:
        for k, v in r["modules"].items():
            mod_tot[k] += v * norm
    a.modules = dict(sorted(mod_tot.items(),
                            key=lambda kv: -kv[1])[:8])
    return a


def parse_anatomy_or_none(trace_dir: "str | None", **kw) -> Optional[dict]:
    """Compact anatomy dict, or None when the capture is missing or
    unparseable (profiler-less backends, empty windows) — the shared
    never-raise recipe for bench/status surfaces."""
    if not trace_dir:
        return None
    try:
        return parse_trace_anatomy(trace_dir, **kw).as_dict()
    except Exception as e:
        _log.debug("anatomy parse skipped for %s: %s", trace_dir, e)
        return None


def profile_dir_anatomy(last_dir: "str | None") -> Optional[dict]:
    """Parsed anatomy for a completed ``POST /debug/profile`` window:
    ``{rank_label: anatomy_dict}`` over the window's ``rank<k>/``
    subdirs (or a single ``"0"`` entry when the capture has no rank
    subdirs).  None when nothing parses."""
    if not last_dir or not os.path.isdir(last_dir):
        return None
    out: dict[str, dict] = {}
    subs = sorted(d for d in os.listdir(last_dir)
                  if d.startswith("rank")
                  and os.path.isdir(os.path.join(last_dir, d)))
    if subs:
        for d in subs:
            a = parse_anatomy_or_none(os.path.join(last_dir, d))
            if a is not None:
                out[d[len("rank"):]] = a
    else:
        a = parse_anatomy_or_none(last_dir)
        if a is not None:
            out["0"] = a
    return out or None


# -- synthetic-trace fixture (tests + selfcheck golden) --------------------

def write_synthetic_trace(trace_dir: str, ops: list[dict],
                          modules: Optional[list[dict]] = None,
                          device: str = "/device:TPU:0") -> str:
    """Write a minimal TPU-layout ``*.trace.json.gz`` capture under
    ``trace_dir``: one device process with "XLA Ops"/"XLA Modules"
    tracks.  ``ops``/``modules``: dicts with ``name``, ``ts``, ``dur``
    (µs) and optional ``args``.  Returns the trace path.  This is the
    golden fixture that pins the exposed-comm overlap math without a
    profiler in the loop."""
    pid, ops_tid, mod_tid = 1, 1, 2
    events = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": device}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": ops_tid,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": mod_tid,
         "args": {"name": "XLA Modules"}},
    ]
    for e in ops:
        events.append({"ph": "X", "pid": pid, "tid": ops_tid,
                       "name": e["name"], "ts": float(e["ts"]),
                       "dur": float(e["dur"]),
                       "args": e.get("args") or {}})
    for e in modules or ():
        events.append({"ph": "X", "pid": pid, "tid": mod_tid,
                       "name": e["name"], "ts": float(e["ts"]),
                       "dur": float(e["dur"]),
                       "args": e.get("args") or {}})
    out_dir = os.path.join(trace_dir, "plugins", "profile", "synthetic")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "synthetic.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


# -- auto-capture: cadence-armed windows, parsed locally -------------------

def anatomy_item(rank: int, anatomy: dict,
                 capture_dir: Optional[str] = None) -> dict:
    """Wire item carrying one rank's compact anatomy dict (rides the
    same worker→driver queue as span batches and metrics windows).
    ``capture_dir`` (incident-armed windows only) links the preserved
    raw capture so the incident report can reference its evidence."""
    item = {TELEMETRY_KEY: 1, "kind": "anatomy", "rank": rank,
            "ts": time.time(), "anatomy": anatomy}
    if capture_dir:
        item["dir"] = capture_dir
    return item


class AnatomyController:
    """Worker-side cadence capture: every ``every_n`` dispatches, arm a
    ``window``-dispatch ``jax.profiler`` trace through the same
    :class:`~ray_lightning_tpu.telemetry.tracing.WorkerProfiler` the
    on-demand profile controllers use, parse THIS rank's capture
    locally, publish ``rlt_anatomy_*`` gauges + the measured exposed
    comm into the local metrics registry, ship the compact dict via
    ``sink``, and delete the capture dir.  Failures disarm the window
    and never raise into the train loop."""

    def __init__(self, rank: int, every_n: int, window: int,
                 sink: Optional[Callable[[dict], None]] = None):
        from ray_lightning_tpu.telemetry.tracing import WorkerProfiler
        self.rank = int(rank)
        self.every_n = max(1, int(every_n))
        self.window = max(1, int(window))
        self.sink = sink
        self.last: Optional[dict] = None
        self.windows = 0
        self._dispatches = 0
        self._window_id = 0
        self._dir: Optional[str] = None
        self._profiler = WorkerProfiler(rank=self.rank)
        #: pending off-cadence arm ({"tag", "steps"}) — incident plane
        self._forced: Optional[dict] = None
        #: tag of the window currently capturing (None = cadence window)
        self._active_tag: Optional[str] = None
        # driver→worker arm channel: incident manager writes the arm
        # file (incident.py write_arm_file), every rank polls it here —
        # same shared-filesystem idiom as RLT_PROFILE_CONTROL
        self._arm_watcher = None
        ctl_path = os.environ.get(INCIDENT_CONTROL_ENV)
        if ctl_path:
            from ray_lightning_tpu.telemetry.incident import ArmWatcher
            self._arm_watcher = ArmWatcher(ctl_path)

    def arm_now(self, tag: Optional[str] = None,
                steps: Optional[int] = None) -> None:
        """Force the NEXT tick to open a window regardless of cadence —
        the incident plane's "capture evidence after detection" hook.
        The window's capture dir is preserved and linked on the wire
        item instead of deleted."""
        self._forced = {"tag": tag or "incident",
                        "steps": int(steps) if steps else None}

    def tick(self) -> None:
        """Once per dispatch (loop-engine hook, next to profile_tick)."""
        prof = self._profiler
        if prof._active:
            prof.note_step()
            if not prof._active:       # window just closed: parse + ship
                self._finish()
            return
        if self._arm_watcher is not None and self._forced is None:
            ctl = self._arm_watcher.poll()
            if ctl is not None:
                self.arm_now(tag=f"incident-{ctl.get('id')}",
                             steps=ctl.get("steps"))
        self._dispatches += 1
        forced, self._forced = self._forced, None
        if forced is None and self._dispatches % self.every_n:
            return
        self._window_id += 1
        d = tempfile.mkdtemp(prefix="rlt_anatomy_")
        self._dir = d
        steps = (forced or {}).get("steps") or self.window
        prof.maybe_start({"id": f"anatomy-{self.rank}-{self._window_id}",
                          "steps": steps, "dir": d})
        if not prof._active:
            # another window owns the profiler (e.g. an on-demand
            # POST /debug/profile capture) — skip to the next cadence;
            # a forced (incident) arm retries on the next dispatch
            shutil.rmtree(d, ignore_errors=True)
            self._dir = None
            self._forced = forced
        else:
            self._active_tag = (forced or {}).get("tag")

    def _finish(self) -> None:
        d, self._dir = self._dir, None
        tag, self._active_tag = self._active_tag, None
        try:
            anatomy = parse_anatomy_or_none(
                os.path.join(d, f"rank{self.rank}"))
            if anatomy is None:
                return
            self.last = anatomy
            self.windows += 1
            self._publish_metrics(anatomy)
            if self.sink is not None:
                # incident-armed windows keep + link their raw capture
                # (the evidence dir the report references); cadence
                # windows ship the compact dict only and delete it
                self.sink(anatomy_item(
                    self.rank, anatomy,
                    capture_dir=d if tag else None))
        except Exception:   # anatomy must never break the train loop
            _log.debug("anatomy window dropped", exc_info=True)
        finally:
            if d and not tag:
                shutil.rmtree(d, ignore_errors=True)

    def _publish_metrics(self, anatomy: dict) -> None:
        from ray_lightning_tpu.telemetry import metrics as _metrics
        reg = _metrics.get_registry()
        if reg is None:
            return
        reg.gauge("rlt_anatomy_compute_seconds").set(anatomy["compute_s"])
        reg.gauge("rlt_anatomy_collective_seconds").set(
            anatomy["collective_s"])
        reg.gauge("rlt_anatomy_exposed_seconds").set(anatomy["exposed_s"])
        reg.gauge("rlt_anatomy_host_seconds").set(anatomy["host_s"])
        reg.gauge("rlt_anatomy_dcn_seconds").set(
            anatomy["collective_by_link"].get("dcn", 0.0))
        reg.counter("rlt_anatomy_windows_total").inc(1)
        # the exposed-comm gauge's MEASURED source (satellite: the
        # wall-minus-floor proxy only feeds it in bench legs)
        _metrics.note_exposed_comm(anatomy["exposed_s"], source="anatomy")

    def stop(self) -> None:
        """Teardown: abandon any mid-capture window (a partial trace is
        not an anatomy)."""
        self._profiler.stop()
        if self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None


_controller: Optional[AnatomyController] = None


def enable_anatomy(rank: int, every_n: int, window: int = DEFAULT_WINDOW,
                   sink: Optional[Callable[[dict], None]] = None
                   ) -> AnatomyController:
    """Install the process-wide auto-capture controller (plugins call
    this when TelemetryConfig/RLT_ANATOMY* arm a cadence)."""
    global _controller
    disable_anatomy()
    _controller = AnatomyController(rank, every_n, window, sink=sink)
    return _controller


def disable_anatomy() -> None:
    global _controller
    if _controller is not None:
        _controller.stop()
    _controller = None


def get_anatomy_controller() -> Optional[AnatomyController]:
    return _controller


def anatomy_tick() -> None:
    """Loop-engine hook, once per dispatch.  Free (one global check)
    when no controller is armed."""
    ctl = _controller
    if ctl is None:
        return
    try:
        ctl.tick()
    except Exception:    # capture must never break the train loop
        _log.debug("anatomy tick failed", exc_info=True)


__all__ = [
    "ANATOMY_ENV",
    "ANATOMY_EVERY_ENV",
    "ANATOMY_STEPS_ENV",
    "INCIDENT_CONTROL_ENV",
    "DEFAULT_EVERY_N",
    "DEFAULT_WINDOW",
    "StepAnatomy",
    "locate_trace_json",
    "read_trace",
    "device_track_events",
    "device_timelines",
    "bucket_of",
    "parse_trace_anatomy",
    "parse_anatomy_or_none",
    "profile_dir_anatomy",
    "write_synthetic_trace",
    "anatomy_item",
    "AnatomyController",
    "enable_anatomy",
    "disable_anatomy",
    "get_anatomy_controller",
    "anatomy_tick",
]
