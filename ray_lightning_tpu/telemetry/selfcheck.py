"""Trace-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the comm/compile/serve/elastic selfchecks: cheap,
deterministic, no pytest, no jax backend — validates the invariants
that would otherwise only fail deep inside a live fleet:

1. span-record schema: what spans.py emits is exactly what the
   aggregator/flight/tracing consumers key on;
2. trace-context round-trip: a driver request span + worker spans
   (single ``trace`` attr and the decode's ``traces`` fan-out map)
   reassemble into one tree, and the tenant breakdown attributes the
   phases;
3. flight-recorder bounded-size invariant: rings never exceed their
   capacity no matter how much is ingested, and a dump names the
   rank's last span;
4. profile-controller state machine: pending→active→done, second POST
   rejected while armed;
5. every new trace-plane instrument name is Prometheus-clean
   (the PR 2 lint);
6. anatomy plane (telemetry/anatomy.py): the parser on the golden
   synthetic fixture — overlap math (fully-overlapped → ~0 exposed,
   serialized → exposed ≈ collective), the wall = compute + exposed +
   host identity, the compact-dict schema — plus the TelemetryConfig
   anatomy knobs round-tripping through ``worker_env`` / RLT_ANATOMY*;
7. goodput plane (telemetry/goodput.py): the partition is exhaustive
   and disjoint per kind, ``sum(buckets) == run_wall`` holds on a
   synthetic ledger (including the overshoot-scaling path, replay
   reattribution and fleet aggregation), the ``rlt_goodput_*`` /
   ``rlt_mfu`` names are Prometheus-clean, and the RLT_GOODPUT* knobs
   round-trip through ``worker_env``.
"""

from __future__ import annotations


def _check_span_schema() -> None:
    from ray_lightning_tpu.telemetry import spans
    from ray_lightning_tpu.telemetry import tracing
    spans.enable(rank=5, sink=None, flush_every=None)
    try:
        with spans.span("step", step=3, trace="abc123"):
            pass
        (rec,) = spans.drain()
        assert rec["t"] == "span" and rec["name"] == "step"
        assert rec["rank"] == 5 and rec["depth"] == 0
        assert rec["dur"] >= 0 and isinstance(rec["ts"], float)
        assert rec["attrs"] == {"step": 3, "trace": "abc123"}
    finally:
        spans.disable()
    synthetic = tracing.span_record("request", 100.0, 100.5,
                                    trace="abc123", tenant="t")
    assert synthetic["rank"] == -1 and synthetic["dur"] == 0.5
    assert set(synthetic) >= {"t", "name", "ts", "dur", "rank", "depth"}
    print("telemetry selfcheck: span-record schema OK")


def _check_trace_roundtrip() -> None:
    import tempfile
    from ray_lightning_tpu.telemetry import tracing
    from ray_lightning_tpu.telemetry.aggregator import TelemetryAggregator
    agg = TelemetryAggregator(tempfile.mkdtemp(prefix="rlt_sc_"))
    tid = tracing.mint_trace_id()
    other = tracing.mint_trace_id()
    assert tid != other and len(tid) == 16
    agg.ingest_records(-1, [
        tracing.span_record("queue_wait", 10.0, 10.2, trace=tid,
                            tenant="alice"),
        tracing.span_record("request", 10.0, 11.0, trace=tid,
                            tenant="alice", status="ok", tokens=4,
                            queue_s=0.2, ttft_s=0.5, tpot_s=0.1)])
    agg.ingest_records(0, [
        tracing.span_record("prefill", 10.2, 10.5, rank=0, trace=tid,
                            bucket=16),
        tracing.span_record("decode", 10.5, 10.6, rank=0,
                            traces={0: tid, 1: other})])
    trees = agg.request_trees()
    assert set(trees) == {tid, other}
    names = [r["name"] for r in trees[tid]]
    assert names == ["queue_wait", "request", "prefill", "decode"], names
    assert trees[other] == [trees[tid][-1]]     # fan-out span is shared
    bd = agg.tenant_breakdown()["alice"]
    assert bd["requests"] == 1 and bd["failed"] == 0
    assert bd["queue_wait_p50_ms"] == 200.0
    assert bd["prefill_p50_ms"] == 300.0
    assert bd["decode_p50_ms"] == 500.0          # 1.0s total - 0.5 ttft
    print(f"telemetry selfcheck: trace round-trip OK "
          f"({len(trees[tid])} spans reassembled)")


def _check_flight_bounded() -> None:
    import json
    import os
    import tempfile
    from ray_lightning_tpu.telemetry.flight import FlightRecorder
    out = tempfile.mkdtemp(prefix="rlt_sc_flight_")
    fr = FlightRecorder(out, span_capacity=16, beat_capacity=4)
    for i in range(500):
        fr.note_records(1, [{"t": "span", "name": f"step{i}",
                             "ts": float(i), "dur": 0.01, "rank": 1}])
        fr.note_heartbeat({"rank": 1, "pid": 9, "wall": float(i),
                           "last_span": f"step{i}"})
    # the bounded-size invariant: rings NEVER exceed capacity
    assert len(fr._records[1]) == 16
    assert len(fr._beats[1]) == 4
    path = fr.dump(1, "selfcheck")
    assert path and os.path.basename(path) == "flight_1.json"
    doc = json.load(open(path))
    assert doc["rank"] == 1 and doc["cause"] == "selfcheck"
    assert doc["last_span"] == "step499"         # newest survives
    assert len(doc["spans"]) == 16
    print("telemetry selfcheck: flight-recorder rings bounded "
          "(16/500 spans kept, newest-first)")


def _check_profile_controller() -> None:
    import tempfile
    from ray_lightning_tpu.telemetry.tracing import ServeProfileController
    ctl = ServeProfileController(tempfile.mkdtemp(prefix="rlt_sc_prof_"))
    assert ctl.status()["state"] == "idle"
    first = ctl.request(3)
    assert first["accepted"] and ctl.status()["state"] == "pending"
    assert not ctl.request(1)["accepted"]        # one window at a time
    pending = ctl.take_pending()
    assert pending["steps"] == 3 and ctl.take_pending() is None
    for _ in range(3):
        assert ctl.status()["state"] == "active"
        ctl.note_step()
    st = ctl.status()
    assert st["state"] == "done" and st["last_dir"] == pending["dir"]
    assert ctl.request(1)["accepted"]            # re-armable when done
    print("telemetry selfcheck: profile controller "
          "pending->active->done OK")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import (
        CORE_METRICS,
        validate_metric_name,
    )
    anatomy_names = [n for n in CORE_METRICS if n.startswith("rlt_anatomy_")]
    assert {"rlt_anatomy_compute_seconds", "rlt_anatomy_collective_seconds",
            "rlt_anatomy_exposed_seconds", "rlt_anatomy_host_seconds",
            "rlt_anatomy_dcn_seconds", "rlt_anatomy_windows_total"} \
        <= set(anatomy_names)
    for name in ("rlt_spans_dropped_total",
                 "rlt_serve_queue_wait_seconds",
                 "rlt_profile_windows_total",
                 *anatomy_names):
        validate_metric_name(name)
    print("telemetry selfcheck: trace-plane + anatomy metric names "
          "Prometheus-clean")


def _check_anatomy_parser() -> None:
    """Golden synthetic fixture pins the exposed-comm overlap math and
    the wall = compute + exposed + host identity."""
    import tempfile
    from ray_lightning_tpu.telemetry import anatomy

    # serialized: 10ms compute then 4ms all-reduce -> exposed ≈ collective
    d = tempfile.mkdtemp(prefix="rlt_sc_anat_")
    anatomy.write_synthetic_trace(d, ops=[
        {"name": "fusion.1", "ts": 0, "dur": 10_000},
        {"name": "all-reduce.1", "ts": 10_000, "dur": 4_000},
    ], modules=[{"name": "jit_step", "ts": 0, "dur": 14_000}])
    a = anatomy.parse_trace_anatomy(d, steps=1, ici_size=1,
                                    multi_process=False)
    assert abs(a.exposed_s - 0.004) < 1e-9, a.exposed_s
    assert abs(a.collective_s - 0.004) < 1e-9
    assert a.collective_by_op == {"all-reduce": 0.004}
    assert a.collective_by_link == {"ici": 0.004}

    # fully overlapped: the same all-reduce inside the compute span ->
    # ~0 exposed; group-less on a multi-process mesh charges DCN
    d = tempfile.mkdtemp(prefix="rlt_sc_anat_")
    anatomy.write_synthetic_trace(d, ops=[
        {"name": "fusion.1", "ts": 0, "dur": 10_000},
        {"name": "all-reduce.1", "ts": 2_000, "dur": 4_000},
    ])
    a = anatomy.parse_trace_anatomy(d, steps=1, ici_size=1,
                                    multi_process=True)
    assert a.exposed_s == 0.0 and abs(a.collective_s - 0.004) < 1e-12
    assert a.collective_by_link == {"dcn": 0.004}

    # identity + compact-dict schema (the wire/bench form)
    assert abs(a.wall_s - (a.compute_s + a.exposed_s + a.host_s)) < 1e-12
    doc = a.as_dict()
    assert {"steps", "devices", "wall_s", "compute_s", "collective_s",
            "exposed_s", "host_s", "collective_by_op",
            "collective_by_link", "bubble_fraction", "modules",
            "source"} <= set(doc)
    assert doc["source"] == "xla-device"
    print("telemetry selfcheck: anatomy overlap math OK "
          "(serialized exposed==collective, overlapped exposed==0, "
          "wall identity holds)")


def _check_anatomy_config_roundtrip() -> None:
    """TelemetryConfig anatomy knobs → worker_env → env resolution."""
    import os
    from ray_lightning_tpu.telemetry import TelemetryConfig, anatomy

    cfg = TelemetryConfig(anatomy_every_n_steps=12, anatomy_steps=3)
    env = cfg.worker_env()
    assert env == {anatomy.ANATOMY_EVERY_ENV: "12",
                   anatomy.ANATOMY_STEPS_ENV: "3"}, env
    saved = {k: os.environ.get(k) for k in
             (anatomy.ANATOMY_ENV, anatomy.ANATOMY_EVERY_ENV,
              anatomy.ANATOMY_STEPS_ENV)}
    try:
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(env)
        # a default config in the worker resolves the SAME cadence
        assert TelemetryConfig().resolved_anatomy() == (12, 3)
        for k in env:
            os.environ.pop(k)
        assert TelemetryConfig().resolved_anatomy()[0] is None
        os.environ[anatomy.ANATOMY_ENV] = "1"
        assert TelemetryConfig().resolved_anatomy() == \
            (anatomy.DEFAULT_EVERY_N, anatomy.DEFAULT_WINDOW)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print("telemetry selfcheck: anatomy config round-trip via "
          "worker_env/RLT_ANATOMY* OK")


def _check_goodput_partition() -> None:
    """Goodput-plane invariants (telemetry/goodput.py): the partition
    is exhaustive + disjoint per kind, the ``sum(buckets) == run_wall``
    identity holds on a synthetic ledger — including the overshoot
    path, where instrumented time exceeds the wall and every bucket
    scales down — and replay reattribution moves seconds without
    touching the wall."""
    from ray_lightning_tpu.telemetry import goodput as gp

    # partition shape: one useful bucket per kind, 'other' residual,
    # no duplicates, no cross-kind leakage of fit-only buckets
    for kind, buckets in gp.BUCKETS.items():
        assert len(set(buckets)) == len(buckets), f"{kind}: dup bucket"
        assert "other" in buckets, f"{kind}: no residual bucket"
        assert gp.USEFUL_BUCKET[kind] in buckets
    assert "replay" not in gp.SERVE_BUCKETS
    assert "decode" not in gp.FIT_BUCKETS

    # identity on a synthetic fit ledger (controlled clock)
    t = [0.0]
    ledger = gp.GoodputLedger("fit", device_tflops=100.0, devices=4,
                              clock=lambda: t[0]).start()
    ledger.add("compile", 2.0)
    ledger.add("init", 0.5)
    for _ in range(10):
        ledger.note_step(0.3)
    ledger.add("data_wait", 0.2)
    ledger.set_flops_per_step(6e12)
    t[0] = 8.0
    doc = ledger.finalize()
    assert gp.check_identity(doc), doc
    assert doc["buckets"]["step"] == 3.0 and doc["steps"] == 10
    assert abs(doc["buckets"]["other"] - 2.3) < 1e-9
    assert doc["mfu"] is not None and 0 < doc["mfu"] < 1

    # overshoot: instrumented 6s against a 3s wall still closes exactly
    over = gp.GoodputLedger("serve")
    over.note_step(4.0)
    over.add("prefill", 2.0)
    doc = over.finalize(3.0)
    assert gp.check_identity(doc), doc
    assert abs(doc["buckets"]["decode"] - 2.0) < 1e-9

    # replay reattribution: seconds move step->replay, wall untouched
    fit = gp.GoodputLedger("fit")
    for _ in range(10):
        fit.note_step(0.5)
    doc = fit.finalize(6.0)
    re = gp.reattribute_replay(doc, 4)
    assert gp.check_identity(re), re
    assert abs(re["buckets"]["replay"] - 2.0) < 1e-9
    assert re["run_wall_s"] == doc["run_wall_s"]

    # fleet aggregation: extra buckets extend wall AND bucket
    agg = gp.aggregate([doc, doc], extra_buckets={"recovery": 1.5})
    assert gp.check_identity(agg), agg
    assert abs(agg["buckets"]["recovery"] - 1.5) < 1e-9
    print("telemetry selfcheck: goodput partition exhaustive+disjoint, "
          "identity holds (incl. overshoot + replay + aggregate)")


def _check_goodput_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import (
        CORE_METRICS,
        validate_metric_name,
    )
    names = ("rlt_goodput_seconds", "rlt_goodput_fraction", "rlt_mfu")
    assert set(names) <= set(CORE_METRICS), "goodput gauges not core"
    for name in names:
        validate_metric_name(name)
    print("telemetry selfcheck: goodput metric names Prometheus-clean")


def _check_goodput_config_roundtrip() -> None:
    """TelemetryConfig goodput knobs → worker_env → env resolution."""
    import os
    from ray_lightning_tpu.telemetry import TelemetryConfig, goodput

    saved = {k: os.environ.get(k) for k in
             (goodput.GOODPUT_ENV, goodput.GOODPUT_TFLOPS_ENV)}
    try:
        for k in saved:
            os.environ.pop(k, None)
        # default: armed, no env emitted (worker_env stays minimal)
        cfg = TelemetryConfig()
        assert cfg.resolved_goodput() is True
        assert goodput.GOODPUT_ENV not in cfg.worker_env()
        # explicit disarm ships RLT_GOODPUT=0 and the worker resolves it
        cfg = TelemetryConfig(goodput=False, goodput_tflops=275.0)
        env = cfg.worker_env()
        assert env[goodput.GOODPUT_ENV] == "0"
        assert env[goodput.GOODPUT_TFLOPS_ENV] == "275.0"
        os.environ.update(env)
        worker = TelemetryConfig()
        assert worker.resolved_goodput() is False
        assert worker.resolved_goodput_tflops() == 275.0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print("telemetry selfcheck: goodput config round-trip via "
          "worker_env/RLT_GOODPUT* OK")


def _check_incident_detector() -> None:
    """Detector invariants: no false trip on stationary noise, a
    MONOTONE breach predicate (a worse regression can never be judged
    healthier), the patience/cooldown state machine."""
    from ray_lightning_tpu.telemetry.incident import Detector, DetectorConfig

    t = [0.0]
    cfg = DetectorConfig(direction="high", warmup=8, patience=2,
                         cooldown_s=5.0)
    det = Detector("step_wall_s", 0, cfg, clock=lambda: t[0])
    # stationary-but-noisy series: never trips
    for i in range(30):
        t[0] += 1.0
        val = 0.1 + 0.002 * ((i * 7) % 5)
        assert det.observe(val, ts=t[0]) is None, (i, val)
    assert not det.tripped and det.trips == 0
    band = det.band()
    assert band is not None
    med, lo, hi = band
    assert lo <= med <= hi
    # monotone breach predicate: once a value breaches, every larger
    # value breaches too (probe an increasing ladder, flags must be
    # sorted False..True)
    probes = [hi * f for f in (0.25, 0.9, 0.999, 1.001, 1.5, 10.0, 1e6)]
    flags = [det.breaches(v) for v in probes]
    assert flags == sorted(flags), list(zip(probes, flags))
    assert flags[-1] is True and flags[0] is False
    # low-direction detector breaches on dips, not spikes
    low = Detector("goodput_fraction", -1,
                   DetectorConfig(direction="low", warmup=4, patience=1),
                   clock=lambda: t[0])
    for _ in range(6):
        t[0] += 1.0
        low.observe(0.9, ts=t[0])
    assert low.breaches(0.1) and not low.breaches(2.0)
    # patience: one breached sample is noise, the Nth is an incident
    t[0] += 1.0
    assert det.observe(50 * med, ts=t[0]) is None
    t[0] += 1.0
    ev = det.observe(50 * med, ts=t[0])
    assert ev is not None and ev["transition"] == "opened", ev
    assert det.tripped and det.trips == 1
    # close needs `patience` consecutive healthy samples
    t[0] += 1.0
    assert det.observe(med, ts=t[0]) is None
    t[0] += 1.0
    ev = det.observe(med, ts=t[0])
    assert ev is not None and ev["transition"] == "closed", ev
    assert not det.tripped
    # cooldown: breaches inside the window never accumulate a streak
    for _ in range(4):
        t[0] += 1.0   # still inside cooldown_s=5.0
        assert det.observe(50 * med, ts=t[0]) is None
    assert not det.tripped and det.trips == 1
    # after cooldown the detector re-arms
    t[0] += cfg.cooldown_s + 1.0
    det.observe(50 * med, ts=t[0])
    t[0] += 1.0
    ev = det.observe(50 * med, ts=t[0])
    assert ev is not None and ev["transition"] == "opened"
    assert det.trips == 2
    print("telemetry selfcheck: incident detector monotone + "
          "patience/cooldown state machine OK")


def _check_incident_schema() -> None:
    """IncidentManager end-to-end in-process: a spike opens an incident,
    the dump matches INCIDENT_SCHEMA_KEYS, recovery closes it, and the
    divergence path carries its explicit verdict."""
    import json
    import os
    import tempfile
    from ray_lightning_tpu.telemetry.incident import (
        INCIDENT_SCHEMA_KEYS,
        IncidentConfig,
        IncidentManager,
    )

    out = tempfile.mkdtemp(prefix="rlt_sc_incident_")
    t = [0.0]
    cfg = IncidentConfig(warmup=4, patience=2, cooldown_s=0.0)
    mgr = IncidentManager(out, cfg=cfg, run_kind="fit",
                          clock=lambda: t[0])
    for i in range(12):
        t[0] += 1.0
        mgr.note_sample("step_wall_s", 1, 0.1 + 0.001 * (i % 3),
                        ts=100.0 + t[0])
    assert not mgr.open_incidents
    for _ in range(2):
        t[0] += 1.0
        mgr.note_sample("step_wall_s", 1, 9.0, ts=100.0 + t[0])
    st = mgr.stats()
    assert st["enabled"] and st["total"] == 1, st
    assert st["open"] and st["open"][0]["series"] == "step_wall_s"
    assert st["open"][0]["rank"] == 1
    path = st["open"][0]["path"]
    assert path and os.path.exists(path), path
    with open(path) as f:
        doc = json.load(f)
    assert set(doc) == set(INCIDENT_SCHEMA_KEYS), sorted(doc)
    assert doc["state"] == "open" and doc["closed_ts"] is None
    assert doc["trigger"]["value"] == 9.0
    # recovery closes the incident and re-dumps with closed_ts set
    for _ in range(2):
        t[0] += 1.0
        mgr.note_sample("step_wall_s", 1, 0.1, ts=100.0 + t[0])
    assert not mgr.open_incidents
    with open(path) as f:
        doc = json.load(f)
    assert doc["state"] == "closed" and doc["closed_ts"] is not None
    # plan-divergence incidents carry their explicit verdict
    inc = mgr.note_divergence({"ratio": 2.0, "modeled_comm_s": 1.0,
                               "exposed_comm_s": 2.0})
    assert inc is not None and inc.verdict == "replan-recommended"
    assert mgr.note_divergence({"ratio": 1.1}) is None  # inside band
    names = {m["name"] for m in mgr.metric_samples()}
    assert names == {"rlt_incident_total", "rlt_incident_active"}, names
    print("telemetry selfcheck: incident open/close round-trip, dump "
          "schema matches INCIDENT_SCHEMA_KEYS")


def _check_incident_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import (
        CORE_METRICS,
        UNITLESS_GAUGES,
        validate_metric_name,
    )
    names = ("rlt_incident_total", "rlt_incident_active")
    assert set(names) <= set(CORE_METRICS), "incident metrics not core"
    assert "rlt_incident_active" in UNITLESS_GAUGES
    for name in names:
        validate_metric_name(name)
    print("telemetry selfcheck: incident metric names Prometheus-clean")


def _main(argv: list) -> int:
    _check_span_schema()
    _check_trace_roundtrip()
    _check_flight_bounded()
    _check_profile_controller()
    _check_metric_names()
    _check_anatomy_parser()
    _check_anatomy_config_roundtrip()
    _check_goodput_partition()
    _check_goodput_metric_names()
    _check_goodput_config_roundtrip()
    _check_incident_detector()
    _check_incident_schema()
    _check_incident_metric_names()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
