"""Per-rank metrics plane: typed instruments + device/collective accounting.

PR 1 gave the run a *trace* plane (spans, heartbeats, Perfetto export);
this module adds the *numeric* plane standard monitoring infra can
scrape and alert on (TorchTitan treats per-rank throughput/memory
metrics as a production requirement — PAPERS.md):

- A process-wide :class:`MetricsRegistry` of typed instruments
  (:class:`Counter` / :class:`Gauge` / :class:`Histogram` with fixed
  buckets).  Names are validated at registration: ``rlt_``-prefixed,
  Prometheus-clean (``^rlt_[a-z0-9_]+$``) and carrying a unit suffix
  (``_bytes`` / ``_seconds`` / ``_total``), so the driver's ``/metrics``
  exposition never emits an unscrapable series.
- Collective byte accounting.  Host-side collectives
  (:func:`ray_lightning_tpu.parallel.gather.fetch_tree`) record bytes +
  seconds directly (:func:`record_collective`).  Collectives *compiled
  into* the step program (ring attention's ppermute rotation, the
  pipeline's activation hops, the ZeRO reduce-scatter/all-gather the
  sharding annotations imply) can only be observed at trace time — they
  register a bytes-per-execution cost (:func:`note_traced_collective`)
  that :func:`on_step` multiplies by executed steps, so the counters
  track actual traffic, not trace count.
- Device state sampling: a window pump thread reads
  ``jax.local_devices()[i].memory_stats()`` into current/peak HBM
  gauges each window and flushes the full cumulative snapshot to the
  sink (the worker→driver queue under cluster backends, the aggregator
  directly in-process).  Backends without memory stats (virtual CPU
  devices) report 0 so the gauges still exist to scrape.

Disabled is the default: every entry point checks one module global and
returns; hot loops keep their instrumentation unconditionally.  Like
spans.py, nothing heavy imports at module load (worker_main touches this
package before jax exists); jax is imported lazily inside the sampler.
"""

from __future__ import annotations

import logging
import re
import sys
import threading
import time
from typing import Any, Callable, Optional

from ray_lightning_tpu.telemetry import spans
from ray_lightning_tpu.telemetry.aggregator import TELEMETRY_KEY

_log = logging.getLogger(__name__)

#: Prometheus-clean instrument name: rlt_ prefix, lowercase, and a unit
#: suffix so the exposition is self-describing (satellite lint contract)
NAME_RE = re.compile(r"^rlt_[a-z0-9_]+$")
UNIT_SUFFIXES = ("_bytes", "_seconds", "_total")

#: unitless boolean gauges (Prometheus "up"-style) explicitly exempt
#: from the unit-suffix rule — a 0/1 liveness verdict has no unit to
#: carry.  Keep this list short and deliberate.
UNITLESS_GAUGES = ("rlt_worker_alive", "rlt_recovery_mode",
                   "rlt_goodput_fraction", "rlt_mfu",
                   "rlt_incident_active",
                   # accepted/drafted ratio in [0, 1] — a rate carries
                   # no unit (serve/scheduler.py speculative decode)
                   "rlt_spec_acceptance_rate")

#: step-time histogram bounds (seconds): sub-ms dispatch latency up to
#: multi-second giant-model steps
STEP_TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: every instrument the framework itself registers (kept in one place so
#: the name lint — ``python -m ray_lightning_tpu.telemetry.metrics
#: --check-names`` and tests/test_metrics.py — covers the full surface)
CORE_METRICS = (
    "rlt_steps_total",
    "rlt_compiles_total",
    "rlt_compile_seconds_total",
    "rlt_compile_cache_hits_total",
    "rlt_compile_cache_misses_total",
    "rlt_time_to_first_step_seconds",
    "rlt_step_time_seconds",
    "rlt_hbm_bytes",
    "rlt_hbm_peak_bytes",
    "rlt_collective_bytes_total",
    "rlt_collective_ops_total",
    "rlt_collective_seconds_total",
    # comm plane (comm/collectives.py hierarchical sync): bytes the
    # step's declared collectives push across the slow DCN tier, and
    # the exposed (non-overlapped) comm seconds per step.  The exposed
    # gauge carries a ``source`` label naming its provenance:
    # ``anatomy`` = measured from trace-event overlap on the device
    # timelines during instrumented runs (telemetry/anatomy.py — the
    # number of record); ``wall_minus_floor`` = bench_comm.py's
    # differential proxy (leg wall minus the same-process fp32 floor,
    # which also pays codec quantize/dequantize compute)
    "rlt_comm_dcn_bytes_total",
    "rlt_comm_exposed_seconds",
    # anatomy plane (telemetry/anatomy.py AnatomyController): measured
    # per-step device-time split from cadence-armed profiler windows,
    # each rank parsing its own capture — compute / collective
    # (overlap-inclusive) / exposed (trace-measured non-overlapped) /
    # host gap, the DCN-link share, and completed windows
    "rlt_anatomy_compute_seconds",
    "rlt_anatomy_collective_seconds",
    "rlt_anatomy_exposed_seconds",
    "rlt_anatomy_host_seconds",
    "rlt_anatomy_dcn_seconds",
    "rlt_anatomy_windows_total",
    "rlt_data_wait_seconds_total",
    "rlt_telemetry_dropped_total",
    # trace plane (telemetry/tracing.py + serve per-request tracing):
    # alertable span-ring data loss + request-phase latency instruments
    "rlt_spans_dropped_total",
    "rlt_serve_queue_wait_seconds",
    "rlt_profile_windows_total",
    # elastic plane (elastic/snapshot.py + the driver-side fleet
    # health series the aggregator synthesizes)
    "rlt_snapshot_total",
    "rlt_snapshot_skipped_total",
    "rlt_snapshot_failed_total",
    "rlt_snapshot_seconds_total",
    "rlt_snapshot_stall_seconds_total",
    "rlt_snapshot_restore_total",
    "rlt_restarts_total",
    "rlt_worker_alive",
    # zero-replay recovery (elastic/redundancy.py + driver routing):
    # parity-tick wire bytes, skipped ticks, in-memory restores, the
    # chosen route and its driver-side decision seconds
    "rlt_parity_ticks_total",
    "rlt_parity_bytes_total",
    "rlt_parity_skipped_total",
    "rlt_parity_restore_total",
    "rlt_recovery_mode",
    "rlt_recovery_seconds",
    # peer-channel retry trail (cluster/peer.py bounded backoff)
    "rlt_peer_retries_total",
    # goodput plane (telemetry/goodput.py): the run-wall partition per
    # bucket, the useful fraction, and measured MFU — per rank from the
    # worker registries, fleet-aggregated as driver (rank -1) series
    "rlt_goodput_seconds",
    "rlt_goodput_fraction",
    "rlt_mfu",
    # MPMD plane (mpmd/engine.py): simulated bubble seconds/step per
    # schedule, set once per fit from the measured per-op replay
    "rlt_mpmd_bubble_seconds",
    # planner plane (core/trainer.py _resolve_auto_strategy gauges the
    # PlanReport counts after a strategy="auto" resolution)
    "rlt_plan_candidates_total",
    "rlt_plan_pruned_total",
    "rlt_plan_rejected_total",
    "rlt_plan_compiled_total",
    "rlt_plan_seconds",
    # incident plane (telemetry/incident.py): detector trips by series
    # and ranked verdict, plus how many incidents are open right now
    "rlt_incident_total",
    "rlt_incident_active",
    # speculative decode (serve/scheduler.py): draft/accept accounting
    # per tenant plus the rolling acceptance-rate gauge
    "rlt_spec_drafted_total",
    "rlt_spec_accepted_total",
    "rlt_spec_fallbacks_total",
    "rlt_spec_acceptance_rate",
    # disaggregated decode (serve/fleet/router.py): KV-page shipping
    # over the peer channel — wire bytes by codec, chaos retries, and
    # per-request pooled-mode failovers
    "rlt_kvship_ships_total",
    "rlt_kvship_bytes_total",
    "rlt_kvship_retries_total",
    "rlt_kvship_failovers_total",
)


def validate_metric_name(name: str) -> str:
    """Raise ValueError unless ``name`` is Prometheus-clean and carries
    a unit suffix; returns the name for chaining."""
    if not NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must match {NAME_RE.pattern}")
    if not name.endswith(UNIT_SUFFIXES) and name not in UNITLESS_GAUGES:
        raise ValueError(
            f"metric name {name!r} must end with a unit suffix "
            f"{UNIT_SUFFIXES} (or be a declared unitless boolean "
            f"gauge: {UNITLESS_GAUGES})")
    return name


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic cumulative value per label set."""

    __slots__ = ("name", "_values", "_lock")

    kind = "counter"

    def __init__(self, name: str):
        self.name = validate_metric_name(name)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(value)

    def value(self, **labels: Any) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def snapshot(self) -> list[dict]:
        with self._lock:
            items = list(self._values.items())
        return [{"name": self.name, "type": self.kind,
                 "labels": dict(k), "value": v} for k, v in items]


class Gauge(Counter):
    """Point-in-time value per label set (same storage, set not add)."""

    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics: each
    bucket counts observations <= its upper bound).  One independent
    bucket array per label set — the serve plane's TTFT/TPOT series
    split by ``status=ok|failed`` so failed requests stop reading as
    missing observations (trace-plane satellite)."""

    __slots__ = ("name", "buckets", "_series", "_lock")

    kind = "histogram"

    def __init__(self, name: str, buckets: tuple = STEP_TIME_BUCKETS):
        self.name = validate_metric_name(name)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        #: label key -> [counts, sum, count]
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: Any) -> None:
        value = float(value)
        i = 0
        for i, b in enumerate(self.buckets):
            if value <= b:
                break
        else:
            i = len(self.buckets)
        key = _label_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]   # +1: +Inf
            series[0][i] += 1
            series[1] += value
            series[2] += 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            items = [(dict(k), list(s[0]), s[1], s[2])
                     for k, s in self._series.items()]
        return [{"name": self.name, "type": self.kind, "labels": labels,
                 "buckets": list(self.buckets), "counts": counts,
                 "sum": total, "count": n}
                for labels, counts, total, n in items]


class MetricsRegistry:
    """Per-process instrument registry + the window pump's data source.

    ``snapshot()`` returns the full cumulative state (Prometheus-style:
    the driver derives rates/bandwidth from deltas or elapsed time, the
    worker never resets)."""

    def __init__(self, rank: int = 0,
                 sink: Optional[Callable[[dict], None]] = None):
        self.rank = rank
        self.sink = sink
        self._instruments: dict[str, Any] = {}
        self._lock = threading.Lock()
        #: op -> bytes one execution of the compiled step moves (filled
        #: at trace time; multiplied by executed steps in on_step)
        self.traced_bytes: dict[str, int] = {}
        #: the subset of traced bytes that crosses the DCN tier
        #: (comm/audit.py declared_dcn_bytes) — charged per step into
        #: rlt_comm_dcn_bytes_total
        self.traced_dcn_bytes: int = 0
        self.last_collective: Optional[str] = None
        self.current_step = 0
        self.last_hbm_bytes = 0
        self._sink_failed = False

    # -- instruments -----------------------------------------------------

    def _get(self, cls, name: str, **kw):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = self._instruments[name] = cls(name, **kw)
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(Counter, name)

    def gauge(self, name: str) -> Gauge:
        return self._get(Gauge, name)

    def histogram(self, name: str,
                  buckets: tuple = STEP_TIME_BUCKETS) -> Histogram:
        return self._get(Histogram, name, buckets=buckets)

    # -- device sampling -------------------------------------------------

    def sample_device_state(self) -> None:
        """Current/peak HBM per local device.  Profiler-less backends
        (virtual CPU devices, some tunnels) report 0 — the gauges still
        exist, so dashboards don't break per platform."""
        cur = self.gauge("rlt_hbm_bytes")
        peak = self.gauge("rlt_hbm_peak_bytes")
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            devices = []
        if not devices:
            cur.set(0, device="0")
            peak.set(0, device="0")
            return
        for i, dev in enumerate(devices):
            try:
                stats = dev.memory_stats() or {}
            except Exception:
                stats = {}
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            cur.set(in_use, device=str(i))
            peak.set(int(stats.get("peak_bytes_in_use", 0) or 0),
                     device=str(i))
            if i == 0:
                self.last_hbm_bytes = in_use

    # -- snapshot / flush ------------------------------------------------

    def snapshot(self) -> list[dict]:
        # span/metric records lost to the ring buffer are data loss the
        # driver must surface (satellite: silent-drop visibility)
        dropped = spans.dropped()
        self.gauge("rlt_telemetry_dropped_total").set(dropped)
        # the same loss as a true Prometheus COUNTER so it is alertable
        # (rate() > 0 == silent trace loss), not just a summary field +
        # a driver log line (trace-plane satellite).  spans.dropped() is
        # monotonic per recorder; the max() guards a recorder restart.
        c = self.counter("rlt_spans_dropped_total")
        delta = dropped - c.value()
        if delta > 0:
            c.inc(delta)
        # compile-plane counters (persistent-cache hits/misses + real
        # backend-compile seconds) mirror in when that module is live;
        # sys.modules-gated so an unused compile plane costs nothing
        cc = sys.modules.get("ray_lightning_tpu.compile.cache")
        if cc is not None:
            cc.publish_metrics(self)
        with self._lock:
            instruments = list(self._instruments.values())
        out: list[dict] = []
        for inst in instruments:
            out.extend(inst.snapshot())
        return out

    def flush(self) -> None:
        if self.sink is None:
            return
        try:
            self.sink(metrics_item(self.rank, self.snapshot()))
        except Exception:
            if not self._sink_failed:
                self._sink_failed = True
                _log.warning("metrics sink failed; further windows will "
                             "be dropped silently", exc_info=True)

    def brief(self) -> dict:
        """Tiny state summary carried on heartbeats so the watchdog can
        say what a wedged rank was *doing* (step, HBM, last collective),
        not just that it went silent."""
        return {"step": self.current_step,
                "hbm_bytes": self.last_hbm_bytes,
                "last_collective": self.last_collective}


class _MetricsPump:
    """Daemon thread sampling device state + flushing the snapshot every
    ``interval`` seconds (and once at stop, so short runs still export
    at least one window)."""

    def __init__(self, registry: MetricsRegistry, interval: float = 2.0):
        self._registry = registry
        self._interval = max(0.05, float(interval))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rlt-metrics-pump")

    def start(self) -> "_MetricsPump":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._window()
        self._window()   # final flush on stop

    def _window(self) -> None:
        try:
            self._registry.sample_device_state()
        except Exception:   # sampling must never kill the pump
            pass
        self._registry.flush()


def metrics_item(rank: int, snapshot: list[dict]) -> dict:
    """Wire item carrying one cumulative metrics window (rides the same
    worker→driver queue as span batches)."""
    return {TELEMETRY_KEY: 1, "kind": "metrics", "rank": rank,
            "ts": time.time(), "metrics": snapshot}


_registry: Optional[MetricsRegistry] = None
_pump: Optional[_MetricsPump] = None

# -- rolling sample tail (incident-plane satellite) ----------------------
# A tiny fixed-size deque of the rank's most recent raw samples, attached
# to every heartbeat (heartbeat.py make_heartbeat).  The driver's
# incident detectors dedupe by timestamp watermark, so the tail keeps
# them ticking when span batches are dropped under backpressure (the
# blind spot behind the PR 9 `dropped` counter) — heartbeats are tiny
# and never ride the span ring.
from collections import deque as _deque

SAMPLE_TAIL_LEN = 32
_sample_tail: "_deque[dict]" = _deque(maxlen=SAMPLE_TAIL_LEN)
_last_step_t: Optional[float] = None


def note_tail_sample(series: str, value: float,
                     ts: Optional[float] = None) -> None:
    """Append one raw sample to the heartbeat tail (deque append is
    atomic; no lock on the hot path)."""
    _sample_tail.append({"s": series, "ts": ts if ts is not None
                         else time.time(), "v": float(value)})


def sample_tail() -> list[dict]:
    """Snapshot of the rolling tail, oldest first (heartbeat payload)."""
    return list(_sample_tail)


def reset_sample_tail() -> None:
    global _last_step_t
    _sample_tail.clear()
    _last_step_t = None


def enable_metrics(rank: int = 0,
                   sink: Optional[Callable[[dict], None]] = None,
                   interval: float = 2.0,
                   pump: bool = True) -> MetricsRegistry:
    """Install the process-wide registry (and its window pump when a
    sink will consume the flushes)."""
    global _registry, _pump
    disable_metrics()
    reset_sample_tail()
    _registry = MetricsRegistry(rank=rank, sink=sink)
    if pump and sink is not None:
        _pump = _MetricsPump(_registry, interval=interval).start()
    return _registry


def disable_metrics() -> None:
    global _registry, _pump
    if _pump is not None:
        _pump.stop()
        _pump = None
    _registry = None


def metrics_enabled() -> bool:
    return _registry is not None


def get_registry() -> Optional[MetricsRegistry]:
    return _registry


def flush_metrics() -> None:
    """Final window: sample + push the cumulative snapshot to the sink
    (teardown paths call this before disable so the driver always sees
    the run's last state)."""
    reg = _registry
    if reg is None:
        return
    try:
        reg.sample_device_state()
    except Exception:
        pass
    reg.flush()


# -- hot-path entry points (all one-global-check no-ops when disabled) --

def record_collective(op: str, nbytes: int,
                      seconds: Optional[float] = None) -> None:
    """Account one host-dispatched collective: ``nbytes`` of logical
    payload moved by ``op`` (and how long it took, when measured —
    seconds make the per-op achieved GiB/s exact instead of inferred)."""
    reg = _registry
    if reg is None:
        return
    reg.last_collective = op
    reg.counter("rlt_collective_bytes_total").inc(nbytes, op=op)
    reg.counter("rlt_collective_ops_total").inc(1, op=op)
    if seconds is not None:
        reg.counter("rlt_collective_seconds_total").inc(seconds, op=op)


def note_traced_collective(op: str, nbytes_per_step: int) -> None:
    """Register the byte cost of a collective compiled INTO the step
    program (observed once at trace time, executed every step): each
    :func:`on_step` then adds ``nbytes_per_step × k`` to the counters.
    Re-tracing the same op overwrites (last trace wins) so recompiles
    never double-count."""
    reg = _registry
    if reg is None:
        return
    reg.traced_bytes[op] = int(nbytes_per_step)
    reg.last_collective = op


def note_step_collectives(op_bytes: dict,
                          dcn_bytes: Optional[int] = None) -> None:
    """Bulk :func:`note_traced_collective` (the trainer registers the
    strategy's implied gradient/param collectives in one call).
    ``dcn_bytes`` (comm/audit.py ``declared_dcn_bytes``) is the
    DCN-crossing share, charged per executed step into
    ``rlt_comm_dcn_bytes_total`` so the hierarchical sync's inter-host
    savings are a scrapeable series."""
    reg = _registry
    if reg is None:
        return
    for op, nbytes in (op_bytes or {}).items():
        if nbytes > 0:
            reg.traced_bytes[op] = int(nbytes)
    if dcn_bytes is not None:
        reg.traced_dcn_bytes = int(dcn_bytes)


def on_step(duration_s: float, k: int = 1,
            step: Optional[int] = None) -> None:
    """Account one train dispatch: ``k`` optimizer steps in
    ``duration_s`` host seconds.  Observes the per-step-normalized time
    into the histogram, bumps the step counter, and charges every
    traced-collective cost ``k`` times."""
    global _last_step_t
    reg = _registry
    if reg is None:
        return
    k = max(1, int(k))
    reg.histogram("rlt_step_time_seconds").observe(duration_s / k)
    reg.counter("rlt_steps_total").inc(k)
    # heartbeat tail: per-step wall plus dispatch-to-dispatch cadence.
    # The interval covers this dispatch AND the host time between
    # dispatches (callbacks, snapshot stalls, a straggler's sleep) —
    # inflation the in-span step wall cannot see, which is exactly what
    # the driver's step_interval_s detector trips on.
    now = time.time()
    note_tail_sample("step_wall_s", duration_s / k, ts=now)
    if _last_step_t is not None and now > _last_step_t:
        note_tail_sample("step_interval_s", (now - _last_step_t) / k,
                         ts=now)
    _last_step_t = now
    if step is not None:
        reg.current_step = int(step)
    if reg.traced_bytes:
        bytes_c = reg.counter("rlt_collective_bytes_total")
        ops_c = reg.counter("rlt_collective_ops_total")
        for op, nbytes in reg.traced_bytes.items():
            bytes_c.inc(nbytes * k, op=op)
            ops_c.inc(k, op=op)
    if reg.traced_dcn_bytes:
        reg.counter("rlt_comm_dcn_bytes_total").inc(
            reg.traced_dcn_bytes * k)


def note_exposed_comm(seconds: float,
                      source: str = "wall_minus_floor") -> None:
    """Record the EXPOSED (non-overlapped) comm seconds per step, with
    its provenance as a ``source`` label:

    - ``"anatomy"`` — MEASURED from collective/compute event-interval
      overlap on the device timelines of a real profiler capture
      (telemetry/anatomy.py publishes it during instrumented runs;
      this is the number of record);
    - ``"wall_minus_floor"`` — benchmarks/bench_comm.py's differential
      proxy: the leg's wall seconds/step minus the comm-off fp32 floor
      measured in the same process (includes codec quantize/dequantize
      compute, so it upper-bounds the measured figure; the divergence
      between the two series is itself a finding).
    """
    reg = _registry
    if reg is None:
        return
    reg.gauge("rlt_comm_exposed_seconds").set(float(seconds),
                                              source=source)


def on_compile() -> None:
    reg = _registry
    if reg is None:
        return
    reg.counter("rlt_compiles_total").inc(1)


def on_data_wait(seconds: float) -> None:
    """Cumulative host-side input-pipeline stall (the data_wait span's
    numeric twin: scrape its rate against rlt_step_time_seconds to see
    when the loader, not the device, is the bottleneck)."""
    reg = _registry
    if reg is None:
        return
    reg.counter("rlt_data_wait_seconds_total").inc(seconds)
    note_tail_sample("data_wait_s", seconds)


def metrics_brief() -> Optional[dict]:
    """Heartbeat payload hook (None when the metrics plane is off)."""
    reg = _registry
    return reg.brief() if reg is not None else None


# -- name lint (format.sh --check / tests/test_metrics.py) ---------------

_REGISTRATION_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*['\"]([^'\"]+)['\"]")


def lint_metric_names(package_root: Optional[str] = None) -> list[str]:
    """Validate CORE_METRICS plus every name literal passed to a
    counter()/gauge()/histogram() registration in the source tree.
    Returns the list of violations (empty = clean)."""
    import os
    problems: list[str] = []
    names = set(CORE_METRICS)
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    for dirpath, _dirs, files in os.walk(package_root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                continue
            names.update(_REGISTRATION_RE.findall(src))
    for name in sorted(names):
        try:
            validate_metric_name(name)
        except ValueError as e:
            problems.append(str(e))
    return problems


def _main(argv: list[str]) -> int:
    if "--check-names" in argv:
        problems = lint_metric_names()
        for p in problems:
            print(f"metrics lint: {p}")
        if not problems:
            print(f"metrics lint: {len(CORE_METRICS)}+ instrument names "
                  f"Prometheus-clean")
        return 1 if problems else 0
    print("usage: python -m ray_lightning_tpu.telemetry.metrics "
          "--check-names")
    return 2


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
