"""Span/counter recording — the worker-side half of run telemetry.

The reference's only perf surface is a single epoch-timer callback
(SURVEY.md §5); this module gives every process a lightweight
monotonic-clock span API the hot loop can afford:

- ``span("step")`` / ``span("compile")`` / ``span("collective")`` /
  ``span("data_wait")`` — context managers timing host-side phases.
  Nesting is tracked (``depth``), so a ``collective`` inside a
  ``checkpoint`` renders nested in the Perfetto timeline.
- ``counter(name, value)`` — point-in-time scalars (throughput, HBM).

Disabled is the default and costs one attribute load + one function
call per ``span()``: the module returns a no-op singleton, allocates
nothing, and records nothing — instrumentation stays in the hot loop
unconditionally.  ``enable()`` installs a process-wide recorder with a
bounded ring buffer; full buffers drop the OLDEST records (a counter
reports how many) so telemetry can never grow without bound or stall
training.  Batches flush to a ``sink`` callable (the worker→driver
queue under distributed plugins, the aggregator directly in-process);
flushing never raises into the training loop.

No jax/numpy imports here: worker_main starts heartbeats through this
package before any heavy import happens.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional

_log = logging.getLogger(__name__)


class _NoopSpan:
    """Singleton returned by ``span()`` when recording is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        rec = _recorder
        if rec is not None:
            rec.stack.append(self.name)
            rec.last_span = self.name
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        rec = _recorder
        if rec is None:  # disabled mid-span: drop silently
            return False
        if rec.stack and rec.stack[-1] == self.name:
            rec.stack.pop()
        record = {
            "t": "span",
            "name": self.name,
            "ts": self.t0 + rec.offset,
            "dur": t1 - self.t0,
            "rank": rec.rank,
            "depth": len(rec.stack),
        }
        if self.attrs:
            record["attrs"] = self.attrs
        rec.add(record)
        return False


class _Recorder:
    """Process-wide ring buffer + sink.  The lock covers buffer swaps
    only; the training loop's common case is one append under it."""

    def __init__(self, rank: int, sink: Optional[Callable],
                 capacity: int, flush_every: Optional[int]):
        self.rank = rank
        self.sink = sink
        self.capacity = max(1, int(capacity))
        self.flush_every = flush_every
        # monotonic→wall offset, captured once: records carry wall-clock
        # timestamps so the driver can merge ranks onto one timeline
        # (same-host skew is zero; cross-host skew is NTP-bounded)
        self.offset = time.time() - time.monotonic()
        self.records: list[dict] = []
        self.dropped = 0
        self.lock = threading.Lock()
        self.stack: list[str] = []       # open span names (host loop)
        self.last_span: Optional[str] = None
        self._sink_failed = False

    def add(self, record: dict) -> None:
        batch = None
        with self.lock:
            if len(self.records) >= self.capacity:
                self.records.pop(0)
                self.dropped += 1
            self.records.append(record)
            if self.sink is not None and self.flush_every \
                    and len(self.records) >= self.flush_every:
                batch, self.records = self.records, []
        if batch:
            self._emit(batch)

    def flush(self) -> None:
        with self.lock:
            batch, self.records = self.records, []
        if batch and self.sink is not None:
            self._emit(batch)
        elif batch:
            # no sink: flushing without a consumer would lose records —
            # put them back for drain()
            with self.lock:
                self.records = batch + self.records

    def drain(self) -> list[dict]:
        with self.lock:
            batch, self.records = self.records, []
        return batch

    def _emit(self, batch: list[dict]) -> None:
        try:
            self.sink(batch)
        except Exception:
            # telemetry must never kill training; warn once per recorder
            if not self._sink_failed:
                self._sink_failed = True
                _log.warning("telemetry sink failed; further records "
                             "will be dropped silently", exc_info=True)


_recorder: Optional[_Recorder] = None


def enable(rank: int = 0, sink: Optional[Callable] = None,
           capacity: int = 65536, flush_every: Optional[int] = 256) -> None:
    """Install a process-wide recorder.  ``sink(batch_of_records)`` is
    called with full batches (and on ``flush()``); with no sink the
    records accumulate in the ring buffer for ``drain()``."""
    global _recorder
    _recorder = _Recorder(rank, sink, capacity, flush_every)


def disable() -> None:
    global _recorder
    _recorder = None


def enabled() -> bool:
    return _recorder is not None


def span(name: str, **attrs: Any):
    """Time a host-side phase.  No-op singleton when disabled."""
    if _recorder is None:
        return _NOOP
    return _Span(name, attrs or None)


def counter(name: str, value: float, **attrs: Any) -> None:
    """Record a point-in-time scalar (no-op when disabled)."""
    rec = _recorder
    if rec is None:
        return
    record = {
        "t": "counter",
        "name": name,
        "ts": time.monotonic() + rec.offset,
        "value": float(value),
        "rank": rec.rank,
    }
    if attrs:
        record["attrs"] = attrs
    rec.add(record)


def flush() -> None:
    rec = _recorder
    if rec is not None:
        rec.flush()


def drain() -> list[dict]:
    """Return and clear buffered records (sink-less recorders)."""
    rec = _recorder
    return rec.drain() if rec is not None else []


def dropped() -> int:
    rec = _recorder
    return rec.dropped if rec is not None else 0


def last_span() -> Optional[str]:
    """Most recently ENTERED span name — heartbeats carry this so the
    driver watchdog can say what a dead worker was doing."""
    rec = _recorder
    return rec.last_span if rec is not None else None
