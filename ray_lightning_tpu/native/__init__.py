"""Native (C++) input-pipeline runtime, bound through ctypes.

The reference's data path bottoms out in torch's native DataLoader worker
machinery; this is the TPU build's equivalent: a dependency-free C++ core
(src/prefetch.cpp) that assembles batches with a multithreaded row-gather
and prefetches them on a background thread, so host batch assembly
overlaps device compute instead of serializing with it.

Build model: compiled on first use with the system ``g++`` into
``_build/librlt_native.so`` (mtime-checked against the source, per-pid
temp + atomic rename so concurrent worker processes race safely).  If no
toolchain is available the library degrades to ``None`` and callers fall
back to the pure-Python path — the same optional-dependency gating the
framework applies to Ray and Tune (utils/imports.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "prefetch.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB = os.path.join(_BUILD_DIR, "librlt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
           "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        _log.warning("native build failed (%s); using pure-Python path", e)
        return False
    os.replace(tmp, _LIB)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.c_void_p
    i64 = ctypes.c_int64
    lib.rlt_prefetcher_create.restype = p
    lib.rlt_prefetcher_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
    lib.rlt_prefetcher_set_array.argtypes = [p, ctypes.c_int, p, i64]
    lib.rlt_prefetcher_set_slot.argtypes = [p, ctypes.c_int, ctypes.c_int, p]
    lib.rlt_prefetcher_start.argtypes = [p, ctypes.POINTER(i64), i64, i64,
                                         ctypes.c_int]
    lib.rlt_prefetcher_next.restype = i64
    lib.rlt_prefetcher_next.argtypes = [p, ctypes.POINTER(i64)]
    lib.rlt_prefetcher_release.argtypes = [p, i64]
    lib.rlt_prefetcher_stop.argtypes = [p]
    lib.rlt_prefetcher_destroy.argtypes = [p]
    lib.rlt_gather.argtypes = [p, i64, ctypes.POINTER(i64), i64, p,
                               ctypes.c_int]
    return lib


def load_library() -> Optional[ctypes.CDLL]:
    """The native library, building it if stale/missing; None if
    unavailable (no toolchain) or disabled via RLT_NATIVE=0."""
    global _lib, _lib_failed
    if os.environ.get("RLT_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            fresh = (os.path.exists(_LIB) and
                     os.path.getmtime(_LIB) >= os.path.getmtime(_SRC))
            if not fresh and not _compile():
                _lib_failed = True
                return None
            _lib = _bind(ctypes.CDLL(_LIB))
        except OSError as e:
            _log.warning("native library unusable (%s)", e)
            _lib_failed = True
            _lib = None
        return _lib


def native_available() -> bool:
    return load_library() is not None


def default_threads() -> int:
    env = os.environ.get("RLT_NATIVE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            _log.warning("ignoring malformed RLT_NATIVE_THREADS=%r", env)
    return min(4, os.cpu_count() or 1)


def _as_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class NativePrefetcher:
    """Batch prefetcher over a fixed set of source arrays.

    Per epoch, Python hands it the index order and iterates.  Each batch
    is yielded with OWNERSHIP: the consumer keeps the arrays forever
    (same semantics as the pure-Python path's fresh ``take()`` copies);
    the wrapper installs a freshly allocated buffer into the vacated ring
    slot before releasing it to the producer, so no yielded batch is ever
    overwritten — even while an async device transfer is still reading it.
    """

    def __init__(self, arrays: list[np.ndarray], batch_size: int,
                 queue_depth: int = 3, n_threads: Optional[int] = None):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        # sources must stay alive and contiguous for the prefetcher's life
        self._sources = [np.ascontiguousarray(a) for a in arrays]
        self.batch_size = int(batch_size)
        # depth < 2 would let a stale kReady satisfy the next batch's wait
        self.queue_depth = max(2, int(queue_depth))
        self._handle = lib.rlt_prefetcher_create(
            len(self._sources), self.queue_depth,
            n_threads or default_threads())
        self._slots: list[list[np.ndarray]] = []
        for a_i, a in enumerate(self._sources):
            row_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            lib.rlt_prefetcher_set_array(self._handle, a_i, _as_ptr(a),
                                         row_bytes)
        for s in range(self.queue_depth):
            slot_bufs = []
            for a_i, a in enumerate(self._sources):
                buf = np.empty((self.batch_size,) + a.shape[1:],
                               dtype=a.dtype)
                lib.rlt_prefetcher_set_slot(self._handle, s, a_i,
                                            _as_ptr(buf))
                slot_bufs.append(buf)
            self._slots.append(slot_bufs)

    def iter_epoch(self, indices: np.ndarray):
        """Yield one list of per-array batches (caller-owned) per batch,
        in ``indices`` order (partial final batch included, matching the
        Python path)."""
        lib, h = self._lib, self._handle
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        n = len(idx)
        lib.rlt_prefetcher_start(
            h, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            self.batch_size, 0)
        nrows = ctypes.c_int64()
        try:
            while True:
                slot = lib.rlt_prefetcher_next(h, ctypes.byref(nrows))
                if slot < 0:
                    break
                rows = int(nrows.value)
                bufs = self._slots[slot]
                # hand these buffers to the consumer; give the slot fresh
                # ones (np.empty is lazy — pages fault in the producer
                # thread, off the consumer's critical path).  set_slot
                # before release: the producer only reads slot pointers
                # after seeing the slot free under the same mutex.
                fresh = [np.empty_like(b) for b in bufs]
                for a_i, nb in enumerate(fresh):
                    lib.rlt_prefetcher_set_slot(h, int(slot), a_i,
                                                _as_ptr(nb))
                self._slots[slot] = fresh
                lib.rlt_prefetcher_release(h, slot)
                yield [b[:rows] for b in bufs]
        finally:
            lib.rlt_prefetcher_stop(h)  # abort-on-early-exit

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.rlt_prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def gather(src: np.ndarray, indices: np.ndarray,
           out: Optional[np.ndarray] = None,
           n_threads: Optional[int] = None) -> np.ndarray:
    """Threaded ``src[indices]`` for 1+-D contiguous arrays; falls back to
    numpy fancy indexing when the native library is unavailable."""
    lib = load_library()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if lib is None:
        result = src[idx]
        if out is not None:
            out[:len(idx)] = result
            return out[:len(idx)]
        return result
    if out is None:
        out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:],
                                                 dtype=np.int64))
    lib.rlt_gather(_as_ptr(src), row_bytes,
                   idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   len(idx), _as_ptr(out), n_threads or default_threads())
    return out[:len(idx)]
