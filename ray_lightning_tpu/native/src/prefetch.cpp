// Native input-pipeline runtime: threaded batch gather + background
// prefetch.
//
// Role in the framework: the host-side data path that feeds the TPU.  The
// reference rides torch's native DataLoader workers (C++/pthreads under
// torch.utils.data) for this; here the equivalent is a small dependency-
// free C++ core driven through ctypes (ray_lightning_tpu/native/__init__.py).
//
// Contract (mirrors the Python DataLoader's semantics exactly):
//   - the caller computes the epoch's index order in Python (so shuffle /
//     shard order is bit-identical to the pure-Python path across
//     processes) and hands it to rlt_prefetcher_start;
//   - a producer thread assembles batches ahead of consumption into a
//     ring of caller-owned slot buffers (double/triple buffering), using
//     a row-gather that fans out across threads for large batches;
//   - the consumer pops slots FIFO; a yielded slot stays valid until the
//     caller releases it (release-on-next-iteration in the Python
//     wrapper).
//
// Everything is C ABI so ctypes can bind it without pybind11.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct SourceArray {
  const char* data;
  int64_t row_bytes;
};

constexpr int kFree = 0;
constexpr int kReady = 1;

// Gather rows src[idx[r]] -> dst[r] for one array, splitting the row
// range across threads when the copy is big enough to amortize spawn.
void gather_rows(const SourceArray& src, const int64_t* idx, int64_t nrows,
                 char* dst, int n_threads) {
  const int64_t rb = src.row_bytes;
  auto copy_range = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      std::memcpy(dst + r * rb, src.data + idx[r] * rb,
                  static_cast<size_t>(rb));
    }
  };
  const int64_t total = nrows * rb;
  if (n_threads <= 1 || total < (1 << 20) || nrows < n_threads) {
    copy_range(0, nrows);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads - 1);
  const int64_t chunk = (nrows + n_threads - 1) / n_threads;
  for (int t = 1; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    if (lo >= nrows) break;
    const int64_t hi = std::min<int64_t>(nrows, lo + chunk);
    pool.emplace_back(copy_range, lo, hi);
  }
  copy_range(0, std::min<int64_t>(nrows, chunk));
  for (auto& th : pool) th.join();
}

struct Prefetcher {
  std::vector<SourceArray> arrays;
  // slots[s][a] = destination buffer for array a in ring slot s
  std::vector<std::vector<char*>> slots;
  int queue_depth = 2;
  int n_threads = 1;

  // epoch state
  std::vector<int64_t> indices;
  int64_t batch_size = 0;
  int64_t n_batches = 0;
  bool running = false;

  std::mutex mu;
  std::condition_variable cv_free;   // producer waits for a free slot
  std::condition_variable cv_ready;  // consumer waits for a ready slot
  std::vector<int> slot_state;
  std::vector<int64_t> slot_rows;
  int64_t produced = 0;  // batches produced
  int64_t consumed = 0;  // batches handed to the consumer
  std::atomic<bool> stop_flag{false};
  std::thread producer;

  void join_producer() {
    if (producer.joinable()) producer.join();
    running = false;
  }

  void produce_loop() {
    for (int64_t b = 0; b < n_batches && !stop_flag.load(); ++b) {
      const int slot = static_cast<int>(b % queue_depth);
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          return slot_state[slot] == kFree || stop_flag.load();
        });
        if (stop_flag.load()) return;
      }
      const int64_t lo = b * batch_size;
      const int64_t nrows =
          std::min<int64_t>(batch_size, (int64_t)indices.size() - lo);
      const int64_t* idx = indices.data() + lo;
      for (size_t a = 0; a < arrays.size(); ++a) {
        gather_rows(arrays[a], idx, nrows, slots[slot][a], n_threads);
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        slot_rows[slot] = nrows;
        slot_state[slot] = kReady;
        ++produced;
      }
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

Prefetcher* rlt_prefetcher_create(int n_arrays, int queue_depth,
                                  int n_threads) {
  auto* p = new Prefetcher();
  p->arrays.resize(static_cast<size_t>(n_arrays));
  p->queue_depth = queue_depth > 0 ? queue_depth : 2;
  p->n_threads = n_threads > 0 ? n_threads : 1;
  p->slots.assign(static_cast<size_t>(p->queue_depth),
                  std::vector<char*>(static_cast<size_t>(n_arrays), nullptr));
  p->slot_state.assign(static_cast<size_t>(p->queue_depth), kFree);
  p->slot_rows.assign(static_cast<size_t>(p->queue_depth), 0);
  return p;
}

void rlt_prefetcher_set_array(Prefetcher* p, int i, const void* data,
                              int64_t row_bytes) {
  p->arrays[static_cast<size_t>(i)] = {static_cast<const char*>(data),
                                       row_bytes};
}

void rlt_prefetcher_set_slot(Prefetcher* p, int slot, int i, void* dst) {
  p->slots[static_cast<size_t>(slot)][static_cast<size_t>(i)] =
      static_cast<char*>(dst);
}

// Begin an epoch: the caller's index order (already shuffled/sharded in
// Python) is copied internally; a producer thread starts filling slots.
void rlt_prefetcher_start(Prefetcher* p, const int64_t* indices, int64_t n,
                          int64_t batch_size, int drop_last) {
  p->join_producer();
  p->indices.assign(indices, indices + n);
  p->batch_size = batch_size;
  p->n_batches =
      drop_last ? n / batch_size : (n + batch_size - 1) / batch_size;
  p->produced = 0;
  p->consumed = 0;
  p->stop_flag.store(false);
  std::fill(p->slot_state.begin(), p->slot_state.end(), kFree);
  p->running = true;
  p->producer = std::thread([p] { p->produce_loop(); });
}

// Pop the next batch FIFO.  Returns the slot index and writes the row
// count, or -1 when the epoch is exhausted.  The slot stays owned by the
// consumer until rlt_prefetcher_release.
int64_t rlt_prefetcher_next(Prefetcher* p, int64_t* nrows) {
  if (p->consumed >= p->n_batches) return -1;
  const int slot = static_cast<int>(p->consumed % p->queue_depth);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_ready.wait(lk, [&] {
    return p->slot_state[slot] == kReady || p->stop_flag.load();
  });
  if (p->stop_flag.load() && p->slot_state[slot] != kReady) return -1;
  *nrows = p->slot_rows[slot];
  ++p->consumed;
  return slot;
}

void rlt_prefetcher_release(Prefetcher* p, int64_t slot) {
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->slot_state[static_cast<size_t>(slot)] = kFree;
  }
  p->cv_free.notify_one();
}

// Abort the in-flight epoch (consumer bailed early).
void rlt_prefetcher_stop(Prefetcher* p) {
  p->stop_flag.store(true);
  p->cv_free.notify_all();
  p->cv_ready.notify_all();
  p->join_producer();
}

void rlt_prefetcher_destroy(Prefetcher* p) {
  rlt_prefetcher_stop(p);
  delete p;
}

// Standalone threaded gather (used for one-shot batch assembly outside
// the prefetch ring, e.g. the distributed predict fast path).
void rlt_gather(const void* src, int64_t row_bytes, const int64_t* indices,
                int64_t nrows, void* dst, int n_threads) {
  SourceArray a{static_cast<const char*>(src), row_bytes};
  gather_rows(a, indices, nrows, static_cast<char*>(dst), n_threads);
}

}  // extern "C"
