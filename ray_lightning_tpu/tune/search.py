"""Search-space primitives and variant generation.

API parity with the ``ray.tune`` search-space surface the reference's
examples consume (reference: examples/ray_ddp_example.py:81-115 uses
``tune.choice``/``tune.loguniform`` + ``num_samples``): ``choice``,
``uniform``, ``loguniform``, ``randint``, ``grid_search``.  Grid axes are
expanded exhaustively; stochastic domains are sampled per trial.
"""

from __future__ import annotations

import itertools
from typing import Any, Sequence

import numpy as np


class Domain:
    """A per-trial sampled hyperparameter."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(len(self.categories)))]

    def __repr__(self):
        return f"choice({self.categories})"


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    def __repr__(self):
        return f"uniform({self.low}, {self.high})"


class LogUniform(Domain):
    def __init__(self, low: float, high: float, base: float = 10.0):
        self.low, self.high, self.base = float(low), float(high), float(base)

    def sample(self, rng):
        import math
        lo = math.log(self.low, self.base)
        hi = math.log(self.high, self.base)
        return float(self.base ** rng.uniform(lo, hi))

    def __repr__(self):
        return f"loguniform({self.low}, {self.high})"


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))

    def __repr__(self):
        return f"randint({self.low}, {self.high})"


class GridSearch:
    """Exhaustive axis; expanded across trials, not sampled."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values})"


def choice(categories: Sequence[Any]) -> Choice:
    return Choice(categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def generate_variants(space: dict, num_samples: int,
                      seed: int = 0) -> list[dict]:
    """Expand grid axes × num_samples stochastic draws into concrete
    configs (ray.tune's grid/sample semantics: each grid combination is
    run ``num_samples`` times with fresh samples of the random axes)."""
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    grid_values = [space[k].values for k in grid_keys]
    rng = np.random.default_rng(seed)
    variants = []
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    for combo in combos:
        for _ in range(num_samples):
            cfg = {}
            for k, v in space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
