"""Trial schedulers: FIFO, ASHA (async successive halving), PBT.

The reference defers scheduling wholesale to Ray Tune (SURVEY.md §3.3:
"Tune scheduler (ASHA/PBT/...) consumes reports, manages trials —
external").  Since this framework must stand alone on a TPU pod without
Ray installed, the two schedulers the reference's docs/examples lean on
are implemented natively.  Decisions are made synchronously inside
``report`` — the trial's thread blocks on its own decision, trials never
preempt each other mid-step.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"


@dataclass
class Decision:
    action: str = CONTINUE
    # for EXPLOIT (PBT): restart from this checkpoint with this config
    config: Optional[dict] = None
    checkpoint: Optional[str] = None


EXPLOIT = "EXPLOIT"


class TrialScheduler:
    """Base: sees every report; decides the trial's fate."""

    def __init__(self, metric: str = "loss", mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self._lock = threading.Lock()

    def _score(self, metrics: dict) -> Optional[float]:
        v = metrics.get(self.metric)
        if v is None:
            return None
        v = float(v)
        return -v if self.mode == "min" else v  # higher is better

    def on_result(self, trial, metrics: dict) -> Decision:
        return Decision(CONTINUE)

    def on_trial_complete(self, trial) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving.

    Rungs at ``grace_period * reduction_factor**k`` (in
    ``training_iteration`` units).  At each rung a trial continues only if
    its score is in the top ``1/reduction_factor`` of results recorded at
    that rung so far — the asynchronous variant: early trials pass through
    until enough competitors exist.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        super().__init__(metric, mode)
        self.max_t = max_t
        self.grace_period = max(1, grace_period)
        self.rf = max(2, reduction_factor)
        self._rungs: dict[int, list[float]] = {}
        #: milestone -> trial_ids already evaluated there (a trial hits
        #: each rung once, at its first report at-or-past the milestone —
        #: reports need not land exactly on milestone iterations)
        self._recorded: dict[int, set[str]] = {}
        self._milestones = []
        t = self.grace_period
        while t < max_t:
            self._milestones.append(t)
            t *= self.rf

    def on_result(self, trial, metrics: dict) -> Decision:
        it = int(metrics.get("training_iteration", 0))
        score = self._score(metrics)
        if score is None:
            return Decision(CONTINUE)
        if it >= self.max_t:
            return Decision(STOP)
        with self._lock:
            for ms in self._milestones:
                if it < ms:
                    break
                seen = self._recorded.setdefault(ms, set())
                if trial.trial_id in seen:
                    continue
                seen.add(trial.trial_id)
                rung = self._rungs.setdefault(ms, [])
                rung.append(score)
                k = max(1, len(rung) // self.rf)
                cutoff = sorted(rung, reverse=True)[k - 1]
                if score < cutoff:
                    return Decision(STOP)
        return Decision(CONTINUE)


class PopulationBasedTraining(TrialScheduler):
    """PBT: every ``perturbation_interval`` iterations, bottom-quantile
    trials clone a top-quantile trial's latest checkpoint and continue
    with a perturbed copy of its config.

    ``hyperparam_mutations`` maps config key → list of values or a
    ``Domain``; perturbation picks a neighbor / resamples.  Requires the
    trainable to save checkpoints via ``tune.checkpoint_dir`` (the
    TuneReportCheckpointCallback does this).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25, seed: int = 0):
        super().__init__(metric, mode)
        self.interval = max(1, perturbation_interval)
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        #: trial_id -> (score, config, checkpoint)
        self._population: dict[str, tuple[float, dict, Optional[str]]] = {}

    def _perturb(self, config: dict) -> dict:
        from ray_lightning_tpu.tune.search import Domain
        out = dict(config)
        for key, mut in self.mutations.items():
            if isinstance(mut, Domain):
                out[key] = mut.sample(
                    np.random.default_rng(self._rng.randrange(2**31)))
            elif isinstance(mut, list):
                out[key] = self._rng.choice(mut)
            elif callable(mut):
                out[key] = mut()
            elif isinstance(out.get(key), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = type(out[key])(out[key] * factor)
        return out

    def on_result(self, trial, metrics: dict) -> Decision:
        it = int(metrics.get("training_iteration", 0))
        score = self._score(metrics)
        if score is None:
            return Decision(CONTINUE)
        with self._lock:
            self._population[trial.trial_id] = (
                score, dict(trial.config), trial.latest_checkpoint)
            if it % self.interval != 0 or len(self._population) < 2:
                return Decision(CONTINUE)
            ranked = sorted(self._population.items(),
                            key=lambda kv: kv[1][0], reverse=True)
            n = len(ranked)
            k = max(1, int(n * self.quantile))
            bottom_ids = {tid for tid, _ in ranked[-k:]}
            if trial.trial_id not in bottom_ids or n <= k:
                return Decision(CONTINUE)
            donor_id, (dscore, dconfig, dckpt) = ranked[
                self._rng.randrange(min(k, n - k))]
            if donor_id == trial.trial_id or dckpt is None:
                return Decision(CONTINUE)
            return Decision(EXPLOIT, config=self._perturb(dconfig),
                            checkpoint=dckpt)
