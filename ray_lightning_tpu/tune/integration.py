"""Trainer↔Tune integration: trial resources + report/checkpoint relay.

Reference parity (ray_lightning/tune.py in full):
- ``get_tune_resources`` (tune.py:32-56) → per-trial resource bundles:
  one head bundle for the trial driver + ``num_workers`` worker bundles,
  expressed in TPU chips instead of GPUs.
- ``TuneReportCallback`` (tune.py:59-134): on the configured trainer
  event, rank 0 snapshots ``trainer.callback_metrics`` (skipping the
  sanity check) and relays ``report(**metrics)`` to the *trial driver* —
  through the worker→driver queue when training runs in actors, directly
  when it runs in-process.
- ``TuneReportCheckpointCallback`` (tune.py:180-236): additionally
  streams the full checkpoint as bytes through the queue; the trial
  driver writes it into ``tune.checkpoint_dir(step)`` (tune.py:161-178).

The "relay the side-effect, not the call" pattern is preserved exactly:
``report`` only works where the trial session lives, so workers enqueue
zero-arg callables that the driver's ``process_results`` loop executes
(SURVEY.md §3.3; util.py:47-52).
"""

from __future__ import annotations

import logging
import warnings
from dataclasses import dataclass, field
from typing import Optional, Sequence, Union

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.session import get_session
from ray_lightning_tpu.tune import session as tune_session
from ray_lightning_tpu.utils.imports import RAY_AVAILABLE

_log = logging.getLogger(__name__)


@dataclass
class TrialResources:
    """Per-trial resource bundles (PlacementGroupFactory analog).

    ``bundles[0]`` is the trial-driver head (1 CPU, parity with
    tune.py:50-53); the rest are worker bundles.  ``as_placement_group_
    factory()`` converts to a real Ray PlacementGroupFactory when Ray is
    installed.
    """

    bundles: list = field(default_factory=list)
    strategy: str = "PACK"

    @property
    def head_cpus(self) -> float:
        return self.bundles[0].get("CPU", 0) if self.bundles else 0

    def as_placement_group_factory(self):
        if not RAY_AVAILABLE:
            raise ImportError("Ray is not installed.")
        from ray.tune import PlacementGroupFactory
        return PlacementGroupFactory(self.bundles, strategy=self.strategy)


def get_tune_resources(
    num_workers: int = 1,
    num_cpus_per_worker: int = 1,
    use_tpu: bool = False,
    tpus_per_worker: int = 1,
    resources_per_worker: Optional[dict] = None,
    cpus_per_worker: Optional[int] = None,   # deprecated shim (tune.py:42-48)
) -> TrialResources:
    """Resources for one Tune trial running ``num_workers`` actors.

    TPU chips replace GPUs in the bundle currency: a worker bundle is
    ``{CPU: n, TPU: chips}`` — one bundle per TPU *host* actor.
    """
    if cpus_per_worker is not None:
        warnings.warn(
            "cpus_per_worker is deprecated; use num_cpus_per_worker",
            DeprecationWarning, stacklevel=2)
        num_cpus_per_worker = cpus_per_worker
    resources = dict(resources_per_worker or {})
    num_cpus_per_worker = resources.pop("CPU", num_cpus_per_worker)
    if "TPU" in resources:
        tpus = resources.pop("TPU")
        use_tpu = tpus > 0
        tpus_per_worker = tpus or tpus_per_worker
    worker = {"CPU": num_cpus_per_worker, **resources}
    if use_tpu:
        worker["TPU"] = tpus_per_worker
    head = {"CPU": 1}
    return TrialResources(bundles=[head] + [dict(worker)] * num_workers,
                          strategy="PACK")


_EVENTS = ("validation_end", "train_epoch_end", "train_end", "batch_end")


class _TuneCallbackBase(Callback):
    """Event-dispatch base (reference TuneCallback(on=...) analog)."""

    def __init__(self, on: Union[str, Sequence[str]] = "validation_end"):
        if isinstance(on, str):
            on = [on]
        bad = [e for e in on if e not in _EVENTS]
        if bad:
            raise ValueError(f"Unknown events {bad}; options: {_EVENTS}")
        self._on = set(on)

    def _handle(self, trainer, module) -> None:
        raise NotImplementedError

    def _fire(self, event, trainer, module):
        if event in self._on and not trainer.sanity_checking \
                and trainer.is_global_zero:
            self._handle(trainer, module)

    def on_validation_end(self, trainer, module):
        self._fire("validation_end", trainer, module)

    def on_train_epoch_end(self, trainer, module):
        self._fire("train_epoch_end", trainer, module)

    def on_train_end(self, trainer, module):
        self._fire("train_end", trainer, module)

    needs_batch = False   # _fire never receives the batch

    def on_train_batch_end(self, trainer, module, outputs, batch, batch_idx):
        self._fire("batch_end", trainer, module)

    @staticmethod
    def _relay(payload) -> None:
        """Run ``payload`` where the trial session lives: enqueue to the
        driver when inside an actor worker, else call directly — the
        direct path resolves against the builtin runner's session OR a
        real Ray Tune/Train session (tune/ray_bridge.py)."""
        try:
            get_session().put_queue(payload)
            return
        except ValueError:
            pass
        from ray_lightning_tpu.tune import ray_bridge
        if tune_session.in_session() or ray_bridge.in_session():
            payload()
        else:
            _log.warning(
                "Tune callback fired outside a tune trial and outside a "
                "worker queue; dropping report.")


class TuneReportCallback(_TuneCallbackBase):
    """Report trainer metrics to Tune (reference: tune.py:59-134).

    ``metrics`` may be None (report everything), a list of metric names,
    or a dict mapping the reported name → trainer metric name.
    """

    def __init__(self, metrics: Union[None, str, list, dict] = None,
                 on: Union[str, Sequence[str]] = "validation_end"):
        super().__init__(on)
        if isinstance(metrics, str):
            metrics = [metrics]
        self._metrics = metrics

    def _get_report_dict(self, trainer) -> Optional[dict]:
        # tune.py:110-128 analog: snapshot callback_metrics, filter/rename
        cbm = {k: float(v) for k, v in trainer.callback_metrics.items()}
        if not self._metrics:
            report = dict(cbm)
        elif isinstance(self._metrics, dict):
            report = {}
            for out_name, src in self._metrics.items():
                if src in cbm:
                    report[out_name] = cbm[src]
        else:
            report = {k: cbm[k] for k in self._metrics if k in cbm}
        if not report:
            _log.warning(
                "Metrics %s not found in trainer.callback_metrics %s; "
                "skipping report.", self._metrics, sorted(cbm))
            return None
        return report

    def _handle(self, trainer, module) -> None:
        report = self._get_report_dict(trainer)
        if report is None:
            return
        self._relay(_ReportPayload(report))


class _ReportPayload:
    """Picklable zero-arg callable executed on the trial driver.  The
    session lookup happens at CALL time, driver-side — builtin runner
    session or real Ray Tune/Train session, whichever is live there."""

    def __init__(self, metrics: dict):
        self.metrics = metrics

    def __call__(self):
        tune_session.report(**self.metrics)


class _CheckpointPayload:
    """Write checkpoint bytes into the trial's checkpoint store,
    driver-side (tune.py:161-167 analog: worker bytes → driver write —
    a directory under classic Tune/builtin runner, a staged
    report-attached checkpoint under the modern Ray Train API)."""

    def __init__(self, blob: bytes, step: int, filename: str):
        self.blob = blob
        self.step = step
        self.filename = filename

    def __call__(self):
        tune_session.deliver_checkpoint(self.blob, self.step, self.filename)


class _TuneCheckpointCallback(_TuneCallbackBase):
    """Stream the full trainer checkpoint to the trial driver
    (reference: tune.py:136-178)."""

    def __init__(self, filename: str = "checkpoint",
                 on: Union[str, Sequence[str]] = "validation_end"):
        super().__init__(on)
        self._filename = filename

    def _fire(self, event, trainer, module):
        # checkpoint assembly is collective (all ranks gather) — only the
        # relay itself is rank-0-gated.
        if event in self._on and not trainer.sanity_checking:
            ckpt = trainer.dump_checkpoint()
            if trainer.is_global_zero:
                blob = trainer.serialize_checkpoint(ckpt)
                self._relay(_CheckpointPayload(
                    blob, trainer.global_step, self._filename))

    def _handle(self, trainer, module) -> None:  # unused; _fire overridden
        pass


class TuneReportCheckpointCallback(_TuneCallbackBase):
    """Checkpoint then report, so Tune associates the checkpoint with the
    reported iteration (reference: tune.py:180-236, order at :234-236)."""

    def __init__(self, metrics: Union[None, str, list, dict] = None,
                 filename: str = "checkpoint",
                 on: Union[str, Sequence[str]] = "validation_end"):
        super().__init__(on)
        self._checkpoint = _TuneCheckpointCallback(filename, on)
        self._report = TuneReportCallback(metrics, on)

    def _fire(self, event, trainer, module):
        self._checkpoint._fire(event, trainer, module)
        self._report._fire(event, trainer, module)

    def _handle(self, trainer, module) -> None:
        pass
