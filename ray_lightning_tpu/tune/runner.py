"""The trial runner: ``tune.run`` executed locally, no Ray required.

Reference shape being reproduced (SURVEY.md §3.3): ``tune.run(train_fn,
config, num_samples, scheduler, resources_per_trial)`` → per-trial driver
runs ``train_fn(config)``, which builds a Trainer (possibly with a
distributed plugin whose actors train remotely) and reports metrics /
checkpoints through the session.  Returns an ``ExperimentAnalysis`` with
``best_config`` / ``best_checkpoint`` / per-trial ``last_result``.

Trials run in threads (``max_concurrent_trials``); the compute inside a
trial lives either in-process (LocalPlugin SPMD) or in actor
subprocesses (RayXlaPlugin), so threads are purely coordination.
"""

from __future__ import annotations

import inspect
import logging
import os
import threading
import time
import traceback
from typing import Any, Callable, Optional

from ray_lightning_tpu.tune.schedulers import (
    EXPLOIT, STOP, FIFOScheduler,
    PopulationBasedTraining, TrialScheduler)
from ray_lightning_tpu.tune.search import generate_variants
from ray_lightning_tpu.tune.session import TrialSession, set_session

_log = logging.getLogger(__name__)


class _StopTrial(Exception):
    pass


class _ExploitTrial(Exception):
    def __init__(self, config: dict, checkpoint: str):
        self.config = config
        self.checkpoint = checkpoint


class Trial:
    def __init__(self, trial_id: str, config: dict, logdir: str):
        self.trial_id = trial_id
        self.config = config
        self.logdir = logdir
        self.status = "PENDING"
        self.last_result: dict = {}
        self.history: list[dict] = []
        self.latest_checkpoint: Optional[str] = None
        self.error: Optional[str] = None
        #: where a telemetry-enabled Trainer inside this trial writes
        #: its trace.json/telemetry.jsonl: TelemetryConfig.resolve_dir
        #: resolves against the live trial session (tune/session.py), so
        #: concurrent trials never interleave into one shared dir
        self.telemetry_dir = os.path.join(logdir, "telemetry")
        #: /metrics endpoint of the trial's Trainer when the metrics
        #: exporter is enabled (always an ephemeral port inside a trial
        #: — concurrent trials never contend for one bind); recorded by
        #: telemetry/exporter.py; the listener dies with the trial's
        #: run, so the URL is only live while the trial executes
        self.metrics_url: Optional[str] = None
        #: device lease this trial ran on (in-process trials only;
        #: populated at first acquire — tune/session.py) for post-hoc
        #: "which chips ran this trial" debugging via ExperimentAnalysis
        self.leased_devices: list[str] = []
        #: PlanReport dict of a Trainer(strategy="auto") run inside
        #: this trial (tune/session.py note_plan_report) — which plan
        #: each trial trained under, for post-hoc sweep analysis; trial
        #: N>0 of a same-shaped sweep reuses trial 0's plan via the
        #: planner memo + the experiment's shared compile cache
        self.plan_report: Optional[dict] = None

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


class ExperimentAnalysis:
    def __init__(self, trials: list[Trial], metric: Optional[str],
                 mode: str):
        self.trials = trials
        self.default_metric = metric
        self.default_mode = mode

    # -- reference-surface accessors (ray.tune.ExperimentAnalysis) ------

    @property
    def results(self) -> dict[str, dict]:
        return {t.trial_id: t.last_result for t in self.trials}

    def get_best_trial(self, metric: Optional[str] = None,
                       mode: Optional[str] = None) -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        sign = -1.0 if mode == "min" else 1.0
        best, best_v = None, None
        for t in self.trials:
            if t.status == "ERROR" or metric not in t.last_result:
                continue
            v = sign * float(t.last_result[metric])
            if best_v is None or v > best_v:
                best, best_v = t, v
        return best

    @property
    def best_trial(self) -> Optional[Trial]:
        return self.get_best_trial()

    @property
    def best_config(self) -> Optional[dict]:
        t = self.best_trial
        return t.config if t else None

    @property
    def best_checkpoint(self) -> Optional[str]:
        t = self.best_trial
        return t.latest_checkpoint if t else None

    @property
    def best_result(self) -> Optional[dict]:
        t = self.best_trial
        return t.last_result if t else None


def _accepts_checkpoint_dir(fn: Callable) -> bool:
    try:
        return "checkpoint_dir" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _trial_device_demand(resources_per_trial: Any) -> Optional[int]:
    """Chips one trial wants, from ``get_tune_resources(...)`` output
    (TrialResources) or a plain ``{"TPU": n}`` dict.  None = no device
    demand declared (CPU-only bundles)."""
    if resources_per_trial is None:
        return None
    bundles = getattr(resources_per_trial, "bundles", None)
    if bundles is not None:
        demand = sum(int(b.get("TPU", 0)) for b in bundles)
    elif isinstance(resources_per_trial, dict):
        demand = int(resources_per_trial.get("TPU", 0))
    else:
        return None
    return demand or None


class _DeviceLeaser:
    """Partitions the visible devices into disjoint per-trial chunks.

    The reference gets trial isolation for free from Ray placement
    groups (tune.py:50-56: bundles exist precisely so trials never share
    devices); the local runner provides the same guarantee for
    *in-process* (LocalPlugin) trials — a trial leases its chunk when
    its Trainer first asks for devices and holds it for the trial's
    lifetime (including PBT exploit restarts); trials wanting more
    chips than remain simply wait, which serializes full-mesh trials.

    Everything is lazy: ``jax`` is imported (and the backend
    initialized) only inside a trial thread that actually trains
    in-process.  Actor-based trials never acquire, so a CPU-only tune
    driver stays free of any JAX backend and cluster-level chip demands
    are left to the cluster backend — exactly the reference's split,
    where placement groups size *cluster* resources and the trial
    driver itself stays thin.
    """

    def __init__(self, per_trial: int):
        self._per_trial = per_trial
        self._chunks: Optional[list] = None
        self._cond = threading.Condition()

    def _ensure_chunks(self) -> None:
        if self._chunks is not None:
            return
        import jax
        devices = list(jax.devices())
        if self._per_trial > len(devices):
            raise ValueError(
                f"resources_per_trial wants {self._per_trial} devices "
                f"but only {len(devices)} are visible to this process")
        stranded = len(devices) % self._per_trial
        if stranded:
            # the reference's placement groups make trial placement
            # inspectable (reference tune.py:50-56); the least we owe the
            # operator is a loud note that part of the host sits idle
            _log.warning(
                "resources_per_trial=%d does not divide the %d visible "
                "devices: %d device(s) (%s) will sit idle under the "
                "trial lease partition.", self._per_trial, len(devices),
                stranded,
                ", ".join(str(d) for d in devices[-stranded:]))
        self._chunks = [
            devices[i:i + self._per_trial]
            for i in range(0, len(devices) - self._per_trial + 1,
                           self._per_trial)]

    def acquire(self) -> list:
        with self._cond:
            self._ensure_chunks()
            while not self._chunks:
                self._cond.wait()
            return self._chunks.pop()

    def release(self, chunk: list) -> None:
        with self._cond:
            self._chunks.append(chunk)
            self._cond.notify()


def run(
    trainable: Callable,
    config: Optional[dict] = None,
    *,
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    metric: Optional[str] = None,
    mode: Optional[str] = None,
    stop: Optional[dict] = None,
    resources_per_trial: Any = None,
    local_dir: Optional[str] = None,
    name: Optional[str] = None,
    max_concurrent_trials: Optional[int] = None,
    max_failures: int = 0,
    fail_fast: bool = False,
    raise_on_failed_trial: bool = True,
    seed: int = 0,
    verbose: int = 1,
) -> ExperimentAnalysis:
    """Run ``num_samples`` trials of ``trainable`` over ``config``.

    ``trainable(config)`` or ``trainable(config, checkpoint_dir=None)``
    (the latter enables PBT exploit restores and checkpoint-resumed
    trial retries, reference-PBT/Tune contract).

    ``max_failures``: retry a crashed trial up to this many times
    (``ray.tune`` ``max_failures`` parity — the reference's recovery
    story is exactly "Tune trial retries + checkpoints", SURVEY.md §5);
    a trainable with a ``checkpoint_dir`` parameter resumes from the
    trial's latest checkpoint.

    Telemetry: a trial whose Trainer enables telemetry writes its
    trace/jsonl under the trial's own logdir (``Trial.telemetry_dir``)
    — the thread-local trial session scopes both the output dir and
    the active driver-side aggregator per trial.

    Compilation: all trials share one persistent XLA compilation cache
    under ``<exp_dir>/compile_cache`` (``RLT_COMPILE_CACHE=0`` opts
    out), so same-shape trials after the first — and crash-retried
    trials under ``max_failures`` — warm-start instead of re-paying
    XLA compilation (compile/cache.py).

    Device isolation: when ``resources_per_trial`` declares a TPU chip
    count (``get_tune_resources(...)`` bundles or ``{"TPU": n}``), the
    visible devices are partitioned into disjoint n-chip leases that
    *in-process* (LocalPlugin) trials acquire when their Trainer first
    asks for devices — each such trial's mesh spans only its lease,
    effective concurrency is ``len(devices) // n``, and trials wanting
    the full mesh serialize.  Trials whose compute runs in actor
    subprocesses never acquire a lease (their chip demand is a cluster
    resource, the backend's job), so the tune driver itself never
    initializes a JAX backend.  Without a declared chip count,
    concurrent in-process trials share every visible device — declare
    resources to isolate them.
    """
    scheduler = scheduler or FIFOScheduler(metric or "loss", mode or "min")
    # metric/mode default from the scheduler as one unit, so analysis
    # ranking agrees with the scheduling direction
    if metric is None:
        metric = scheduler.metric
    if mode is None:
        mode = scheduler.mode
    local_dir = local_dir or os.path.join(os.getcwd(), "rlt_tune")
    exp_name = name or f"exp_{int(time.time())}"
    exp_dir = os.path.join(local_dir, exp_name)
    os.makedirs(exp_dir, exist_ok=True)

    variants = generate_variants(dict(config or {}), num_samples, seed)
    trials = []
    for i, cfg in enumerate(variants):
        tid = f"trial_{i:05d}"
        logdir = os.path.join(exp_dir, tid)
        os.makedirs(logdir, exist_ok=True)
        trials.append(Trial(tid, cfg, logdir))

    stop = dict(stop or {})
    takes_ckpt = _accepts_checkpoint_dir(trainable)
    errors: list[BaseException] = []
    errors_lock = threading.Lock()
    abort = threading.Event()  # fail_fast: first error stops the sweep

    if max_concurrent_trials is None:
        # PBT is population-based: the population must coexist.
        max_concurrent_trials = (
            len(trials) if isinstance(scheduler, PopulationBasedTraining)
            else 1)
    demand = _trial_device_demand(resources_per_trial)
    leaser = _DeviceLeaser(demand) if demand is not None else None
    sem = threading.Semaphore(max(1, max_concurrent_trials))

    # one persistent compilation cache for the WHOLE experiment: trials
    # of a sweep dispatch byte-identical SPMD programs per shape, so
    # trial 0 pays each compile once and trial N>0 (and every
    # max_failures restart) loads the executable from disk instead of
    # re-paying XLA — multiplied by num_samples, the dominant startup
    # cost of exactly this workload.  RLT_COMPILE_CACHE=0 opts out;
    # an explicit RLT_COMPILE_CACHE_DIR (a cross-experiment root)
    # outranks this per-experiment dir at config resolution
    # (compile/cache.py precedence).
    compile_cache_dir = (
        None if os.environ.get("RLT_COMPILE_CACHE", "").strip() == "0"
        else os.path.join(exp_dir, "compile_cache"))

    def on_report(trial: Trial, metrics: dict) -> None:
        trial.last_result = dict(metrics)
        trial.history.append(dict(metrics))
        if abort.is_set():
            raise _StopTrial()
        it = int(metrics.get("training_iteration", 0))
        stop_it = stop.get("training_iteration")
        decision = scheduler.on_result(trial, metrics)
        if decision.action == EXPLOIT:
            trial.config = dict(decision.config)
            raise _ExploitTrial(decision.config, decision.checkpoint)
        if decision.action == STOP or (stop_it and it >= stop_it):
            raise _StopTrial()
        for key, bound in stop.items():
            if key in metrics and key != "training_iteration" \
                    and float(metrics[key]) >= float(bound):
                raise _StopTrial()

    def run_trial(trial: Trial) -> None:
        with sem:
            if abort.is_set():
                return  # fail_fast tripped; leave trial PENDING
            trial.status = "RUNNING"
            session = TrialSession(trial, on_report, device_leaser=leaser,
                                   compile_cache_dir=compile_cache_dir)
            set_session(session)
            restore_from: Optional[str] = None
            failures = 0
            try:
                while True:
                    try:
                        if takes_ckpt:
                            trainable(dict(trial.config),
                                      checkpoint_dir=restore_from)
                        else:
                            trainable(dict(trial.config))
                        trial.status = "TERMINATED"
                        return
                    except _StopTrial:
                        trial.status = "TERMINATED"
                        return
                    except _ExploitTrial as e:
                        if not takes_ckpt:
                            _log.warning(
                                "PBT exploit requested but %s has no "
                                "checkpoint_dir parameter; continuing "
                                "without restore.", trainable)
                        restore_from = e.checkpoint
                        # the donor checkpoint is now this trial's
                        # restore source: a crash-retry after the
                        # exploit must resume the exploited weights,
                        # not the trial's stale pre-exploit checkpoint
                        trial.latest_checkpoint = e.checkpoint
                        _log.info("%s exploiting: restart from %s",
                                  trial.trial_id, e.checkpoint)
                        continue  # restart with mutated config
                    except Exception:
                        # trial retry — the reference's ONLY recovery
                        # story (SURVEY.md §5 failure detection: "Tune
                        # trial retries + checkpoints"): restart the
                        # trainable, resuming from its latest checkpoint
                        # when it takes one.  Exception only: SystemExit
                        # / KeyboardInterrupt are deliberate exits, not
                        # retryable crashes (ray.tune parity) — the
                        # outer handler records them once.
                        failures += 1
                        if failures > max_failures or abort.is_set():
                            raise
                        restore_from = (trial.latest_checkpoint
                                        if takes_ckpt else None)
                        _log.warning(
                            "%s failed (attempt %d/%d); retrying%s:\n%s",
                            trial.trial_id, failures, max_failures + 1,
                            f" from {restore_from}" if restore_from
                            else "", traceback.format_exc())
                        continue
            except BaseException as e:          # noqa: BLE001
                trial.status = "ERROR"
                trial.error = traceback.format_exc()
                with errors_lock:
                    errors.append(e)
                if fail_fast:
                    abort.set()
                if verbose:
                    _log.error("%s failed:\n%s", trial.trial_id, trial.error)
            finally:
                scheduler.on_trial_complete(trial)
                set_session(None)
                session.release_devices()

    threads = [threading.Thread(target=run_trial, args=(t,), daemon=True)
               for t in trials]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    if errors and (fail_fast or raise_on_failed_trial):
        # ray.tune parity: any failed trial raises by default, so partial
        # failures can't be misread as complete sweeps
        failed = [t.trial_id for t in trials if t.status == "ERROR"]
        raise RuntimeError(
            f"{len(failed)} trial(s) failed: {failed}. First error "
            f"below; pass raise_on_failed_trial=False to get a partial "
            f"ExperimentAnalysis instead.") from errors[0]
    return ExperimentAnalysis(trials, metric, mode)
