"""Per-trial session: the process/thread-local context that makes
``report`` / ``checkpoint_dir`` work inside a running trial.

Reference behavior being reproduced: ``tune.report`` and
``tune.checkpoint_dir`` only work in the process Tune launched
(reference: tune.py:130-134, :161-178 route them through the queue so
they execute on the trial driver).  Here the session is thread-local —
the local runner executes each trial in its own thread — and the
framework's distributed plugins relay worker-side calls to the trial
thread through the worker→driver queue exactly like the reference.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

_local = threading.local()


class TrialSession:
    """Live context of one running trial.

    ``devices`` is the trial's leased device subset, acquired LAZILY the
    first time in-process training asks for devices (tune/runner.py
    ``_DeviceLeaser``) — trials whose compute lives in actor
    subprocesses never acquire, so the tune driver never initializes a
    JAX backend for them.  None = no lease, the trial may span every
    visible device.
    """

    def __init__(self, trial, on_report, device_leaser=None,
                 compile_cache_dir=None):
        self.trial = trial
        self._on_report = on_report
        self._step = 0
        self._leaser = device_leaser
        self.devices = None
        #: the experiment's SHARED persistent-compilation-cache dir
        #: (tune/runner.py): every same-shape trial, and every
        #: max_failures restart of this trial, warm-starts from the
        #: programs earlier trials already compiled (compile/cache.py
        #: resolves it when the trial's Trainer is constructed)
        self.compile_cache_dir = compile_cache_dir

    def acquire_devices(self):
        if self._leaser is not None and self.devices is None:
            self.devices = self._leaser.acquire()
            # record the lease on the trial for post-hoc debugging via
            # ExperimentAnalysis (which chips ran which trial — the
            # inspectability the reference gets from placement groups)
            self.trial.leased_devices = [str(d) for d in self.devices]
        return self.devices

    def release_devices(self) -> None:
        if self._leaser is not None and self.devices is not None:
            self._leaser.release(self.devices)
            self.devices = None

    def report(self, **metrics) -> None:
        self._step += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self._step)
        self._on_report(self.trial, metrics)

    @contextlib.contextmanager
    def checkpoint_dir(self, step: int):
        """Directory for this trial's checkpoint at ``step`` (parity with
        ``tune.checkpoint_dir``, which the reference writes into via
        fsspec, tune.py:161-167)."""
        path = os.path.join(self.trial.logdir, f"checkpoint_{step:06d}")
        os.makedirs(path, exist_ok=True)
        yield path
        self.trial.latest_checkpoint = path


def _get() -> Optional[TrialSession]:
    return getattr(_local, "session", None)


def set_session(session: Optional[TrialSession]) -> None:
    _local.session = session


def in_session() -> bool:
    return _get() is not None


def note_plan_report(report: dict) -> None:
    """Record the planner's PlanReport dict on the live trial (no-op
    outside a trial) — the post-hoc "which plan did this trial train
    under" analog of ``leased_devices`` / ``metrics_url``.  Called by
    plan/planner.py after every (including memo-reused) plan."""
    s = _get()
    if s is not None:
        s.trial.plan_report = report


def report(_metrics: Optional[dict] = None, **metrics) -> None:
    """Report metrics for the current trial (``tune.report`` analog).

    Resolves against the builtin runner's session when one is live,
    falling back to a *real* Ray Tune/Train session (tune/ray_bridge.py)
    — so a train_fn written against this API runs unchanged under
    genuine ``ray.tune.run``.
    """
    merged = dict(_metrics or {})
    merged.update(metrics)
    s = _get()
    if s is not None:
        s.report(**merged)
        return
    from ray_lightning_tpu.tune import ray_bridge
    if ray_bridge.report(merged):
        return
    raise RuntimeError(
        "tune.report() called outside a tune trial; run this function "
        "via ray_lightning_tpu.tune.run() or a real Ray Tune trial.")


def deliver_checkpoint(blob: bytes, step: int, filename: str) -> None:
    """Write checkpoint bytes where the live trial session keeps
    checkpoints — builtin runner's trial dir, classic Ray Tune's
    ``checkpoint_dir``, or staged for the modern Train API's next
    report (reference analog: tune.py:161-167)."""
    s = _get()
    if s is not None:
        with s.checkpoint_dir(step) as d:
            with open(os.path.join(d, filename), "wb") as f:
                f.write(blob)
        return
    from ray_lightning_tpu.tune import ray_bridge
    if ray_bridge.stage_checkpoint(blob, step, filename):
        return
    raise RuntimeError(
        "Tune checkpoint relay outside a tune trial; run via "
        "ray_lightning_tpu.tune.run() or a real Ray Tune trial.")


@contextlib.contextmanager
def checkpoint_dir(step: int):
    s = _get()
    if s is None:
        from ray_lightning_tpu.tune import ray_bridge
        if ray_bridge.in_session():
            with ray_bridge.checkpoint_dir(step) as path:
                yield path
            return
        raise RuntimeError("tune.checkpoint_dir() outside a tune trial.")
    with s.checkpoint_dir(step) as path:
        yield path


def get_trial_devices():
    """Devices leased to the current trial, or None (no trial / no
    lease declared).  LocalPlugin consults this so an in-process
    trial's mesh spans only its own partition of the host's chips; the
    lease is acquired on first call (may block until a chunk frees)."""
    s = _get()
    return s.acquire_devices() if s is not None else None


def get_trial_id() -> str:
    s = _get()
    return s.trial.trial_id if s else "default"


def get_trial_dir() -> Optional[str]:
    s = _get()
    return s.trial.logdir if s else None


def get_compile_cache_dir() -> Optional[str]:
    """The experiment-wide shared compilation-cache dir, or None outside
    a builtin tune trial (or when the runner disabled sharing).
    ``CompileCacheConfig.resolve`` consults this so a Trainer built
    inside a trial points at the experiment's cache by default."""
    s = _get()
    return getattr(s, "compile_cache_dir", None) if s is not None else None


def get_trial():
    """The live Trial object, or None outside a builtin tune trial.
    The metrics exporter (telemetry/exporter.py) uses it to give each
    concurrent trial its own ephemeral /metrics port and to record the
    bound URL on the trial for ExperimentAnalysis."""
    s = _get()
    return s.trial if s else None
