"""Per-trial session: the process/thread-local context that makes
``report`` / ``checkpoint_dir`` work inside a running trial.

Reference behavior being reproduced: ``tune.report`` and
``tune.checkpoint_dir`` only work in the process Tune launched
(reference: tune.py:130-134, :161-178 route them through the queue so
they execute on the trial driver).  Here the session is thread-local —
the local runner executes each trial in its own thread — and the
framework's distributed plugins relay worker-side calls to the trial
thread through the worker→driver queue exactly like the reference.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

_local = threading.local()


class TrialSession:
    """Live context of one running trial."""

    def __init__(self, trial, on_report):
        self.trial = trial
        self._on_report = on_report
        self._step = 0

    def report(self, **metrics) -> None:
        self._step += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self._step)
        self._on_report(self.trial, metrics)

    @contextlib.contextmanager
    def checkpoint_dir(self, step: int):
        """Directory for this trial's checkpoint at ``step`` (parity with
        ``tune.checkpoint_dir``, which the reference writes into via
        fsspec, tune.py:161-167)."""
        path = os.path.join(self.trial.logdir, f"checkpoint_{step:06d}")
        os.makedirs(path, exist_ok=True)
        yield path
        self.trial.latest_checkpoint = path


def _get() -> Optional[TrialSession]:
    return getattr(_local, "session", None)


def set_session(session: Optional[TrialSession]) -> None:
    _local.session = session


def in_session() -> bool:
    return _get() is not None


def report(_metrics: Optional[dict] = None, **metrics) -> None:
    """Report metrics for the current trial (``tune.report`` analog)."""
    s = _get()
    if s is None:
        raise RuntimeError(
            "tune.report() called outside a tune trial; run this function "
            "via ray_lightning_tpu.tune.run().")
    merged = dict(_metrics or {})
    merged.update(metrics)
    s.report(**merged)


@contextlib.contextmanager
def checkpoint_dir(step: int):
    s = _get()
    if s is None:
        raise RuntimeError("tune.checkpoint_dir() outside a tune trial.")
    with s.checkpoint_dir(step) as path:
        yield path


def get_trial_id() -> str:
    s = _get()
    return s.trial.trial_id if s else "default"


def get_trial_dir() -> Optional[str]:
    s = _get()
    return s.trial.logdir if s else None
