"""Hyperparameter tuning: native Tune-capability subsystem.

Reference surface (``ray_lightning/tune.py`` + the ``ray.tune`` API its
examples consume): trial resources, report/checkpoint callbacks, and a
``run`` entry point with search spaces and ASHA/PBT schedulers.  The
reference delegates scheduling to Ray Tune; this framework ships its own
local runner so a TPU pod needs no Ray, while the callbacks/resources
also plug into real Ray Tune when it is installed (RAY_AVAILABLE).
"""

from ray_lightning_tpu.tune.integration import (
    TrialResources,
    TuneReportCallback,
    TuneReportCheckpointCallback,
    _TuneCheckpointCallback,  # noqa: F401  (tested internal)
    get_tune_resources,
)
from ray_lightning_tpu.tune.runner import (
    ExperimentAnalysis,
    Trial,
    run,
)
from ray_lightning_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_lightning_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_lightning_tpu.tune.session import (
    checkpoint_dir,
    get_trial_dir,
    get_trial_id,
    report,
)

#: parity with the reference's TUNE_INSTALLED guard (tune.py:13-27): the
#: native tune subsystem is always available; this flag remains for
#: user code written against the reference's pattern.
TUNE_INSTALLED = True

__all__ = [
    "TrialResources",
    "TuneReportCallback",
    "TuneReportCheckpointCallback",
    "get_tune_resources",
    "ExperimentAnalysis",
    "Trial",
    "run",
    "ASHAScheduler",
    "FIFOScheduler",
    "PopulationBasedTraining",
    "TrialScheduler",
    "choice",
    "grid_search",
    "loguniform",
    "randint",
    "uniform",
    "checkpoint_dir",
    "get_trial_dir",
    "get_trial_id",
    "report",
    "TUNE_INSTALLED",
]
