"""Bridge to a *genuine* Ray Tune/Train trial session.

The builtin runner (tune/runner.py) keeps its own thread-local session;
but the reference's canonical recipe is ``ray.tune.run(train_fn,
resources_per_trial=get_tune_resources(...))`` with real Ray Tune
(reference README.md:140-183), where ``tune.report`` /
``tune.checkpoint_dir`` resolve against Ray's own session living in the
trial-driver process (reference tune.py:130-134, :161-178).  This module
detects that session and routes our relay payloads into it, so the same
``TuneReportCallback`` works under either runner.

Three Ray API generations are supported, probed in order:

- **classic function-trainable API** (the one the reference binds):
  ``ray.tune.report(**metrics)`` and ``with ray.tune.checkpoint_dir(step)``
  — detected via ``tune.is_session_enabled`` (reference tune.py:130-134).
- **public context API** (newer ray, where ``is_session_enabled`` is
  gone): ``ray.tune.get_context()`` returning a context with a live
  trial id, reporting via ``ray.tune.report(metrics_dict,
  checkpoint=...)`` (positional-dict signature).  Probed AHEAD of the
  private path below, so a Ray release that drops its internals does
  not strand the bridge.
- **modern Train API via the private session** (last resort):
  ``ray.train._internal.session.get_session`` +
  ``ray.train.report(metrics, checkpoint=Checkpoint.from_directory(d))``.

Under both non-classic generations a checkpoint can only ride a report,
so checkpoint payloads are *staged* and attached to the next report
(the callbacks fire checkpoint-then-report in that order precisely so
this pairing works, reference tune.py:234-236).

Everything is probed lazily and defensively: Ray absent, Ray present but
no live session, and any API generation all behave sensibly.  The
builtin runner's thread-local session always wins over this bridge —
tune/session.py probes it first (probe order is itself under test,
tests/test_ray_tune_bridge.py).
"""

from __future__ import annotations

import contextlib
import logging
import os
import shutil
import tempfile
import threading

_log = logging.getLogger(__name__)

# modern-API checkpoint staged for the next report, per trial thread
_local = threading.local()


# -- session detection ------------------------------------------------------

def _classic_session_live() -> bool:
    """True when ray.tune's classic function-trainable session exists."""
    try:
        from ray import tune
    except Exception:
        return False
    for probe in ("is_session_enabled",):
        fn = getattr(tune, probe, None)
        if fn is not None:
            try:
                return bool(fn())
            except Exception:
                return False
    # older layout: ray.tune.session.get_session()
    try:
        from ray.tune.session import get_session
        return get_session() is not None
    except Exception:
        return False


def _tune_context():
    """Live public-API tune context (``ray.tune.get_context()``), or None.

    Recent Ray hands back a context object even outside a trial, so a
    context only counts as live when it can produce a trial id.
    """
    try:
        from ray import tune
    except Exception:
        return None
    get_ctx = getattr(tune, "get_context", None)
    if get_ctx is None:
        return None
    try:
        ctx = get_ctx()
        if ctx is None or not ctx.get_trial_id():
            return None
        return ctx
    except Exception:
        return None


def _train_session():
    """The modern Train-API session object via the PRIVATE module path.
    Kept as the last probe: releases that drop the internals are served
    by :func:`_tune_context` above."""
    try:
        from ray.train._internal.session import get_session
        return get_session()
    except Exception:
        return None


def in_session() -> bool:
    """True when a real Ray Tune/Train session is live in this process."""
    return (_classic_session_live() or _tune_context() is not None
            or _train_session() is not None)


# -- report -----------------------------------------------------------------

def report(metrics: dict) -> bool:
    """Deliver ``metrics`` to the live real-Ray session.

    Returns False when no real session exists (caller falls through to
    its own error/warning path).  A staged modern-API checkpoint is
    attached and consumed.
    """
    if _classic_session_live():
        from ray import tune
        tune.report(**metrics)
        return True
    if _tune_context() is not None:
        from ray import tune
        if _report_accepts_checkpoint(tune.report):
            return _report_with_staged(
                lambda m, c: tune.report(m, checkpoint=c)
                if c is not None else tune.report(m), metrics)
        # MID-generation Ray: tune.get_context exists but tune.report
        # still has the classic kwargs-only signature — calling it with
        # a positional dict would TypeError.  Prefer the train session
        # (falls through to the branch below, which can attach staged
        # checkpoints); with no train session, deliver a staged
        # checkpoint via the classic dir if it survives, then the
        # metrics classic-style.
        if _train_session() is None:
            _deliver_staged_classic(tune)
            tune.report(**metrics)
            return True
    if _train_session() is not None:
        from ray import train
        return _report_with_staged(lambda m, c: train.report(m, checkpoint=c)
                                   if c is not None else train.report(m),
                                   metrics)
    return False


def _deliver_staged_classic(tune) -> None:
    """Mid-generation last resort for a staged checkpoint: the report
    about to go out is kwargs-only and cannot attach it.  If this Ray
    still ships the classic ``tune.checkpoint_dir``, write the staged
    files there (the reference's own move, tune.py:161-167); otherwise
    warn LOUDLY and drop — silently losing a trial's checkpoints is the
    one unacceptable outcome."""
    staged = getattr(_local, "pending_checkpoint", None)
    if staged is None:
        return
    _local.pending_checkpoint = None
    step = getattr(_local, "pending_step", 0)
    try:
        ckpt_dir = getattr(tune, "checkpoint_dir", None)
        if ckpt_dir is not None:
            with ckpt_dir(step=step) as d:
                for name in os.listdir(staged):
                    shutil.copy2(os.path.join(staged, name),
                                 os.path.join(d, name))
            return
        _log.warning(
            "Staged Tune checkpoint dropped: this Ray generation's "
            "tune.report cannot attach checkpoints and tune.checkpoint_dir "
            "is gone; install a Ray with the modern report signature to "
            "record checkpoints from this callback.")
    finally:
        shutil.rmtree(staged, ignore_errors=True)


def _report_accepts_checkpoint(report_fn) -> bool:
    """True when ``report_fn`` takes a ``checkpoint`` kwarg (the modern
    positional-dict signature).  Mid-generation Ray ships
    ``tune.get_context`` while ``tune.report`` keeps the classic
    kwargs-only signature; probing the signature (instead of catching a
    TypeError mid-call) keeps staged checkpoints from being consumed by
    a call that was never going to deliver them."""
    import inspect
    try:
        params = inspect.signature(report_fn).parameters
    except (TypeError, ValueError):
        return True   # uninspectable builtins: assume modern
    return "checkpoint" in params


def _report_with_staged(report_fn, metrics: dict) -> bool:
    """Shared non-classic delivery: attach and consume any staged
    checkpoint (it can only ride a report in these generations)."""
    staged = getattr(_local, "pending_checkpoint", None)
    _local.pending_checkpoint = None
    if staged is not None:
        checkpoint = _as_train_checkpoint(staged)
        try:
            report_fn(dict(metrics), checkpoint)
        finally:
            shutil.rmtree(staged, ignore_errors=True)
    else:
        report_fn(dict(metrics), None)
    return True


def _as_train_checkpoint(directory: str):
    # same class either way in real Ray; probe the tune alias first so a
    # release that reorganizes ray.train keeps working
    try:
        from ray.tune import Checkpoint
    except Exception:
        from ray.train import Checkpoint
    return Checkpoint.from_directory(directory)


# -- checkpoint -------------------------------------------------------------

def stage_checkpoint(blob: bytes, step: int, filename: str) -> bool:
    """Hand checkpoint bytes to the live real-Ray session.

    Classic API: written straight into ``tune.checkpoint_dir(step)``
    (the reference's exact move, tune.py:161-167).  Modern API: written
    to a temp dir and staged; the next :func:`report` attaches it.
    Returns False when no real session exists.
    """
    if _classic_session_live():
        from ray import tune
        with tune.checkpoint_dir(step=step) as d:
            with open(os.path.join(d, filename), "wb") as f:
                f.write(blob)
        return True
    if _tune_context() is not None or _train_session() is not None:
        prev = getattr(_local, "pending_checkpoint", None)
        if prev is not None:
            # a checkpoint was staged but never reported (standalone
            # checkpoint cadence): the newer one supersedes it.
            _log.warning(
                "Staged Tune checkpoint was replaced before any report "
                "attached it; pair _TuneCheckpointCallback with a report "
                "(TuneReportCheckpointCallback) under the modern Ray "
                "Train API.")
            shutil.rmtree(prev, ignore_errors=True)
        d = tempfile.mkdtemp(prefix=f"rlt_tune_ckpt_{step}_")
        with open(os.path.join(d, filename), "wb") as f:
            f.write(blob)
        _local.pending_checkpoint = d
        _local.pending_step = step   # classic-dir fallback needs it
        return True
    return False


@contextlib.contextmanager
def checkpoint_dir(step: int):
    """Classic-API passthrough used when callers want a directory.  The
    modern Train API has no standalone checkpoint directory — use
    :func:`stage_checkpoint` + :func:`report` there."""
    if not _classic_session_live():
        raise RuntimeError(
            "checkpoint_dir() requires the classic Ray Tune session; "
            "under the modern Ray Train API checkpoints must be "
            "attached to a report.")
    from ray import tune
    with tune.checkpoint_dir(step=step) as d:
        yield d
