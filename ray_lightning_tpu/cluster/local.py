"""Built-in subprocess actor backend.

Replaces Ray core for single-node use (and makes the framework runnable
with zero orchestration dependencies): each actor is a subprocess
connected to the driver over a unix socket, RPC is length-prefixed
cloudpickle, and the worker→driver queue rides the same connection as
unsolicited frames.  This supplies, in-repo, the runtime roles the
reference outsources to Ray's C++ core (actor RPC, object transport,
queue — SURVEY.md §2.2).

Large payloads (the pickled trainer+model, ray.put analog at
ray_ddp.py:331) go through a shared-memory object store: ``put`` writes
the serialized object ONCE to a file under /dev/shm and returns a
:class:`LocalObjectRef`; refs appearing in call arguments are resolved
worker-side by mapping the segment read-only — N workers share the
driver's pages instead of receiving N socket copies (the plasma-store
behavior of Ray, SURVEY.md §2.2 "Ray core" row).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Optional

import cloudpickle

from ray_lightning_tpu.cluster.backend import (
    ActorHandle,
    ClusterBackend,
    Future,
)
from ray_lightning_tpu.cluster.protocol import Connection


class LocalObjectRef:
    """Reference to a shared-memory object-store segment.

    Carries the segment path, so any process on the node can resolve it
    without a driver round-trip (``load``).  The worker call layer
    auto-resolves refs found in call args (worker_main.py), mirroring
    Ray's deref-on-delivery semantics for ObjectRefs.
    """

    __slots__ = ("object_id", "path")

    def __init__(self, object_id: str, path: str):
        self.object_id = object_id
        self.path = path

    def load(self) -> Any:
        import mmap
        with open(self.path, "rb") as f:
            with mmap.mmap(f.fileno(), 0,
                           access=mmap.ACCESS_READ) as m:
                # loads() reads straight from the mapped pages — the
                # only copy is deserialization itself
                return cloudpickle.loads(m)


def resolve_refs(args: tuple, kwargs: Optional[dict] = None):
    """Top-level deref of object refs in call args/kwargs (Ray derefs
    top-level ObjectRefs in both)."""
    out_args = tuple(a.load() if isinstance(a, LocalObjectRef) else a
                     for a in args)
    out_kwargs = {
        k: (v.load() if isinstance(v, LocalObjectRef) else v)
        for k, v in (kwargs or {}).items()}
    return out_args, out_kwargs


class LocalActorHandle(ActorHandle):
    def __init__(self, backend: "LocalBackend", actor_id: str,
                 proc: Optional[subprocess.Popen] = None,
                 log_path: Optional[str] = None):
        self.actor_id = actor_id
        self._backend = backend
        # None only during create_actor: the handle registers in the
        # backend BEFORE the subprocess spawns, so a worker whose hello
        # races ahead of the driver thread still finds its handle
        self._proc = proc
        self.log_path = log_path  # captured worker stdout+stderr
        self._conn: Optional[Connection] = None
        self._conn_ready = threading.Event()
        self._pending: dict[str, Future] = {}
        self._lock = threading.Lock()
        self._dead = False
        self._death_error: Optional[BaseException] = None
        #: monotonic time of the last frame received from this worker
        #: (any type, heartbeats included) — watchdog/failure forensics
        self.last_frame_at: Optional[float] = None

    def _log_tail(self, max_bytes: int = 4096) -> str:
        """Banner-framed tail of the worker's captured output, for
        failure-error messages (Ray surfaces worker logs the same way);
        ``log_tail`` below is the raw-forensics flavor."""
        tail = self.log_tail(max_bytes)
        return f"\n--- worker log tail ({self.log_path}) ---\n{tail}" \
            if tail else ""

    # -- wiring (called by backend accept loop) -------------------------

    def _attach(self, conn: Connection) -> None:
        self._conn = conn
        self._conn_ready.set()
        t = threading.Thread(target=self._reader, daemon=True,
                             name=f"rlt-reader-{self.actor_id}")
        t.start()

    def _reader(self) -> None:
        try:
            while True:
                msg = self._conn.recv()
                self.last_frame_at = time.monotonic()
                kind = msg.get("type")
                if kind == "result":
                    with self._lock:
                        fut = self._pending.pop(msg["call_id"], None)
                    if fut is None:
                        if not msg.get("ok", True):
                            # e.g. constructor failure: no future is
                            # awaiting this id — fail the actor with the
                            # real remote traceback instead of dropping it.
                            self._fail_pending(RemoteActorError(msg["error"]))
                        continue
                    if msg["ok"]:
                        fut.set_result(msg["value"])
                    else:
                        fut.set_error(RemoteActorError(msg["error"]))
                elif kind == "queue":
                    self._backend._queue_push(msg["item"])
                elif kind == "peer":
                    # worker↔worker channel (cluster/peer.py): this
                    # reader thread is per-actor, so routing here keeps
                    # peer traffic flowing while other actors compute
                    self._backend.peer_route(msg["dst"], msg["item"])
        except (ConnectionError, OSError):
            silent = (f"; last frame "
                      f"{time.monotonic() - self.last_frame_at:.1f}s ago"
                      if self.last_frame_at is not None else "")
            self._fail_pending(
                RemoteActorError(
                    f"actor {self.actor_id} died (connection lost); "
                    f"returncode="
                    f"{self._proc.poll() if self._proc else 'unknown'}"
                    f"{silent}{self._log_tail()}"))

    def _fail_pending(self, err: BaseException) -> None:
        self._dead = True
        if self._death_error is None:
            self._death_error = err  # keep the FIRST (root-cause) error
        with self._lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            fut.set_error(self._death_error)

    # -- API -------------------------------------------------------------

    def call(self, method: str, *args, **kwargs) -> Future:
        fut = Future()
        if self._dead:
            fut.set_error(self._death_error or RemoteActorError(
                f"actor {self.actor_id} is dead"))
            return fut
        if not self._conn_ready.wait(timeout=120):
            rc = self._proc.poll() if self._proc else None
            fut.set_error(RemoteActorError(
                f"actor {self.actor_id} never connected; "
                f"{'process alive' if rc is None else f'returncode={rc}'}"
                f"{self._log_tail()}"))
            return fut
        call_id = uuid.uuid4().hex
        with self._lock:
            self._pending[call_id] = fut
        try:
            self._conn.send({"type": "call", "call_id": call_id,
                             "method": method, "args": args,
                             "kwargs": kwargs})
        except (ConnectionError, OSError) as e:
            self._fail_pending(RemoteActorError(str(e)))
        return fut

    def harvest_escrow(self, timeout: float = 15.0):
        """Recovery-escrow fetch over a dedicated ``escrow`` frame: the
        worker's frame-reader thread answers it directly
        (worker_main.py), so a survivor wedged inside a dead collective
        still yields its escrowed state.  The reply rides the normal
        ``result`` routing via a pending future."""
        if self._dead or self._conn is None:
            return None
        fut = Future()
        call_id = uuid.uuid4().hex
        with self._lock:
            self._pending[call_id] = fut
        try:
            self._conn.send({"type": "escrow", "call_id": call_id})
        except (ConnectionError, OSError):
            with self._lock:
                self._pending.pop(call_id, None)
            return None
        try:
            return fut.result(timeout)
        except BaseException:   # noqa: BLE001 - harvest is best-effort
            with self._lock:
                self._pending.pop(call_id, None)
            return None

    def log_tail(self, max_bytes: int = 4096) -> str:
        """Raw tail of the captured worker log (no banner — the flight
        recorder stores it as its own JSON field)."""
        if not self.log_path:
            return ""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode(errors="replace").strip()
        except OSError:
            return ""

    def alive(self) -> Optional[bool]:
        if self._proc is None:
            return None
        return self._proc.poll() is None

    def process_alive(self) -> Optional[bool]:
        # the subprocess poll IS process-precise: a busy worker still
        # reads alive, so this doubles as the strict elastic probe
        return self.alive()

    def kill(self) -> None:
        """Hard-stop the actor (``ray.kill(no_restart=True)`` analog,
        ray_ddp.py:384)."""
        self._dead = True
        if self._conn is not None:
            try:
                self._conn.send({"type": "shutdown"})
            except (ConnectionError, OSError):
                pass
        if self._proc is None:
            return
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5)
        except (subprocess.TimeoutExpired, OSError):
            self._proc.kill()


class RemoteActorError(RuntimeError):
    """An exception raised inside an actor, carried back with its remote
    traceback text (what ``ray.get`` raising does for the reference,
    util.py:61-63)."""


class LocalBackend(ClusterBackend):
    supports_object_store = True  # shm segments, see module docstring
    # actors are subprocesses on THIS node: the driver's persistent
    # compilation-cache dir is directly usable by every worker, so the
    # compile plane shares it via env instead of shipping a seed blob
    shared_filesystem = True

    def __init__(self):
        self._dir = tempfile.mkdtemp(prefix="rlt_cluster_")
        self._sock_path = os.path.join(self._dir, "driver.sock")
        import socket as _socket
        self._listener = _socket.socket(_socket.AF_UNIX,
                                        _socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(64)
        self._actors: dict[str, LocalActorHandle] = {}
        self._objects: dict[str, str] = {}  # object_id -> segment path
        self._queue: list[Any] = []
        self._queue_lock = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rlt-accept")
        self._accept_thread.start()

    # -- accept/queue -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            # read the hello off-thread with a deadline: a connection
            # whose peer dies between connect and hello must not block
            # every other worker's attach (observed as spurious
            # "never connected" timeouts under load)
            threading.Thread(target=self._handshake, args=(sock,),
                             daemon=True, name="rlt-handshake").start()

    def _handshake(self, sock) -> None:
        sock.settimeout(60)
        conn = Connection(sock)
        try:
            hello = conn.recv()
        except (ConnectionError, OSError, TimeoutError):
            try:
                sock.close()
            except OSError:
                pass
            return
        sock.settimeout(None)
        actor_id = hello.get("actor_id")
        handle = self._actors.get(actor_id)
        if handle is not None:
            handle._attach(conn)
        else:
            print(f"[rlt-backend] dropping hello from unknown actor "
                  f"{actor_id!r} (known: {sorted(self._actors)})",
                  file=sys.stderr, flush=True)

    def _queue_push(self, item: Any) -> None:
        with self._queue_lock:
            self._queue.append(item)

    def queue_get_nowait(self):
        with self._queue_lock:
            return self._queue.pop(0) if self._queue else None

    # -- actors -----------------------------------------------------------

    def peer_route(self, dst_actor_id: str, item) -> bool:
        """Route one peer payload to ``dst_actor_id``'s connection
        (frame delivered by the worker's reader thread straight into
        its peer mailbox — worker_main.py)."""
        handle = self._actors.get(dst_actor_id)
        if handle is None or handle._conn is None:
            print(f"[rlt-backend] dropping peer payload for unknown or "
                  f"unattached actor {dst_actor_id!r}",
                  file=sys.stderr, flush=True)
            return False
        try:
            handle._conn.send({"type": "peer", "item": item})
            return True
        except (ConnectionError, OSError):
            return False

    def create_actor(self, actor_cls: type, *args,
                     env: Optional[dict[str, str]] = None,
                     resources: Optional[dict[str, float]] = None,
                     name: Optional[str] = None,
                     max_concurrency: Optional[int] = None,
                     **kwargs) -> ActorHandle:
        del max_concurrency   # peer frames ride the reader thread here
        actor_id = name or f"actor-{uuid.uuid4().hex[:8]}"
        spec_path = os.path.join(self._dir, f"{actor_id}.spec")
        with open(spec_path, "wb") as f:
            f.write(cloudpickle.dumps((actor_cls, args, kwargs)))
        child_env = {**os.environ, **(env or {})}
        child_env["RLT_DRIVER_SOCKET"] = self._sock_path
        child_env["RLT_ACTOR_ID"] = actor_id
        child_env["RLT_ACTOR_SPEC"] = spec_path
        # capture worker output per actor; surfaced in failure errors
        # (the log-tail diagnostics Ray gives for dead workers)
        log_path = os.path.join(self._dir, f"{actor_id}.log")
        log_file = open(log_path, "ab")
        # register BEFORE spawning: on a loaded box the worker's hello
        # can reach the handshake thread before this thread resumes
        # after Popen, and an unregistered id would drop the connection
        handle = LocalActorHandle(self, actor_id, log_path=log_path)
        self._actors[actor_id] = handle
        try:
            handle._proc = subprocess.Popen(
                [sys.executable, "-m",
                 "ray_lightning_tpu.cluster.worker_main"],
                env=child_env, cwd=os.getcwd(),
                stdout=log_file, stderr=subprocess.STDOUT)
        except BaseException:
            self._actors.pop(actor_id, None)
            raise
        finally:
            log_file.close()  # the child holds its own descriptor
        return handle

    # -- shared-memory object store ---------------------------------------

    @staticmethod
    def _shm_dir() -> str:
        d = "/dev/shm"
        return d if os.path.isdir(d) and os.access(d, os.W_OK) \
            else tempfile.gettempdir()

    def put(self, obj: Any) -> LocalObjectRef:
        oid = uuid.uuid4().hex
        path = os.path.join(self._shm_dir(), f"rlt-obj-{oid}")
        blob = cloudpickle.dumps(obj)
        tmp = f"{path}.{os.getpid()}.tmp"
        # 0600: /dev/shm is world-listable; the payload is the pickled
        # trainer+model and must not be readable by other local users
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # visible to workers only when complete
        except BaseException:
            # never leak a partial multi-GB segment in shm (ENOSPC etc.)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._objects[oid] = path
        return LocalObjectRef(oid, path)

    def get(self, ref: Any) -> Any:
        if isinstance(ref, LocalObjectRef):
            return ref.load()
        if isinstance(ref, Future):
            return ref.result()
        return ref

    def free(self, ref: LocalObjectRef) -> None:
        """Drop a stored object's segment (plugins free the shipped
        payload after the workers finish)."""
        path = self._objects.pop(ref.object_id, None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    def available_resources(self) -> dict[str, float]:
        return {"CPU": float(os.cpu_count() or 1)}

    def shutdown(self) -> None:
        self._closed = True
        for handle in list(self._actors.values()):
            handle.kill()
        self._actors.clear()
        for path in self._objects.values():
            try:
                os.unlink(path)
            except OSError:
                pass
        self._objects.clear()
        try:
            self._listener.close()
        except OSError:
            pass
