"""Built-in subprocess actor backend.

Replaces Ray core for single-node use (and makes the framework runnable
with zero orchestration dependencies): each actor is a subprocess
connected to the driver over a unix socket, RPC is length-prefixed
cloudpickle, and the worker→driver queue rides the same connection as
unsolicited frames.  This supplies, in-repo, the runtime roles the
reference outsources to Ray's C++ core (actor RPC, object transport,
queue — SURVEY.md §2.2); an optional C++ shared-memory object store
accelerates large-payload transport (native/, used when built).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import uuid
from typing import Any, Optional

import cloudpickle

from ray_lightning_tpu.cluster.backend import (
    ActorHandle,
    ClusterBackend,
    Future,
)
from ray_lightning_tpu.cluster.protocol import Connection


class LocalObjectRef:
    """Reference into the driver-side object store."""

    __slots__ = ("object_id",)

    def __init__(self, object_id: str):
        self.object_id = object_id


class LocalActorHandle(ActorHandle):
    def __init__(self, backend: "LocalBackend", actor_id: str,
                 proc: subprocess.Popen):
        self.actor_id = actor_id
        self._backend = backend
        self._proc = proc
        self._conn: Optional[Connection] = None
        self._conn_ready = threading.Event()
        self._pending: dict[str, Future] = {}
        self._lock = threading.Lock()
        self._dead = False
        self._death_error: Optional[BaseException] = None

    # -- wiring (called by backend accept loop) -------------------------

    def _attach(self, conn: Connection) -> None:
        self._conn = conn
        self._conn_ready.set()
        t = threading.Thread(target=self._reader, daemon=True,
                             name=f"rlt-reader-{self.actor_id}")
        t.start()

    def _reader(self) -> None:
        try:
            while True:
                msg = self._conn.recv()
                kind = msg.get("type")
                if kind == "result":
                    with self._lock:
                        fut = self._pending.pop(msg["call_id"], None)
                    if fut is None:
                        if not msg.get("ok", True):
                            # e.g. constructor failure: no future is
                            # awaiting this id — fail the actor with the
                            # real remote traceback instead of dropping it.
                            self._fail_pending(RemoteActorError(msg["error"]))
                        continue
                    if msg["ok"]:
                        fut.set_result(msg["value"])
                    else:
                        fut.set_error(RemoteActorError(msg["error"]))
                elif kind == "queue":
                    self._backend._queue_push(msg["item"])
        except (ConnectionError, OSError):
            self._fail_pending(
                RemoteActorError(
                    f"actor {self.actor_id} died (connection lost); "
                    f"returncode={self._proc.poll()}"))

    def _fail_pending(self, err: BaseException) -> None:
        self._dead = True
        if self._death_error is None:
            self._death_error = err  # keep the FIRST (root-cause) error
        with self._lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            fut.set_error(self._death_error)

    # -- API -------------------------------------------------------------

    def call(self, method: str, *args, **kwargs) -> Future:
        fut = Future()
        if self._dead:
            fut.set_error(self._death_error or RemoteActorError(
                f"actor {self.actor_id} is dead"))
            return fut
        if not self._conn_ready.wait(timeout=120):
            fut.set_error(RemoteActorError(
                f"actor {self.actor_id} never connected"))
            return fut
        call_id = uuid.uuid4().hex
        with self._lock:
            self._pending[call_id] = fut
        try:
            self._conn.send({"type": "call", "call_id": call_id,
                             "method": method, "args": args,
                             "kwargs": kwargs})
        except (ConnectionError, OSError) as e:
            self._fail_pending(RemoteActorError(str(e)))
        return fut

    def kill(self) -> None:
        """Hard-stop the actor (``ray.kill(no_restart=True)`` analog,
        ray_ddp.py:384)."""
        self._dead = True
        if self._conn is not None:
            try:
                self._conn.send({"type": "shutdown"})
            except (ConnectionError, OSError):
                pass
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5)
        except (subprocess.TimeoutExpired, OSError):
            self._proc.kill()


class RemoteActorError(RuntimeError):
    """An exception raised inside an actor, carried back with its remote
    traceback text (what ``ray.get`` raising does for the reference,
    util.py:61-63)."""


class LocalBackend(ClusterBackend):
    def __init__(self):
        self._dir = tempfile.mkdtemp(prefix="rlt_cluster_")
        self._sock_path = os.path.join(self._dir, "driver.sock")
        import socket as _socket
        self._listener = _socket.socket(_socket.AF_UNIX,
                                        _socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(64)
        self._actors: dict[str, LocalActorHandle] = {}
        self._objects: dict[str, bytes] = {}
        self._queue: list[Any] = []
        self._queue_lock = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rlt-accept")
        self._accept_thread.start()

    # -- accept/queue -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = Connection(sock)
            try:
                hello = conn.recv()
            except (ConnectionError, OSError):
                continue
            handle = self._actors.get(hello.get("actor_id"))
            if handle is not None:
                handle._attach(conn)

    def _queue_push(self, item: Any) -> None:
        with self._queue_lock:
            self._queue.append(item)

    def queue_get_nowait(self):
        with self._queue_lock:
            return self._queue.pop(0) if self._queue else None

    # -- actors -----------------------------------------------------------

    def create_actor(self, actor_cls: type, *args,
                     env: Optional[dict[str, str]] = None,
                     resources: Optional[dict[str, float]] = None,
                     name: Optional[str] = None, **kwargs) -> ActorHandle:
        actor_id = name or f"actor-{uuid.uuid4().hex[:8]}"
        spec_path = os.path.join(self._dir, f"{actor_id}.spec")
        with open(spec_path, "wb") as f:
            f.write(cloudpickle.dumps((actor_cls, args, kwargs)))
        child_env = {**os.environ, **(env or {})}
        child_env["RLT_DRIVER_SOCKET"] = self._sock_path
        child_env["RLT_ACTOR_ID"] = actor_id
        child_env["RLT_ACTOR_SPEC"] = spec_path
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_lightning_tpu.cluster.worker_main"],
            env=child_env, cwd=os.getcwd())
        handle = LocalActorHandle(self, actor_id, proc)
        self._actors[actor_id] = handle
        return handle

    # -- object store -----------------------------------------------------

    def put(self, obj: Any) -> LocalObjectRef:
        oid = uuid.uuid4().hex
        self._objects[oid] = cloudpickle.dumps(obj)
        return LocalObjectRef(oid)

    def get(self, ref: Any) -> Any:
        if isinstance(ref, LocalObjectRef):
            return cloudpickle.loads(self._objects[ref.object_id])
        if isinstance(ref, Future):
            return ref.result()
        return ref

    def resolve_ref_payload(self, object_id: str) -> bytes:
        return self._objects[object_id]

    def available_resources(self) -> dict[str, float]:
        return {"CPU": float(os.cpu_count() or 1)}

    def shutdown(self) -> None:
        self._closed = True
        for handle in list(self._actors.values()):
            handle.kill()
        self._actors.clear()
        self._objects.clear()
        try:
            self._listener.close()
        except OSError:
            pass
