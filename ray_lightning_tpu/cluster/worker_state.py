"""Per-worker-process connection state.

Lives in its own module (not worker_main) because worker_main executes
as ``__main__`` under ``python -m`` — a module-level global there would
be invisible to code importing ``ray_lightning_tpu.cluster.worker_main``
(two module objects).  Everything that needs the driver connection goes
through here.
"""

from __future__ import annotations

from typing import Optional

from ray_lightning_tpu.cluster.protocol import Connection

_conn: Optional[Connection] = None


def set_conn(conn: Optional[Connection]) -> None:
    global _conn
    _conn = conn


def get_conn() -> Optional[Connection]:
    return _conn


def queue_send(item) -> None:
    """Push an item onto the driver-side queue from inside an actor."""
    if _conn is None:
        raise RuntimeError("queue_send outside of a worker process")
    _conn.send({"type": "queue", "item": item})
