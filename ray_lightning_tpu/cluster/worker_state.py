"""Per-worker-process connection state.

Lives in its own module (not worker_main) because worker_main executes
as ``__main__`` under ``python -m`` — a module-level global there would
be invisible to code importing ``ray_lightning_tpu.cluster.worker_main``
(two module objects).  Everything that needs the driver connection goes
through here.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from ray_lightning_tpu.cluster.peer import Mailbox
from ray_lightning_tpu.cluster.protocol import Connection

_log = logging.getLogger(__name__)

_conn: Optional[Connection] = None
_peer_mailbox = Mailbox()

_escrow_lock = threading.Lock()
_escrow: Optional[dict] = None
_peer_drop = 0


def set_conn(conn: Optional[Connection]) -> None:
    global _conn
    _conn = conn


def get_conn() -> Optional[Connection]:
    return _conn


def queue_send(item) -> None:
    """Push an item onto the driver-side queue from inside an actor."""
    if _conn is None:
        raise RuntimeError("queue_send outside of a worker process")
    _conn.send({"type": "queue", "item": item})


# -- worker↔worker peer channel (cluster/peer.py) ---------------------------


def peer_mailbox() -> Mailbox:
    """This worker process's peer-payload mailbox.  Fed by
    worker_main's frame reader (builtin backend ``peer`` frames) or by
    :func:`peer_push` (Ray ``__rlt_peer_deliver__`` calls)."""
    return _peer_mailbox


def peer_push(item: dict) -> None:
    """Deposit an inbound peer payload ``{"tag": ..., "wire": ...}``.
    An armed ``peerdrop`` fault (elastic/faults.py) swallows the frame
    here — the lossy-fabric chaos case, receiver-side so both backends'
    transports are covered."""
    global _peer_drop
    with _escrow_lock:
        if _peer_drop > 0:
            _peer_drop -= 1
            remaining = _peer_drop
            dropped = True
        else:
            dropped = False
    if dropped:
        _log.warning("peerdrop fault: dropping inbound peer frame "
                     "%r (%d more to drop)", item.get("tag"), remaining)
        return
    _peer_mailbox.put(tuple(item["tag"]), item["wire"])


def arm_peer_drop(count: int) -> None:
    """Arm the ``peerdrop`` chaos fault: swallow the next ``count``
    inbound peer frames on this process."""
    global _peer_drop
    with _escrow_lock:
        _peer_drop += max(0, int(count))


def peer_drop_pending() -> int:
    with _escrow_lock:
        return _peer_drop


def peer_send(dst_actor_name: str, item: dict) -> None:
    """Send a peer payload to another worker by actor name.

    Builtin backend: a ``peer`` frame on the driver socket, routed by
    the driver to the destination's connection.  Ray backend (no
    driver socket in this process): resolve the named actor and call
    its ``__rlt_peer_deliver__`` (the destination must be created with
    ``max_concurrency >= 2`` — cluster/peer.py).
    """
    if _conn is not None:
        _conn.send({"type": "peer", "dst": dst_actor_name, "item": item})
        return
    try:
        import ray
    except ImportError:   # pragma: no cover - no transport available
        raise RuntimeError(
            "peer_send outside of a worker process (no driver socket, "
            "no Ray runtime)")
    ray.get(ray.get_actor(dst_actor_name).__rlt_peer_deliver__
            .remote(item))


# -- recovery escrow (elastic/redundancy.py) --------------------------------


def escrow_set(item: Optional[dict]) -> None:
    """Deposit this process's latest recovery escrow (the elastic
    parity tick).  One cell, latest wins — recovery only ever wants the
    most recent completed tick."""
    global _escrow
    with _escrow_lock:
        _escrow = item


def escrow_export() -> Optional[dict]:
    """The latest escrow, served to the driver's harvest — called from
    the frame-reader thread (worker_main) or a concurrent Ray method,
    so it must never touch the (possibly wedged) main thread."""
    with _escrow_lock:
        return _escrow


def escrow_clear() -> None:
    escrow_set(None)


def reset_for_tests() -> None:
    """Clear process-global chaos/escrow state between in-process
    tests."""
    global _peer_drop
    with _escrow_lock:
        _peer_drop = 0
    escrow_clear()


# typing helper for the escrow payload (driver-side)
Escrow = dict[str, Any]
