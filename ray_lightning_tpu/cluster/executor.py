"""Generic remote executor actor (``RayExecutor`` parity,
ray_ddp.py:38-63): run arbitrary functions, set env vars, report
topology facts.  The same class runs under both the built-in backend and
real Ray (it has no backend-specific state)."""

from __future__ import annotations

import os
from typing import Callable, Optional

from ray_lightning_tpu.cluster.protocol import find_free_port, node_ip


class RLTExecutor:
    """One instance per worker process (per TPU host)."""

    def __init__(self, env: Optional[dict] = None):
        if env:
            self.set_env_vars(env)

    # -- generic execution (ray_ddp.py:61-63 analog) ---------------------

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    # -- env plumbing (ray_ddp.py:44-55 analog) --------------------------

    def set_env_var(self, key: str, value: str) -> None:
        os.environ[key] = str(value)

    def set_env_vars(self, env: dict) -> None:
        for k, v in env.items():
            self.set_env_var(k, v)

    # -- topology discovery (ray_ddp.py:57-63, :282-306 analog) ----------

    def get_node_ip(self) -> str:
        return node_ip()

    def get_free_port(self) -> int:
        return find_free_port()

    def get_node_and_device_info(self) -> dict:
        """Node identity + local accelerator inventory.  The TPU analog of
        ``get_node_and_gpu_ids`` (ray_ddp.py:58-63): chip counts come from
        the JAX runtime *if already initialized*, else env hints — the
        driver uses this for topology bookkeeping only."""
        info = {"ip": node_ip(), "pid": os.getpid()}
        count = os.environ.get("RLT_NUM_LOCAL_DEVICES")
        if count is not None:
            info["num_local_devices"] = int(count)
        return info

    def ping(self) -> str:
        return "pong"

    # -- peer channel + recovery escrow (Ray transport) -------------------
    # On the builtin backend peer frames and escrow harvests ride the
    # worker's frame-reader thread; under Ray they arrive as CONCURRENT
    # actor method calls (the plugin creates executors with
    # max_concurrency >= 2), so both work while the main call computes.

    def __rlt_peer_deliver__(self, item: dict) -> None:
        from ray_lightning_tpu.cluster import worker_state
        worker_state.peer_push(item)

    def __rlt_escrow_export__(self) -> Optional[dict]:
        from ray_lightning_tpu.cluster import worker_state
        return worker_state.escrow_export()
