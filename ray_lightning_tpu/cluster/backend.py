"""Cluster backend abstraction.

The reference hard-depends on Ray core for actor placement, object
transport and queues (SURVEY.md §2.2 "Ray core" row).  Here those roles
sit behind one small interface with two implementations:

- :class:`~ray_lightning_tpu.cluster.local.LocalBackend` — built-in,
  zero-dependency subprocess actors (always available; used by tests the
  way the reference tests run against a local ``ray.init``).
- ``RayBackend`` (cluster/ray_backend.py) — real Ray actors with TPU
  resource labels, used automatically when Ray is importable and
  connected.

Only control, pickled specs and metrics ride this plane — gradients never
do (they ride ICI/DCN via XLA collectives), matching the reference's
"Ray is never on the gradient path" invariant (SURVEY.md §3.1).
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class Future:
    """Resolvable handle for an in-flight actor call (ObjectRef analog)."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("actor call timed out")
        if self._error is not None:
            raise self._error
        return self._value


class ActorHandle:
    """Handle to a remote actor; ``call`` is async, returning a Future."""

    actor_id: str

    def call(self, method: str, *args, **kwargs) -> Future:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def alive(self) -> Optional[bool]:
        """Cheap liveness probe for watchdog diagnostics (telemetry/):
        True/False when the backend can tell, None when it cannot.
        May read a wedged-but-responsive-process actor as not-alive —
        exactly what a watchdog should report."""
        return None

    def process_alive(self) -> Optional[bool]:
        """STRICT process-level liveness for the elastic shrink
        classifier (elastic/driver.py): True/False only when the
        backend can answer precisely — a busy-but-alive actor MUST
        read True here (unlike :meth:`alive`, whose ping-style probes
        time out on busy actors), because a False verdict turns a
        failure into a restartable death.  None when unknown."""
        return None

    def log_tail(self, max_bytes: int = 4096) -> str:
        """Tail of the worker's captured output for forensic context —
        the crash flight recorder (telemetry/flight.py) attaches it to
        ``flight_<rank>.json`` so the dead rank's own log lines sit
        next to its last spans.  Empty when the backend does not
        capture worker output (real Ray surfaces logs its own way)."""
        return ""

    def harvest_escrow(self, timeout: float = 15.0) -> Optional[dict]:
        """Best-effort fetch of the worker's recovery escrow
        (cluster/worker_state.py, deposited by the elastic parity tick)
        WITHOUT going through the main-thread call queue — at harvest
        time the survivor is usually wedged in a collective whose peer
        just died.  The builtin backend answers from the worker's
        frame-reader thread; Ray from a concurrent actor method.  None
        when the backend cannot harvest, the worker never escrowed, or
        the fetch times out — the elastic driver then falls back to
        snapshot replay."""
        del timeout
        return None


class ClusterBackend:
    """Actor lifecycle + object transport + worker→driver queue."""

    #: True when ``put`` stores into a shared object store that actors can
    #: dereference (fan-out ships the payload once instead of per-worker).
    supports_object_store: bool = False

    #: True when every actor sees the driver's filesystem (same node /
    #: shared mount).  The compile plane branches on this: shared-FS
    #: backends point workers at the driver's persistent-compilation-
    #: cache dir directly; others get a packed seed of it shipped
    #: through the object store (compile/shipping.py).
    shared_filesystem: bool = False

    def create_actor(
        self,
        actor_cls: type,
        *args,
        env: Optional[dict[str, str]] = None,
        resources: Optional[dict[str, float]] = None,
        name: Optional[str] = None,
        max_concurrency: Optional[int] = None,
        **kwargs,
    ) -> ActorHandle:
        """``max_concurrency`` matters to actors on the worker↔worker
        peer channel (cluster/peer.py): Ray delivers peer payloads as
        concurrent method calls, so receivers need >= 2; the builtin
        backend delivers via its frame reader thread and ignores it."""
        raise NotImplementedError

    def put(self, obj: Any) -> Any:
        """Store an object once for fan-out to actors (ray.put analog,
        ray_ddp.py:331)."""
        raise NotImplementedError

    def get(self, ref: Any) -> Any:
        raise NotImplementedError

    def free(self, ref: Any) -> None:
        """Release a stored object when the fan-out is done.  Default
        no-op: reference-counted stores (Ray) reclaim on their own;
        explicit stores (LocalBackend shm segments) override."""

    def queue_get_nowait(self):
        """Pop one worker→driver queue item or None."""
        raise NotImplementedError

    def peer_route(self, dst_actor_id: str, item) -> bool:
        """Driver-side hop of the worker↔worker peer channel
        (cluster/peer.py): deliver ``item`` to ``dst_actor_id``'s
        process.  Backends whose workers reach each other directly
        (Ray named actors) never call this; the builtin backend routes
        through the driver socket fan-in.  Returns False when the
        destination is unknown (receiver-side timeouts do the
        failure naming)."""
        del dst_actor_id, item
        return False

    def available_resources(self) -> dict[str, float]:
        return {}

    def shutdown(self) -> None:
        raise NotImplementedError


_backend_lock = threading.Lock()
_backend: Optional[ClusterBackend] = None


def get_backend(prefer_ray: bool = True) -> ClusterBackend:
    """Return the process-wide backend, creating one if needed.

    Selection order: the ``RLT_BACKEND`` env var when set (``ray`` —
    require a real Ray runtime, error if not importable; ``local`` —
    force the built-in backend even when Ray is present); otherwise
    prefer a real Ray runtime when importable (and initialize it,
    matching ``ray.init()``-if-needed at ray_ddp.py:125-126), falling
    back to the built-in local backend.
    """
    import os

    global _backend
    with _backend_lock:
        if _backend is not None:
            return _backend
        choice = os.environ.get("RLT_BACKEND", "").strip().lower()
        if choice and choice not in ("ray", "local"):
            raise ValueError(
                f"RLT_BACKEND={choice!r}; expected 'ray' or 'local'")
        if choice == "ray" or (choice != "local" and prefer_ray):
            from ray_lightning_tpu.utils.imports import RAY_AVAILABLE
            if not RAY_AVAILABLE and choice == "ray":
                raise ImportError(
                    "RLT_BACKEND=ray but Ray is not installed; "
                    "pip install 'ray[tune]' or unset RLT_BACKEND.")
            if RAY_AVAILABLE:
                from ray_lightning_tpu.cluster.ray_backend import RayBackend
                _backend = RayBackend()
                return _backend
        from ray_lightning_tpu.cluster.local import LocalBackend
        _backend = LocalBackend()
        return _backend


def set_backend(backend: Optional[ClusterBackend]) -> None:
    """Install (or clear) the process-wide backend (tests use this)."""
    global _backend
    with _backend_lock:
        _backend = backend
