"""Entry point of a built-in-backend actor subprocess.

Connects back to the driver's unix socket, constructs the actor instance
from its pickled spec, then serves calls sequentially on the main thread
(JAX/libtpu want the main thread).  Unsolicited ``queue`` frames may be
emitted mid-call through :func:`queue_send` — that is the transport under
``session.put_queue`` (the reference's ray.util.queue relay,
session.py:17-24 / util.py:47-52).

Inbound frames are drained by a dedicated reader thread: ``call`` /
``shutdown`` frames queue for the main thread (execution stays
sequential), while ``peer`` frames — the worker↔worker channel
(cluster/peer.py) — deposit straight into this process's peer mailbox.
Without the split, a peer payload could not arrive while the main
thread is busy executing the very call that wants to receive it (the
MPMD stage actors' shape).
"""

from __future__ import annotations

import os
import queue
import socket
import sys
import threading
import time
import traceback

import cloudpickle

from ray_lightning_tpu.cluster import worker_state
from ray_lightning_tpu.cluster.protocol import Connection


def _trace(msg: str) -> None:
    """Milestone line in the worker's captured log (cluster/local.py
    redirects stdout there); read back by _log_tail on failures."""
    print(f"[worker {os.getpid()} {time.strftime('%H:%M:%S')}] {msg}",
          flush=True)


def main() -> int:
    sock_path = os.environ["RLT_DRIVER_SOCKET"]
    actor_id = os.environ["RLT_ACTOR_ID"]
    spec_path = os.environ["RLT_ACTOR_SPEC"]
    _trace(f"start {actor_id}")

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    _conn = Connection(sock)
    worker_state.set_conn(_conn)
    _conn.send({"type": "hello", "actor_id": actor_id})
    _trace("hello sent")

    if os.environ.get("RLT_TELEMETRY") == "1":
        # process-level heartbeats over the queue channel, from BEFORE
        # any heavy import: a worker that wedges during jax/libtpu init
        # is already visible to the driver watchdog.  Rank is re-read
        # from RLT_PROCESS_ID per beat (assigned after spawn).
        from ray_lightning_tpu.telemetry.heartbeat import (
            start_process_heartbeat)
        start_process_heartbeat(
            worker_state.queue_send,
            interval=float(os.environ.get("RLT_HEARTBEAT_INTERVAL", "5")),
            actor_id=actor_id)
        _trace("heartbeats started")

    with open(spec_path, "rb") as f:
        actor_cls, args, kwargs = cloudpickle.loads(f.read())
    try:
        actor = actor_cls(*args, **kwargs)
    except BaseException:
        _conn.send({"type": "result", "call_id": "__construct__",
                    "ok": False, "error": traceback.format_exc()})
        return 1
    _trace("actor constructed; serving")

    # frame reader (module docstring): peer frames bypass the main
    # thread's call queue so receives inside a running call make
    # progress; everything else serializes through the inbox
    inbox: "queue.Queue" = queue.Queue()

    def _reader() -> None:
        while True:
            try:
                msg = _conn.recv()
            except (ConnectionError, OSError) as e:
                _trace(f"connection closed ({type(e).__name__}: {e}); "
                       f"exiting")
                inbox.put(None)
                return
            if msg.get("type") == "peer":
                worker_state.peer_push(msg["item"])
            elif msg.get("type") == "escrow":
                # recovery-escrow harvest (elastic/redundancy.py):
                # answered HERE, on the reader thread, because at
                # harvest time the main thread is typically wedged in a
                # collective whose peer just died — the escrow cell is
                # the survivors' state the driver must not lose
                try:
                    _conn.send({"type": "result",
                                "call_id": msg["call_id"], "ok": True,
                                "value": worker_state.escrow_export()})
                except (ConnectionError, OSError):
                    pass
            else:
                inbox.put(msg)

    threading.Thread(target=_reader, daemon=True,
                     name="rlt-worker-reader").start()

    while True:
        msg = inbox.get()
        if msg is None:
            return 0
        kind = msg.get("type")
        if kind == "shutdown":
            return 0
        if kind != "call":
            continue
        call_id = msg["call_id"]
        try:
            from ray_lightning_tpu.cluster.local import resolve_refs
            method = getattr(actor, msg["method"])
            # object refs in args/kwargs resolve here, from shared
            # memory — the payload bytes never ride the socket (Ray
            # deref-on-delivery parity)
            args, kwargs = resolve_refs(msg.get("args", ()),
                                        msg.get("kwargs", {}))
            value = method(*args, **kwargs)
            _conn.send({"type": "result", "call_id": call_id, "ok": True,
                        "value": value})
        except BaseException:
            _conn.send({"type": "result", "call_id": call_id, "ok": False,
                        "error": traceback.format_exc()})


if __name__ == "__main__":
    sys.exit(main())
