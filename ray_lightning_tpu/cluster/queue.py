"""Worker→driver queue endpoints.

Reference equivalent: ``ray.util.queue.Queue`` created in
``execution_loop`` (ray_ddp.py:335-338) and drained by
``process_results`` (util.py:47-68).  Under the built-in backend the
queue rides the actor's socket as unsolicited frames; under Ray it is a
real ``ray.util.queue.Queue``.  Either way the worker-side object is a
picklable proxy with ``put``.
"""

from __future__ import annotations

from typing import Any


class WorkerQueueProxy:
    """Picklable worker-side queue handle (built-in backend).

    Inside an actor subprocess, ``put`` routes through the worker's
    driver connection (worker_main.queue_send).
    """

    def put(self, item: Any) -> None:
        from ray_lightning_tpu.cluster import worker_state
        worker_state.queue_send(item)


class RayQueueProxy:
    """Adapter giving ray.util.queue.Queue the same ``put`` surface."""

    def __init__(self, ray_queue):
        self._q = ray_queue

    def put(self, item: Any) -> None:
        self._q.put(item)
