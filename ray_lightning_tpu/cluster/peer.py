"""Worker↔worker peer channel primitives.

The cluster backends' third data plane, next to actor RPC
(driver→worker) and the queue (worker→driver): tagged payloads
travelling BETWEEN workers.  The MPMD pipeline's activation exchange
(ray_lightning_tpu/mpmd/channel.py) is the first consumer.

Transport per backend:

- builtin (cluster/local.py): the sender emits a ``peer`` frame on its
  driver socket; the driver's per-actor reader routes it to the
  destination actor's connection, whose frame-reader thread
  (cluster/worker_main.py) deposits it into this process's
  :func:`peer mailbox <ray_lightning_tpu.cluster.worker_state.peer_mailbox>`
  without waiting for the main thread (which may be busy executing the
  receiving actor's current call — that's the point).
- Ray (cluster/ray_backend.py): the sender resolves the destination's
  named actor handle and calls its ``__rlt_peer_deliver__`` method;
  the destination actor must be created with ``max_concurrency >= 2``
  so the delivery thread runs beside the busy main call.

:class:`Mailbox` is the receiving side either way: a tag-addressed
blocking store — out-of-order delivery is harmless by construction (a
receive blocks on ITS tag), and a receive that outlives its timeout
raises :class:`PeerTimeout` naming the waiter and the missing payload
instead of hanging the fleet.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class PeerTimeout(RuntimeError):
    """A worker waited longer than the dead-peer bound for a payload."""


class Mailbox:
    """Thread-safe tag-addressed blocking store."""

    def __init__(self):
        self._items: dict = {}
        self._cond = threading.Condition()

    def put(self, tag: tuple, payload: Any) -> None:
        with self._cond:
            self._items[tag] = payload
            self._cond.notify_all()

    def take(self, tag: tuple, timeout: float, *, who: str = "worker",
             src: str = "peer") -> Any:
        deadline = time.monotonic() + timeout
        with self._cond:
            while tag not in self._items:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PeerTimeout(
                        f"{who} timed out after {timeout:.1f}s waiting "
                        f"for peer payload {tag!r} from {src} — peer "
                        f"dead or schedules desynchronized")
                self._cond.wait(remaining)
            return self._items.pop(tag)

    def __len__(self):
        with self._cond:
            return len(self._items)
