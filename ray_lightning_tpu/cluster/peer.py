"""Worker↔worker peer channel primitives.

The cluster backends' third data plane, next to actor RPC
(driver→worker) and the queue (worker→driver): tagged payloads
travelling BETWEEN workers.  The MPMD pipeline's activation exchange
(ray_lightning_tpu/mpmd/channel.py) and the elastic plane's parity
ticks (elastic/redundancy.py) are the consumers.

Transport per backend:

- builtin (cluster/local.py): the sender emits a ``peer`` frame on its
  driver socket; the driver's per-actor reader routes it to the
  destination actor's connection, whose frame-reader thread
  (cluster/worker_main.py) deposits it into this process's
  :func:`peer mailbox <ray_lightning_tpu.cluster.worker_state.peer_mailbox>`
  without waiting for the main thread (which may be busy executing the
  receiving actor's current call — that's the point).
- Ray (cluster/ray_backend.py): the sender resolves the destination's
  named actor handle and calls its ``__rlt_peer_deliver__`` method;
  the destination actor must be created with ``max_concurrency >= 2``
  so the delivery thread runs beside the busy main call.

:class:`Mailbox` is the receiving side either way: a tag-addressed
blocking store — out-of-order delivery is harmless by construction (a
receive blocks on ITS tag), and a receive that outlives its timeout
raises :class:`PeerTimeout` naming the waiter and the missing payload
instead of hanging the fleet.

**Retry/backoff** (``RLT_PEER_RETRIES`` / ``RLT_PEER_BACKOFF_S``):
by default a receive makes exactly ONE attempt of ``timeout`` seconds
(today's behavior).  With ``RLT_PEER_RETRIES=N`` it re-waits up to N
more times with exponential backoff between attempts, emitting a
``peer_retry`` span per re-attempt so the crash flight recorder shows
the retry trail next to the rank's last steps; the final
:class:`PeerTimeout` names the attempt count.  Retries absorb
transient delivery loss (a dropped frame whose sender re-emits, a
driver-hop hiccup) without changing the dead-peer bound for
single-attempt callers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any


class PeerTimeout(RuntimeError):
    """A worker waited longer than the dead-peer bound for a payload."""


ENV_PEER_RETRIES = "RLT_PEER_RETRIES"
ENV_PEER_BACKOFF_S = "RLT_PEER_BACKOFF_S"


def _retry_policy() -> tuple:
    """(extra_attempts, base_backoff_s) from the env; (0, 0.0) —
    today's single-attempt behavior — unless explicitly raised."""
    try:
        retries = int(os.environ.get(ENV_PEER_RETRIES, "0") or 0)
    except ValueError:
        retries = 0
    try:
        backoff = float(os.environ.get(ENV_PEER_BACKOFF_S, "0.05") or 0.05)
    except ValueError:
        backoff = 0.05
    return max(0, retries), max(0.0, backoff)


class Mailbox:
    """Thread-safe tag-addressed blocking store."""

    def __init__(self):
        self._items: dict = {}
        self._cond = threading.Condition()

    def put(self, tag: tuple, payload: Any) -> None:
        with self._cond:
            self._items[tag] = payload
            self._cond.notify_all()

    def _take_one(self, tag: tuple, timeout: float):
        """One bounded wait; returns (found, payload)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while tag not in self._items:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False, None
                self._cond.wait(remaining)
            return True, self._items.pop(tag)

    def take(self, tag: tuple, timeout: float, *, who: str = "worker",
             src: str = "peer") -> Any:
        retries, backoff = _retry_policy()
        for attempt in range(retries + 1):
            found, payload = self._take_one(tag, timeout)
            if found:
                return payload
            if attempt >= retries:
                break
            # record the retry in the span stream (the flight recorder
            # shows the trail) and the metrics plane; both no-op when
            # telemetry is off
            from ray_lightning_tpu.telemetry import metrics as _metrics
            from ray_lightning_tpu.telemetry.spans import span
            reg = _metrics.get_registry()
            if reg is not None:
                reg.counter("rlt_peer_retries_total").inc()
            delay = backoff * (2 ** attempt)
            with span("peer_retry", tag=repr(tag), attempt=attempt + 1,
                      of=retries, backoff_s=delay):
                time.sleep(delay)
        raise PeerTimeout(
            f"{who} timed out after {retries + 1} attempt(s) of "
            f"{timeout:.1f}s waiting for peer payload {tag!r} from "
            f"{src} — peer dead or schedules desynchronized")

    def __len__(self):
        with self._cond:
            return len(self._items)
