"""Real-Ray backend (used automatically when Ray is importable).

Maps the backend interface onto Ray primitives exactly where the
reference binds to them: ``@ray.remote`` actors with resource requests
(ray_ddp.py:174-180), ``ray.put`` object transport (ray_ddp.py:331),
``ray.util.queue.Queue`` relay (ray_ddp.py:335-338), ``ray.kill``
teardown (ray_ddp.py:384).  TPU workers request ``{"TPU": chips}``
custom resources instead of ``num_gpus`` — one actor per TPU host.

This module is only imported when Ray is present (cluster/backend.py
gates it), so the hard ``import ray`` here is safe.
"""

from __future__ import annotations

from typing import Any, Optional

import ray
from ray.util.queue import Queue as RayQueue

from ray_lightning_tpu.cluster.backend import (
    ActorHandle,
    ClusterBackend,
    Future,
)
from ray_lightning_tpu.cluster.queue import RayQueueProxy


class RayActorHandle(ActorHandle):
    def __init__(self, actor):
        self._actor = actor
        self.actor_id = actor._actor_id.hex()

    def call(self, method: str, *args, **kwargs) -> Future:
        ref = getattr(self._actor, method).remote(*args, **kwargs)
        fut = Future()

        def _resolve():
            try:
                fut.set_result(ray.get(ref))
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                fut.set_error(e)

        import threading
        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def kill(self) -> None:
        ray.kill(self._actor, no_restart=True)


class RayBackend(ClusterBackend):
    supports_object_store = True

    def __init__(self):
        if not ray.is_initialized():
            ray.init()
        self._queue: Optional[RayQueue] = None

    def _ensure_queue(self) -> RayQueue:
        if self._queue is None:
            # num_cpus=0 so the queue actor never competes for worker
            # resources (ray_ddp.py:338 parity).
            self._queue = RayQueue(actor_options={"num_cpus": 0})
        return self._queue

    def worker_queue_proxy(self) -> RayQueueProxy:
        return RayQueueProxy(self._ensure_queue())

    def create_actor(self, actor_cls: type, *args,
                     env: Optional[dict[str, str]] = None,
                     resources: Optional[dict[str, float]] = None,
                     name: Optional[str] = None, **kwargs) -> ActorHandle:
        resources = dict(resources or {})
        num_cpus = resources.pop("CPU", 1)
        num_gpus = resources.pop("GPU", 0)
        options: dict[str, Any] = {
            "num_cpus": num_cpus,
            "num_gpus": num_gpus,
        }
        if resources:
            options["resources"] = resources
        if env:
            options["runtime_env"] = {"env_vars": {
                k: str(v) for k, v in env.items()}}
        remote_cls = ray.remote(actor_cls)
        actor = remote_cls.options(**options).remote(*args, **kwargs)
        return RayActorHandle(actor)

    def put(self, obj: Any):
        return ray.put(obj)

    def get(self, ref: Any) -> Any:
        if isinstance(ref, Future):
            return ref.result()
        return ray.get(ref)

    def queue_get_nowait(self):
        if self._queue is None:
            return None  # no queue was requested for this run
        from ray.util.queue import Empty
        try:
            return self._queue.get_nowait()
        except Empty:
            return None

    def available_resources(self) -> dict[str, float]:
        return dict(ray.available_resources())

    def shutdown(self) -> None:
        if self._queue is not None:
            self._queue.shutdown()
            self._queue = None
