"""Real-Ray backend (used automatically when Ray is importable).

Maps the backend interface onto Ray primitives exactly where the
reference binds to them: ``@ray.remote`` actors with resource requests
(ray_ddp.py:174-180), ``ray.put`` object transport (ray_ddp.py:331),
``ray.util.queue.Queue`` relay (ray_ddp.py:335-338), ``ray.kill``
teardown (ray_ddp.py:384).  TPU workers request ``{"TPU": chips}``
custom resources instead of ``num_gpus`` — one actor per TPU host.

This module is only imported when Ray is present (cluster/backend.py
gates it), so the hard ``import ray`` here is safe.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

import ray
from ray.util.queue import Queue as RayQueue

from ray_lightning_tpu.cluster.backend import (
    ActorHandle,
    ClusterBackend,
    Future,
)
from ray_lightning_tpu.cluster.queue import RayQueueProxy


class _CallResolver:
    """One daemon thread resolving ALL in-flight actor calls.

    A thread per call is the wrong shape at pod scale (128 actors ×
    several calls each = hundreds of threads); here every pending
    ObjectRef sits in one table that a single thread drains with
    ``ray.wait`` — O(1) threads regardless of fan-out.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict[Any, Future] = {}
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._version = 0            # bumped per submit; detects traffic

    def submit(self, ref: Any, fut: Future) -> None:
        with self._lock:
            self._pending[ref] = fut
            self._version += 1
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="rlt-ray-resolver", daemon=True)
                self._thread.start()
        self._wake.set()

    def _run(self) -> None:
        # Adaptive wait: while calls are completing or arriving, stay at
        # a 50 ms wait so request-response loops (e.g. worker setup's
        # dozen sequential short calls) resolve promptly even with a
        # long call in flight; when the pending set goes quiet (one long
        # fit dispatched and nothing else), back off to a 0.5 s wait so
        # the thread idles at ~2 Hz instead of spinning at 20 Hz
        # (advisor finding r2 + reviewer latency findings r3).
        timeout = 0.05
        while True:
            with self._lock:
                refs = list(self._pending)
                version = self._version
            if not refs:
                self._wake.wait()
                self._wake.clear()
                timeout = 0.05
                continue
            try:
                # num_returns=1: return the moment ANY call completes
                ready, _ = ray.wait(refs, num_returns=1, timeout=timeout)
            except BaseException as e:  # noqa: BLE001
                # wait-level failure (e.g. ray.shutdown with calls in
                # flight): fail the futures whose refs were in THIS wait
                # so their callers see the error instead of hanging —
                # calls submitted after the snapshot (possibly against a
                # re-initialized Ray) stay pending and get a fresh wait.
                doomed = []
                with self._lock:
                    for ref in refs:
                        fut = self._pending.pop(ref, None)
                        if fut is not None:
                            doomed.append(fut)
                for fut in doomed:
                    fut.set_error(e)
                continue
            for ref in ready:
                with self._lock:
                    fut = self._pending.pop(ref, None)
                if fut is None:
                    continue
                try:
                    fut.set_result(ray.get(ref))
                except BaseException as e:  # noqa: BLE001 - to caller
                    fut.set_error(e)
            with self._lock:
                traffic = ready or self._version != version
            # backoff cap 0.5 s: a ray.wait in flight cannot be
            # interrupted, so the cap bounds how long the FIRST call
            # after a quiet period waits to join the wait set (the
            # steady-state spin is still 40× lazier than the old fixed
            # 50 ms cycle)
            timeout = 0.05 if traffic else min(timeout * 2, 0.5)


_resolver = _CallResolver()


class RayActorHandle(ActorHandle):
    def __init__(self, actor):
        self._actor = actor
        self.actor_id = actor._actor_id.hex()

    def call(self, method: str, *args, **kwargs) -> Future:
        ref = getattr(self._actor, method).remote(*args, **kwargs)
        fut = Future()
        _resolver.submit(ref, fut)
        return fut

    def kill(self) -> None:
        ray.kill(self._actor, no_restart=True)

    def alive(self) -> Optional[bool]:
        """Liveness probe via the executor's ``ping`` (watchdog
        diagnostics).  Bounded wait: a wedged-but-alive actor that
        cannot answer within 2s reads as not-alive, which is exactly
        what the watchdog wants to report."""
        try:
            ref = self._actor.ping.remote()
            ready, _ = ray.wait([ref], timeout=2.0)
            return bool(ready)
        except Exception:
            return False

    def process_alive(self) -> Optional[bool]:
        """Strict probe for the elastic shrink classifier: the actor's
        GCS-reported state, which a busy actor does not affect (the
        ping probe above would misread a mid-collective worker as dead
        and turn a user exception into a shrink).  None when the state
        API is unavailable in this Ray build."""
        try:
            from ray.util.state import get_actor
            st = get_actor(self.actor_id)
            if st is None:
                return None
            return str(getattr(st, "state", "")).upper() != "DEAD"
        except Exception:
            return None

    def harvest_escrow(self, timeout: float = 15.0):
        """Recovery-escrow fetch via the executor's concurrent
        ``__rlt_escrow_export__`` method — the actor must have been
        created with ``max_concurrency >= 2`` (the plugin does) so the
        call runs beside a wedged main call.  None on any failure: the
        elastic driver then falls back to snapshot replay."""
        try:
            ref = self._actor.__rlt_escrow_export__.remote()
            ready, _ = ray.wait([ref], timeout=timeout)
            if not ready:
                return None
            return ray.get(ready[0])
        except Exception:
            return None

    def log_tail(self, max_bytes: int = 4096) -> str:
        """Best-effort worker-log forensics for the crash flight
        recorder (telemetry/flight.py): the state API's log fetch when
        this Ray build has one (driver-colocated clusters), else empty
        — Ray's own log aggregation remains the canonical path."""
        try:
            from ray.util.state import get_log
            lines = list(get_log(actor_id=self.actor_id, tail=60))
            text = "\n".join(str(ln) for ln in lines).strip()
            return text[-max_bytes:]
        except Exception:
            return ""


class RayBackend(ClusterBackend):
    supports_object_store = True
    # Ray actors may land on other nodes where the driver's compile-
    # cache path is an empty local dir — the plugin ships a packed seed
    # of the driver's cache through ray.put instead (one object, every
    # worker derefs; compile/shipping.py).  Workers still WRITE to their
    # node-local dir at the same path, so co-located restarts warm up.
    shared_filesystem = False

    def __init__(self, address: Optional[str] = None):
        """Connect to (or start) a Ray runtime.

        ``address`` — explicit cluster address, including Ray Client
        URIs (``ray://host:10001``, the pickle-over-gRPC path the
        reference tests in tests/test_client*.py).  Defaults to the
        ``RLT_RAY_ADDRESS`` / ``RAY_ADDRESS`` env vars; unset means a
        fresh local runtime (bare ``ray.init()``, ray_ddp.py:125-126).
        An already-initialized runtime (user called ``ray.init``
        themselves, client or not) is used as-is.
        """
        if not ray.is_initialized():
            address = (address
                       or os.environ.get("RLT_RAY_ADDRESS")
                       or os.environ.get("RAY_ADDRESS"))
            if address:
                ray.init(address=address)
            else:
                ray.init()
        self._queue: Optional[RayQueue] = None

    def _ensure_queue(self) -> RayQueue:
        if self._queue is None:
            # num_cpus=0 so the queue actor never competes for worker
            # resources (ray_ddp.py:338 parity).
            self._queue = RayQueue(actor_options={"num_cpus": 0})
        return self._queue

    def worker_queue_proxy(self) -> RayQueueProxy:
        return RayQueueProxy(self._ensure_queue())

    def create_actor(self, actor_cls: type, *args,
                     env: Optional[dict[str, str]] = None,
                     resources: Optional[dict[str, float]] = None,
                     name: Optional[str] = None,
                     max_concurrency: Optional[int] = None,
                     **kwargs) -> ActorHandle:
        resources = dict(resources or {})
        num_cpus = resources.pop("CPU", 1)
        num_gpus = resources.pop("GPU", 0)
        options: dict[str, Any] = {
            "num_cpus": num_cpus,
            "num_gpus": num_gpus,
        }
        if resources:
            options["resources"] = resources
        if env:
            options["runtime_env"] = {"env_vars": {
                k: str(v) for k, v in env.items()}}
        if name:
            # named + namespaced so peers can ray.get_actor each other
            # (the worker↔worker channel's Ray transport — peer_send)
            options["name"] = name
        if max_concurrency:
            # peer deliveries arrive as concurrent method calls on Ray
            # (cluster/peer.py): without this they would queue behind
            # the receiver's in-flight step and deadlock the exchange
            options["max_concurrency"] = int(max_concurrency)
        remote_cls = ray.remote(actor_cls)
        actor = remote_cls.options(**options).remote(*args, **kwargs)
        return RayActorHandle(actor)

    def put(self, obj: Any):
        return ray.put(obj)

    def get(self, ref: Any) -> Any:
        if isinstance(ref, Future):
            return ref.result()
        return ray.get(ref)

    def queue_get_nowait(self):
        if self._queue is None:
            return None  # no queue was requested for this run
        from ray.util.queue import Empty
        try:
            return self._queue.get_nowait()
        except Empty:
            return None

    def available_resources(self) -> dict[str, float]:
        return dict(ray.available_resources())

    def shutdown(self) -> None:
        if self._queue is not None:
            self._queue.shutdown()
            self._queue = None
