"""Wire protocol for the built-in actor backend.

Length-prefixed cloudpickle frames over a unix-domain socket — the
transport under the built-in backend's actor RPC and the worker→driver
queue stream (the roles Ray core's GCS/RPC + ``ray.util.queue.Queue``
play for the reference, SURVEY.md §2.2).  Messages are dicts with a
``type`` field:

  driver→worker: {type: call, call_id, method, args, kwargs}
                 {type: shutdown}
  worker→driver: {type: hello, actor_id}
                 {type: result, call_id, ok, value|error}
                 {type: queue, item}         (unsolicited, session relay)

``queue`` frames carry two item families: user session relays (Tune
reports/checkpoints — callables executed on the driver) and telemetry
items (span batches + heartbeats, dicts marked with
``telemetry.TELEMETRY_KEY``) routed to the driver-side aggregator by
``util.process_results``.  Heartbeats ride this same channel so worker
liveness needs no second socket.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Any

import cloudpickle

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 36  # 64 GiB guard


class Connection:
    """Thread-safe framed connection over a stream socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._wlock = threading.Lock()
        self._rlock = threading.Lock()

    def send(self, msg: Any) -> None:
        payload = cloudpickle.dumps(msg)
        with self._wlock:
            self._sock.sendall(_LEN.pack(len(payload)) + payload)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self._sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("peer closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def recv(self) -> Any:
        with self._rlock:
            (length,) = _LEN.unpack(self._recv_exact(_LEN.size))
            if length > MAX_FRAME:
                raise ValueError(f"frame too large: {length}")
            payload = self._recv_exact(length)
        return cloudpickle.loads(payload)

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def find_free_port(host: str = "") -> int:
    """Bind port 0 and report what the OS picked (ray_ddp.py:31-35 analog;
    used to allocate the PJRT coordinator port on the rank-0 node)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


def node_ip() -> str:
    """Best-effort IP of this node (RayExecutor.get_node_ip analog).

    ``RLT_NODE_IP_OVERRIDE`` fakes the answer per process — the
    single-machine stand-in for multi-node topology, as the reference
    fakes node IPs "1"/"2" to test rank assignment (test_ddp.py:78-112)
    and spins two raylets on one box (ray.cluster_utils.Cluster,
    test_ddp.py:52-60).
    """
    override = os.environ.get("RLT_NODE_IP_OVERRIDE")
    if override:
        return override
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
