from ray_lightning_tpu.cluster.backend import (
    ActorHandle,
    ClusterBackend,
    Future,
    get_backend,
    set_backend,
)
from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.cluster.local import LocalBackend

__all__ = [
    "ActorHandle",
    "ClusterBackend",
    "Future",
    "get_backend",
    "set_backend",
    "LocalBackend",
    "RLTExecutor",
]
