"""Pipeline parallelism: GPipe microbatching over a ``stage`` mesh axis.

Beyond the reference's parity surface (SURVEY.md §2.3 marks PP absent),
built the TPU way rather than the torch way: instead of processes
exchanging activations through a framework RPC layer, the whole
pipeline is ONE compiled SPMD program.  Layer-stacked parameters
(leading dim = layer) shard over the ``stage`` axis, each stage scans
its local layer slice, and activations hop to the next stage with
``lax.ppermute`` — lowered to ICI neighbor DMAs that XLA overlaps with
the next microbatch's compute.  The classic GPipe schedule
(arxiv.org/abs/1811.06965; the "scaling book" pipelining recipe) falls
out of a single ``lax.scan`` over time steps:

    time t:  stage s computes microbatch (t - s); stage 0 feeds fresh
    microbatches; the last stage collects outputs for t ≥ S-1.

Bubble fraction is the usual (S-1)/(M+S-1): raise ``n_microbatches``
to amortize.  Composes with data parallelism (batch stays sharded on
``data``) in the same mesh.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.parallel.mesh import get_current_mesh, shard_map_compat
from ray_lightning_tpu.parallel.strategy import SpmdStrategy
from ray_lightning_tpu.telemetry.metrics import note_traced_collective
from ray_lightning_tpu.parallel.ring import _tensor_bytes


def _scan_layers(stage_fn, params_stacked, h):
    """Run ``stage_fn`` once per leading-dim slice of ``params_stacked``
    (layers execute in order; XLA compiles the body once)."""
    def body(carry, p):
        return stage_fn(p, carry), None
    out, _ = lax.scan(body, h, params_stacked)
    return out


def _pipeline_inner(params_loc, x_loc, *, stage_fn, axis_name,
                    n_microbatches, n_stages):
    """Per-device GPipe body under shard_map.

    params_loc: this stage's layer slice ([L/S, ...] leaves);
    x_loc: this data shard's activations [B_loc, ...].
    """
    S, M = n_stages, n_microbatches
    sid = lax.axis_index(axis_name)
    B = x_loc.shape[0]
    mb = B // M
    x_mb = x_loc.reshape((M, mb) + x_loc.shape[1:])
    perm = [(j, (j + 1) % S) for j in range(S)]

    def step(carry, t):
        recv, outs = carry
        # stage 0 feeds microbatch t (clipped during the drain phase —
        # those time steps produce garbage that is never collected)
        feed = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, M - 1), 0,
                                        keepdims=False)
        inp = jnp.where(sid == 0, feed, recv)
        out = _scan_layers(stage_fn, params_loc, inp)
        nxt = lax.ppermute(out, axis_name, perm)
        # the last stage finished microbatch t-(S-1) this step
        oidx = t - (S - 1)
        cur = lax.dynamic_index_in_dim(outs, jnp.clip(oidx, 0, M - 1), 0,
                                       keepdims=False)
        keep = jnp.where((oidx >= 0) & (oidx < M), out, cur)
        outs = lax.dynamic_update_index_in_dim(
            outs, keep, jnp.clip(oidx, 0, M - 1), 0)
        return (nxt, outs), None

    init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
    (_, outs), _ = lax.scan(step, init, jnp.arange(M + S - 1))
    # only the last stage holds real outputs; broadcast them so the
    # (replicated-over-stage) downstream head/loss sees one consistent
    # value — gradients flow back only into stage S-1's contribution.
    # psum-of-masked-zeros IS the broadcast here: XLA has no one-hop
    # pbroadcast primitive, a ppermute chain costs S-1 serial hops, and
    # a log-tree of ppermutes moves log2(S)*|outs| per link vs the ring
    # all-reduce's 2(S-1)/S*|outs| — psum wins for S>=4 and ties below.
    outs = lax.psum(
        jnp.where(sid == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs.reshape((B,) + x_loc.shape[1:])


def pipeline_forward(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     stacked_params: Any, x: jax.Array, *,
                     n_microbatches: int = 4, axis_name: str = "stage",
                     mesh=None) -> jax.Array:
    """Apply ``n_layer`` layers to ``x``, pipelined over ``axis_name``.

    stage_fn(layer_params, h) -> h applies ONE layer; ``stacked_params``
    is its parameter pytree with a leading layer dim on every leaf,
    sharded on the ``stage`` mesh axis (PipelineStrategy does this).
    Without a stage axis (or size 1) this is a plain sequential scan —
    same math, same results, so models are portable across meshes.
    """
    if mesh is None:
        mesh = get_current_mesh()
    S = (mesh.shape[axis_name]
         if mesh is not None and axis_name in mesh.axis_names else 1)
    if S == 1:
        return _scan_layers(stage_fn, stacked_params, x)

    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S:
        raise ValueError(
            f"{n_layers} layers do not divide over {S} pipeline stages")

    from ray_lightning_tpu.parallel.mesh import data_and_tensor_axes
    dp, _ = data_and_tensor_axes(mesh)
    data_size = 1
    for a in (dp or ()):
        data_size *= mesh.shape[a]
    if x.shape[0] % max(1, data_size):
        raise ValueError(
            f"global batch {x.shape[0]} does not divide across "
            f"{data_size} data shards")
    b_loc, rem = divmod(x.shape[0] // max(1, data_size), n_microbatches)
    if rem or b_loc == 0:
        raise ValueError(
            f"per-data-shard batch {x.shape[0]}//{data_size} does not "
            f"divide into {n_microbatches} microbatches")
    # fabric traffic per invocation (trace-time accounting, charged per
    # executed step by telemetry.metrics): every GPipe time step each of
    # the S stages ppermutes one microbatch-sized activation block per
    # data shard — global bytes x_bytes/M per stage — over M+S-1 time
    # steps, plus the final psum broadcasting the last stage's outputs
    # (logical payload: the full activation tensor once).
    x_bytes = _tensor_bytes(x)
    note_traced_collective(
        "pipeline", S * (n_microbatches + S - 1) * x_bytes
        // n_microbatches + x_bytes)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name),
                                         stacked_params)
    x_spec = P(dp)
    inner = functools.partial(
        _pipeline_inner, stage_fn=stage_fn, axis_name=axis_name,
        n_microbatches=n_microbatches, n_stages=S)
    fn = shard_map_compat(inner, mesh,
                          in_specs=(param_specs, x_spec),
                          out_specs=x_spec)
    return fn(stacked_params, x)


class PipelineStrategy(SpmdStrategy):
    """Sharding strategy for pipelined models: parameters whose path
    matches ``stage_param_regex`` (the layer-stacked blocks) shard their
    leading layer dim on ``stage``; everything else follows the usual
    SpmdStrategy rules (so data/tensor/fsdp compose).  Optimizer state
    mirrors the stage sharding — each stage also owns its layers' Adam
    moments, the PP-natural ZeRO placement.
    """

    name = "pipeline"

    def __init__(self, stages: int,
                 stage_param_regex: str = r"(^|/)blocks/",
                 rules: Sequence = (),
                 axis_names: Sequence[str] = ("data", "stage"),
                 axis_sizes=None, **kw):
        sizes = dict(axis_sizes or {})
        sizes.setdefault("stage", stages)
        super().__init__(rules=rules, axis_names=axis_names,
                         axis_sizes=sizes, **kw)
        self.stages = stages
        self._stage_rx = re.compile(stage_param_regex)

    def _stage_spec(self, path: str) -> "P | None":
        if self._stage_rx.search(path):
            return P("stage")
        return None

    def param_spec(self, mesh, path, aval) -> P:
        spec = self._stage_spec(path)
        if spec is not None:
            return spec
        return super().param_spec(mesh, path, aval)

    def opt_spec(self, mesh, path, aval) -> P:
        spec = self._stage_spec(path)
        # optax moment leaves mirror the param tree; only leaves that
        # kept the stacked layer rank can carry the stage dim (scalars
        # like the Adam step count fall through)
        if spec is not None and getattr(aval, "ndim", 0) >= 1:
            return spec
        return super().opt_spec(mesh, path, aval)
