"""Device-mesh construction over TPU topologies.

The reference's topology unit is "one process per GPU joining a NCCL
group" with rank math derived from node IPs (ray_ddp.py:282-306).  The
TPU-native unit is a ``jax.sharding.Mesh`` over all chips of all hosts;
rank math is subsumed by ``jax.process_index()`` + the mesh's logical
axes.  ``build_device_mesh`` shapes the global device list into named
axes (data / fsdp / tensor / sequence / expert), preferring ICI-contiguous
placement for the innermost (most communication-heavy) axes by putting
them last, which keeps XLA collectives on-slice.
"""

from __future__ import annotations

import math
import threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def _infer_axis_sizes(n_devices: int, axis_sizes: dict[str, int],
                      axis_names: Sequence[str]) -> list[int]:
    """Fill in at most one -1/None axis so the product equals n_devices."""
    sizes = [axis_sizes.get(name, None) for name in axis_names]
    known = [s for s in sizes if s not in (None, -1)]
    unknown = [i for i, s in enumerate(sizes) if s in (None, -1)]
    prod = math.prod(known) if known else 1
    if len(unknown) > 1:
        raise ValueError(f"At most one axis may be inferred, got {axis_sizes}")
    if unknown:
        if n_devices % prod != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes {axis_sizes}")
        sizes[unknown[0]] = n_devices // prod
    elif prod != n_devices:
        raise ValueError(
            f"Mesh axes {dict(zip(axis_names, sizes))} need {prod} devices, "
            f"have {n_devices}")
    return [int(s) for s in sizes]


def build_device_mesh(
    axis_names: Sequence[str] = ("data",),
    axis_sizes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all global devices).

    ``axis_sizes`` maps axis name → size; one axis may be ``-1``/absent to
    absorb the remainder (typically the data axis).  Axis order in
    ``axis_names`` is outermost→innermost: put the heaviest-traffic axis
    (tensor) last so it lands on physically adjacent chips.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = _infer_axis_sizes(len(devices), dict(axis_sizes or {}), axis_names)
    arr = np.asarray(devices, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axis_names))


def mesh_axis_size(mesh: Mesh, *names: str) -> int:
    """Product of the sizes of the given axes present in the mesh."""
    total = 1
    for n in names:
        if n in mesh.axis_names:
            total *= mesh.shape[n]
    return total


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across the jax API generations this image may carry:
    the top-level ``jax.shard_map`` (``check_vma`` keyword) when present,
    ``jax.experimental.shard_map.shard_map`` (``check_rep``) otherwise.
    Replication checking is disabled either way — every body routed
    through here performs manual collectives whose replication the
    checker cannot see."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _esm
    return _esm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False)


# The trainer publishes its mesh here so mesh-aware ops traced *inside*
# its jitted step (ring attention's shard_map, parallel/ring.py) can
# reach it without threading a handle through the flax module tree.
# Thread-local because concurrent tune trials each run a Trainer in
# their own thread (tune/runner.py) with distinct meshes.
_MESH_TLS = threading.local()


def data_and_tensor_axes(mesh: Mesh):
    """(data_axes, tensor_axis) present in ``mesh`` — the batch/head
    sharding layout shared by the attention shard_map paths
    (ops/attention.py, parallel/ring.py)."""
    dp = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names) or None
    tensor = "tensor" if "tensor" in mesh.axis_names else None
    return dp, tensor


def set_current_mesh(mesh: Mesh | None) -> None:
    _MESH_TLS.mesh = mesh


def get_current_mesh() -> Mesh | None:
    return getattr(_MESH_TLS, "mesh", None)
