from ray_lightning_tpu.parallel.mesh import build_device_mesh
from ray_lightning_tpu.parallel.pipeline import (
    PipelineStrategy,
    pipeline_forward,
)
from ray_lightning_tpu.parallel.strategy import (
    DataParallelStrategy,
    FullyShardedStrategy,
    ShardingStrategy,
    SpmdStrategy,
    Zero1Strategy,
    resolve_strategy,
)

__all__ = [
    "build_device_mesh",
    "ShardingStrategy",
    "DataParallelStrategy",
    "Zero1Strategy",
    "FullyShardedStrategy",
    "SpmdStrategy",
    "PipelineStrategy",
    "pipeline_forward",
    "resolve_strategy",
]
