"""Host fetch of (possibly multi-host-sharded) global arrays.

Checkpointing and the rank-0→driver state stream need full host values.
Single-process arrays are fetched directly; arrays spanning processes are
first replicated by one compiled identity program (XLA all-gather over
ICI/DCN — every process must call this together), then read from the
local shard.  This is how ZeRO-sharded optimizer state gets gathered into
world-size-independent checkpoints (SURVEY.md §5 checkpoint notes;
resume-with-different-world-size parity, test_ddp_sharded.py:119-138).
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.telemetry import span
from ray_lightning_tpu.telemetry.metrics import record_collective


def _replicate_leaves(leaves: list) -> list:
    """All-gather non-addressable leaves to full replication in ONE jitted
    program (single compilation, single collective schedule)."""
    mesh = leaves[0].sharding.mesh
    shardings = tuple(NamedSharding(mesh, P()) for _ in leaves)
    return jax.jit(lambda *xs: xs, out_shardings=shardings)(*leaves)


def fetch_tree(tree: Any) -> Any:
    """Pytree of global jax.Arrays → pytree of full host numpy arrays.

    The ``collective`` span times the all-gather + host transfer — the
    cross-host cost of checkpoints and result streams, visible per rank
    in the telemetry timeline.  The ``gather`` byte counter carries the
    replicated payload size; with the measured seconds it yields an
    exact per-op achieved GiB/s in the metrics summary."""
    t0 = time.monotonic()
    with span("collective", op="fetch_tree"):
        out, nbytes = _fetch_tree(tree)
    if nbytes:
        record_collective("gather", nbytes,
                          seconds=time.monotonic() - t0)
    return out


def _leaf_bytes(x) -> int:
    return int(np.prod(x.shape, dtype=np.int64)) * np.dtype(x.dtype).itemsize


def _fetch_tree(tree: Any) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    pending = [i for i, l in enumerate(leaves)
               if isinstance(l, jax.Array) and not l.is_fully_addressable]
    nbytes = 0
    if pending:
        # all-gather to full replication: each leaf's global size is the
        # logical payload every participating process ends up holding
        nbytes = sum(_leaf_bytes(leaves[i]) for i in pending)
        replicated = _replicate_leaves([leaves[i] for i in pending])
        for i, r in zip(pending, replicated):
            leaves[i] = r

    def to_host(x):
        if not isinstance(x, jax.Array):
            return x
        if x.is_fully_addressable:
            return np.asarray(jax.device_get(x))
        # replicated across processes: the local shard is the full value
        return np.asarray(x.addressable_shards[0].data)

    return jax.tree_util.tree_unflatten(
        treedef, [to_host(l) for l in leaves]), nbytes
