"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context training shards the *sequence* dimension across devices (a
capability absent from the reference — SURVEY.md §5 "long-context:
absent" — but first-class here).  Each device holds a local Q block and
rotates K/V blocks around the ``sequence`` mesh ring with
``lax.ppermute`` (lowered to ICI neighbor exchanges), folding each block
into an online-softmax accumulator — so the full [T, T] score matrix
never exists and per-device attention memory is O(T_local²) while
compute/communication overlap around the ring (Ring Attention,
arxiv.org/abs/2310.01889; blockwise attention, PAPERS.md).

Integration: the GPT family selects this with ``attention_impl="ring"``
and an ``SpmdStrategy`` whose mesh has a ``sequence`` axis; the trainer
publishes its mesh via :func:`parallel.mesh.set_current_mesh` so the op
can build the ``shard_map`` inside the jitted train step.  Without a
sequence axis (or size 1) it degrades to plain blockwise attention on
one device — same math, same results.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.parallel.mesh import get_current_mesh, shard_map_compat
from ray_lightning_tpu.telemetry.metrics import note_traced_collective

NEG_INF = -1e30


def _tensor_bytes(x) -> int:
    """Byte size from shape/dtype only — works on tracers (this runs at
    trace time, inside jit)."""
    import numpy as np
    size = 1
    for d in x.shape:
        size *= int(d)
    return size * np.dtype(x.dtype).itemsize


def _block_update(carry, q, k_blk, v_blk, q_off, k_off, causal, scale):
    """Fold one K/V block into the online-softmax accumulators.

    q: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D];
    carry = (m, l, acc) with m,l: [B, H, Tq, 1], acc: [B, Tq, H, D].
    """
    m, l, acc = carry
    tq, tk = q.shape[1], k_blk.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    s_max = jnp.max(s, axis=-1, keepdims=True)              # [B,H,Tq,1]
    m_new = jnp.maximum(m, s_max)
    p = jnp.exp(s - m_new)                                  # [B,H,Tq,Tk]
    alpha = jnp.exp(m - m_new)                              # [B,H,Tq,1]
    l_new = alpha * l + jnp.sum(p, -1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk,
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha.transpose(0, 2, 1, 3) + pv
    return m_new, l_new, acc_new


def blockwise_attention(q, k, v, *, causal: bool = True,
                        dtype=jnp.bfloat16, sm_scale: float | None = None,
                        block_size: int = 512):
    """Single-device blockwise attention (the ring's i=0 special case):
    K/V streamed in blocks, online softmax, no [T, T] materialization.
    The jnp-level sibling of ops/flash_attention.py, and the local math
    ring_attention runs per ring step."""
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)
    nblk = max(1, t // max(1, min(block_size, t)))
    tk = t // nblk
    m = jnp.full((b, h, t, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t, 1), jnp.float32)
    acc = jnp.zeros((b, t, h, d), jnp.float32)
    carry = (m, l, acc)
    step = jax.checkpoint(
        functools.partial(_block_update, causal=causal, scale=scale))
    for i in range(nblk):
        kb = k[:, i * tk:(i + 1) * tk].astype(jnp.float32)
        vb = v[:, i * tk:(i + 1) * tk].astype(jnp.float32)
        carry = step(carry, qf, kb, vb, 0, i * tk)
    m, l, acc = carry
    return (acc / l.transpose(0, 2, 1, 3)).astype(dtype)


def _ring_inner(q, k, v, *, axis_name, causal, scale, dtype, ring_size):
    """Per-device body under shard_map: rotate K/V around the ring."""
    idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qf = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    m = jnp.full((b, h, tq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq, 1), jnp.float32)
    acc = jnp.zeros((b, tq, h, d), jnp.float32)
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
    carry = (m, l, acc)
    # rematerialize each block on backward: keeps activation memory at
    # O(Tq·D) instead of O(ring·Tq·Tk)
    step = jax.checkpoint(
        functools.partial(_block_update, causal=causal, scale=scale))
    for i in range(ring_size):
        # the block we currently hold started at device (idx - i) % ring
        src = jax.lax.rem(idx - i + ring_size, ring_size)
        carry = step(carry, qf, k, v, idx * tq, src * tk)
        if i < ring_size - 1:
            # rotate while the next step's compute is ready to issue; XLA
            # overlaps the ppermute DMA with the block matmuls
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    m, l, acc = carry
    return (acc / l.transpose(0, 2, 1, 3)).astype(dtype)


def ring_attention(q, k, v, *, causal: bool = True, dtype=jnp.bfloat16,
                   sm_scale: float | None = None,
                   axis_name: str = "sequence", mesh=None):
    """Sequence-parallel attention over ``[B, T, H, D]`` tensors.

    Call sites inside a jitted SPMD program (the usual case) need the
    mesh: pass it or let the trainer publish it (set_current_mesh).
    """
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if mesh is None:
        mesh = get_current_mesh()
    ring = (mesh.shape[axis_name]
            if mesh is not None and axis_name in mesh.axis_names else 1)
    if ring == 1:
        return blockwise_attention(q, k, v, causal=causal, dtype=dtype,
                                   sm_scale=scale)

    # fabric traffic per invocation: every rotation moves each device's
    # local K/V block one hop, so ring devices together move the full
    # global K+V per rotation, (ring-1) rotations per call.  This runs
    # at trace time (the call sits inside the jitted step); the traced
    # cost is charged once per executed step by telemetry.metrics.
    note_traced_collective(
        "ring", (ring - 1) * (_tensor_bytes(k) + _tensor_bytes(v)))

    from ray_lightning_tpu.parallel.mesh import data_and_tensor_axes
    dp, tensor = data_and_tensor_axes(mesh)
    spec = P(dp, axis_name, tensor, None)
    inner = functools.partial(_ring_inner, axis_name=axis_name,
                              causal=causal, scale=scale, dtype=dtype,
                              ring_size=ring)
    fn = shard_map_compat(inner, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)
