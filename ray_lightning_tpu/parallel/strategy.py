"""Sharding strategies: parallelism expressed as sharding annotations.

This is the architectural inversion at the center of the framework.  The
reference implements its three parallelism flavors as *process-group
protocols* — DDP allreduce hooks (ray_ddp.py:467-468), Horovod ring
(ray_horovod.py:196), FairScale OSS/SDP wrap (ray_ddp_sharded.py:17-34).
On TPU all of them are the *same compiled program* with different sharding
annotations on the train-state pytree; XLA lowers the annotations to
ICI/DCN collectives (psum / reduce-scatter / all-gather):

- :class:`DataParallelStrategy` (≙ RayPlugin/DDP and HorovodRayPlugin):
  params+opt replicated, batch sharded on ``data`` → XLA inserts a
  gradient psum.
- :class:`Zero1Strategy` (≙ RayShardedPlugin/FairScale OSS): params
  replicated, optimizer state sharded on ``data`` → XLA reduce-scatters
  grads into the sharded update and all-gathers updated params (the
  "Automatic Cross-Replica Sharding of Weight Update" pattern,
  arxiv.org/pdf/2004.13336, see PAPERS.md).
- :class:`FullyShardedStrategy` (beyond-parity ZeRO-3/FSDP): params and
  opt state both sharded; XLA all-gathers params where consumed.
- :class:`SpmdStrategy` (beyond-parity): general mesh
  (data, fsdp, sequence, tensor, expert) with regex partition rules for
  tensor parallelism and a sequence axis for long-context.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_logger = logging.getLogger(__name__)

from ray_lightning_tpu.parallel.mesh import build_device_mesh, mesh_axis_size


def _best_shardable_axis(shape: Sequence[int], size: int,
                         taken: set[int] | None = None) -> int | None:
    """Largest dim divisible by ``size`` (None if none)."""
    best, best_dim = None, -1
    for i, d in enumerate(shape):
        if taken and i in taken:
            continue
        if size > 0 and d % size == 0 and d >= size and d > best_dim:
            best, best_dim = i, d
    return best


def _axis_spec(shape: Sequence[int], axis: str, size: int) -> P:
    """PartitionSpec sharding the best divisible dim of ``shape`` on
    ``axis``, replicated if nothing divides."""
    i = _best_shardable_axis(shape, size)
    if i is None:
        return P()
    spec = [None] * len(shape)
    spec[i] = axis
    return P(*spec)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingStrategy:
    """Base: maps an abstract TrainState + batch to sharding pytrees."""

    name: str = "base"
    #: outermost→innermost mesh axis names
    axis_names: tuple[str, ...] = ("data",)
    #: axes the batch's leading dim is sharded over
    data_axis_names: tuple[str, ...] = ("data",)
    #: whether this strategy's gradient sync can route through the comm
    #: plane's compressed collectives (ray_lightning_tpu/comm/): requires
    #: params replicated across the reduction axes — true for DDP and
    #: ZeRO-1, false for param-sharded strategies (FSDP/SPMD), whose
    #: mapped-region in_specs would misdeclare the param layout
    comm_compressible: bool = False

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        return {"data": n_devices}

    def build_mesh(self, devices=None, batch_hint: int | None = None) -> Mesh:
        """Build the mesh.  ``batch_hint`` (global batch size) lets a
        single-process run clamp the data axis so tiny batches still
        shard cleanly (XLA needs the batch dim divisible by the data-axis
        size); multi-process meshes always span every process's devices.
        """
        import math

        devices = list(devices) if devices is not None else jax.devices()
        n = len(devices)
        sizes = dict(self.axis_sizes(n))
        other = 1
        for a, s in sizes.items():
            if a != "data" and s not in (None, -1):
                other *= s
        data = sizes.get("data")
        if data in (None, -1):
            if n % other:
                raise ValueError(
                    f"{n} devices not divisible by non-data axes ({other})")
            data = n // other
        if batch_hint and jax.process_count() == 1:
            clamped = math.gcd(int(data), int(batch_hint)) or 1
            if clamped != data:
                _logger.warning(
                    "Global batch %d does not divide across %d data shards; "
                    "using %d of %d devices. Increase the batch size to use "
                    "the full mesh.", batch_hint, data, clamped * other, n)
            data = clamped
        sizes["data"] = data
        used = data * other
        return build_device_mesh(self.axis_names, sizes, devices[:used])

    # -- per-component specs (override points) -----------------------------

    def param_spec(self, mesh: Mesh, path: str, aval) -> P:
        return P()

    def opt_spec(self, mesh: Mesh, path: str, aval) -> P:
        return P()

    def batch_spec(self, mesh: Mesh, ndim: int) -> P:
        if ndim == 0:
            return P()
        return P(self.data_axis_names
                 if len(self.data_axis_names) > 1 else self.data_axis_names[0])

    # -- pytree-level products (used by the loop) --------------------------

    def replicated(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, P())

    def _shardings_with(self, mesh, tree, spec_fn):
        def leaf(path, aval):
            if getattr(aval, "ndim", 0) == 0:
                return NamedSharding(mesh, P())
            return NamedSharding(mesh, spec_fn(mesh, _path_str(path), aval))
        return jax.tree_util.tree_map_with_path(leaf, tree)

    def state_shardings(self, mesh: Mesh, abstract_state) -> Any:
        """TrainState-shaped pytree of NamedSharding."""
        return abstract_state.replace(
            step=NamedSharding(mesh, P()),
            params=self._shardings_with(mesh, abstract_state.params,
                                        self.param_spec),
            model_state=self._shardings_with(mesh, abstract_state.model_state,
                                             self.param_spec),
            opt_state=self._shardings_with(mesh, abstract_state.opt_state,
                                           self.opt_spec),
            rng=NamedSharding(mesh, P()),
        )

    def batch_shardings(self, mesh: Mesh, batch) -> Any:
        def leaf(x):
            ndim = getattr(x, "ndim", 0)
            return NamedSharding(mesh, self.batch_spec(mesh, ndim))
        return jax.tree_util.tree_map(leaf, batch)

    def data_parallel_size(self, mesh: Mesh) -> int:
        return mesh_axis_size(mesh, *self.data_axis_names)

    def kv_cache_spec(self, mesh: Mesh, ndim: int = 5) -> P:
        """Sharding of the serve plane's slot-indexed KV cache
        ``[n_layer, slot, pos, head, dim]`` (serve/kvcache.py): slots
        shard exactly like the batch's leading dim — each data shard
        decodes its own slots with no cross-device attention traffic.
        Requires ``max_batch_slots`` divisible by the data-axis size
        (the serve engine builds its mesh with ``batch_hint=slots`` so
        single-process meshes clamp instead of erroring)."""
        if ndim < 2:
            return P()
        spec = [None] * ndim
        spec[1] = (self.data_axis_names
                   if len(self.data_axis_names) > 1
                   else self.data_axis_names[0])
        return P(*spec)

    @staticmethod
    def _tree_bytes(tree) -> int:
        import numpy as np
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            total += int(np.prod(getattr(leaf, "shape", ()),
                                 dtype=np.int64)) \
                * np.dtype(leaf.dtype).itemsize
        return total

    @staticmethod
    def _tree_elements(tree) -> int:
        import numpy as np
        return sum(int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64))
                   for leaf in jax.tree_util.tree_leaves(tree))

    # -- planner introspection hooks (plan/candidates.py) ------------------

    @classmethod
    def plan_mesh_options(cls, n_devices: int) -> tuple:
        """Feasible mesh factorizations of ``n_devices`` this strategy
        can plan over, as axis_sizes dicts — the planner enumerates one
        candidate per entry.  Single-axis strategies have exactly one
        layout; multi-axis strategies (SpmdStrategy) override with
        their divisor factorizations.  New strategies self-describe by
        overriding this pair of hooks rather than teaching the planner
        about themselves."""
        return ({"data": n_devices},)

    @classmethod
    def from_plan(cls, axis_sizes: dict) -> "ShardingStrategy":
        """Construct the strategy instance for one
        :meth:`plan_mesh_options` entry."""
        del axis_sizes   # single-axis strategies: nothing to configure
        return cls()

    def grad_transform(self, mesh: Mesh, policy):
        """Resolve a comm policy against this strategy on this mesh: a
        ``comm.GradSync`` the step builder routes the gradient reduction
        through, or ``None`` (the default — the uncompressed build,
        byte-identical to a policy-less trainer).  See
        ray_lightning_tpu/comm/collectives.py:build_grad_sync for the
        resolution rules."""
        if policy is None:
            return None
        from ray_lightning_tpu.comm import build_grad_sync
        return build_grad_sync(self, mesh, policy)

    def step_collective_bytes(self, mesh: Mesh, abstract_state,
                              comm=None) -> dict:
        """op -> logical payload bytes ONE optimizer step moves through
        the fabric as a consequence of this strategy's sharding
        annotations (XLA compiles the collectives into the step, so the
        metrics plane accounts them from the annotation, not a call
        site).  Pure DDP: one gradient all-reduce the size of the
        params.  With an active comm plane (``comm`` = the resolved
        GradSync) the charge is the COMPRESSED wire payload, so
        ``rlt_collective_*`` and the bench JSON reflect the savings; a
        hierarchical sync splits the declaration by link tier
        (``_dcn``/``_ici`` op suffixes — the planner scores each at its
        own bandwidth and the metrics plane feeds
        ``rlt_comm_dcn_bytes_total`` from the suffix)."""
        if self.data_parallel_size(mesh) <= 1:
            return {}
        if comm is not None:
            n = self._tree_elements(abstract_state.params)
            if comm.hierarchical:
                link = comm.psum_link_bytes(n)
                return {"grad_all_reduce_dcn": link["dcn"],
                        "grad_all_reduce_ici": link["ici"]}
            return {"grad_all_reduce": comm.psum_wire_bytes(n)}
        return {"grad_all_reduce": self._tree_bytes(abstract_state.params)}

    # Strategies are part of the plugin config pickled driver→worker; they
    # hold no live handles so default pickling is fine.

    def __repr__(self):
        return f"{type(self).__name__}()"


class DataParallelStrategy(ShardingStrategy):
    """Pure DDP: replicate state, shard batch, XLA psums grads."""

    name = "ddp"
    comm_compressible = True


class Zero1Strategy(ShardingStrategy):
    """ZeRO-1: shard optimizer state across data ranks.

    Parity target for ``RayShardedPlugin`` (ray_ddp_sharded.py:17-34):
    FairScale OSS shards optimizer state across DDP ranks; here the same
    partitioning is a sharding annotation on the opt-state pytree.  What
    the annotation guarantees (audited at the compiled-HLO level in
    tests/test_collective_audit.py): the optimizer update math and its
    f32 master/moment buffers are 1/N-sized per device, each rank
    slices its shard of the summed grads, and the updated params are
    re-assembled with an all-gather.  Whether the grad-sum + slice pair
    lowers to a literal reduce-scatter is an XLA backend pass
    (ReduceScatterCreator) — the audited CPU lowering emits
    all-reduce + dynamic-slice; byte-for-byte the memory story is the
    OSS one either way.

    ``min_shard_elements`` leaves tiny leaves replicated (collective
    latency beats memory savings below a threshold).
    """

    name = "zero1"
    comm_compressible = True

    def __init__(self, min_shard_elements: int = 0):
        self.min_shard_elements = min_shard_elements

    def opt_spec(self, mesh: Mesh, path: str, aval) -> P:
        if aval.size < max(2, self.min_shard_elements):
            return P()
        return _axis_spec(aval.shape, "data", mesh.shape["data"])

    def param_gather_spec(self, mesh: Mesh, path: str, aval) -> P:
        """Shard layout of the post-update params BEFORE their re-gather
        (mirrors :meth:`opt_spec` — the update is computed where its
        optimizer shard lives).  The comm plane's compressed param
        all-gather constrains the updated params to this spec, quantizes
        shard-wise, and lets the replication constraint form the
        low-precision gather."""
        return self.opt_spec(mesh, path, aval)

    def step_collective_bytes(self, mesh: Mesh, abstract_state,
                              comm=None) -> dict:
        """ZeRO step traffic: grads reduce-scatter into the sharded
        update, updated params all-gather back out — each one params'
        worth of logical payload (whether XLA lowers the pair literally
        or as all-reduce + slice, the bytes on the wire are the OSS
        story — see class docstring).  With an active comm plane the
        grad phases carry the compressed payload (+ their all-gather
        leg) and the param gather charges at its policy dtype; a
        hierarchical sync declares the grad phases per link tier
        (``_dcn``/``_ici`` suffixes, see the base class).

        An honest declaration of the LATENCY-HIDDEN gather
        (``policy.gather_bucket_bytes > 0``, comm/collectives.py
        ``regather_params``): the bytes on the wire are unchanged —
        bucketing moves WHEN the gather runs, not how much it moves —
        so the payload is identical, but the op is keyed
        ``param_all_gather_bucketed`` so the planner's cost model
        (plan/cost.py ``op_overlap_factor``) can price the portion XLA
        hides behind the next forward's compute, and the audit/drift
        guards (tests/test_plan.py) can band it separately."""
        if self.data_parallel_size(mesh) <= 1:
            return {}
        if comm is not None:
            gather_key = ("param_all_gather_bucketed"
                          if comm.policy.gather_bucket_bytes > 0
                          and not comm.policy.barrier_sync
                          else "param_all_gather")
            n = self._tree_elements(abstract_state.params)
            if comm.hierarchical:
                link = comm.psum_link_bytes(n)
                return {
                    "grad_sync_dcn": link["dcn"],
                    "grad_sync_ici": link["ici"],
                    gather_key: comm.param_gather_wire_bytes(
                        abstract_state.params),
                }
            return {
                "grad_reduce_scatter": comm.reduce_scatter_wire_bytes(n),
                "grad_all_gather": comm.all_gather_wire_bytes(n),
                gather_key: comm.param_gather_wire_bytes(
                    abstract_state.params),
            }
        params = self._tree_bytes(abstract_state.params)
        return {"grad_reduce_scatter": params,
                "param_all_gather": params}


class FullyShardedStrategy(Zero1Strategy):
    """ZeRO-3/FSDP analog: params and optimizer state both sharded on
    ``data``; XLA all-gathers parameters at their use sites.  Beyond the
    reference's parity surface (SURVEY.md §2.3 marks FSDP absent) but
    nearly free once sharding is declarative."""

    name = "fsdp"
    comm_compressible = False   # params sharded: no replicated-param
    #                             mapped region (comm plane declines)

    def param_spec(self, mesh: Mesh, path: str, aval) -> P:
        if aval.size < max(2, self.min_shard_elements):
            return P()
        return _axis_spec(aval.shape, "data", mesh.shape["data"])

    def step_collective_bytes(self, mesh: Mesh, abstract_state,
                              comm=None) -> dict:
        """FSDP step traffic: params all-gathered at their use sites in
        BOTH forward and backward (2× params' worth) plus the gradient
        reduce-scatter (one params' worth) — strictly more than
        ZeRO-1's 2× total, which the inherited declaration used to
        claim.  Declared separately so the planner's cost model ranks
        FSDP below ZeRO-1 on comm whenever both fit (the memory story
        is what FSDP buys).  The comm plane declines param-sharded
        strategies, so ``comm`` never compresses these bytes."""
        del comm
        if self.data_parallel_size(mesh) <= 1:
            return {}
        params = self._tree_bytes(abstract_state.params)
        return {"param_all_gather": 2 * params,
                "grad_reduce_scatter": params}


class SpmdStrategy(ShardingStrategy):
    """General SPMD over a multi-axis mesh with regex partition rules.

    ``rules`` is an ordered list of ``(regex, PartitionSpec)`` matched
    against the ``/``-joined parameter path (the SNIPPETS.md §1
    ``match_partition_rules`` shape); first match wins; no match →
    replicated (or fsdp-sharded when an ``fsdp`` axis exists).
    Optimizer-state leaves inherit the spec of the parameter whose path
    they embed (optax states mirror the param tree).
    """

    name = "spmd"

    def __init__(
        self,
        rules: Sequence[tuple[str, P]] = (),
        axis_names: Sequence[str] = ("data", "fsdp", "expert", "sequence",
                                     "tensor"),
        axis_sizes: dict[str, int] | None = None,
        shard_sequence_dim: bool = True,
        min_shard_elements: int = 0,
    ):
        self.rules = [(re.compile(r), spec) for r, spec in rules]
        self.axis_names = tuple(axis_names)
        self._axis_sizes = dict(axis_sizes or {})
        self.shard_sequence_dim = shard_sequence_dim and (
            "sequence" in self.axis_names)
        self.min_shard_elements = min_shard_elements
        self.data_axis_names = tuple(
            a for a in ("data", "fsdp") if a in self.axis_names)

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self._axis_sizes)
        for a in self.axis_names:
            sizes.setdefault(a, 1 if a != "data" else None)
        if sizes.get("data") is None:
            sizes["data"] = -1
        return sizes

    def _rule_spec(self, mesh: Mesh, path: str, aval) -> P | None:
        for rx, spec in self.rules:
            if rx.search(path):
                pruned = self._prune_spec(mesh, spec)
                if any(e is not None for e in spec) and \
                        not any(e is not None for e in pruned):
                    # the rule only named axes this mesh lacks (e.g. a
                    # 'tensor' rule on a (data, fsdp) mesh): treat as
                    # unmatched so the param still reaches later rules /
                    # the fsdp fallback instead of silently replicating
                    continue
                return pruned
        return None

    @staticmethod
    def _prune_spec(mesh: Mesh, spec: P) -> P:
        """Drop axes the mesh does not have, so one rule set (written for
        the full data/fsdp/sequence/tensor layout) works on any sub-mesh
        — a rules entry P('tensor', None) on a (data, sequence) mesh
        becomes P(None, None) instead of erroring."""
        def keep(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in mesh.axis_names)
                return kept if kept else None
            return entry if entry in mesh.axis_names else None
        return P(*(keep(e) for e in spec))

    def _fsdp_fallback(self, mesh: Mesh, aval) -> P:
        if "fsdp" in mesh.axis_names and mesh.shape["fsdp"] > 1 \
                and aval.size >= max(2, self.min_shard_elements):
            return _axis_spec(aval.shape, "fsdp", mesh.shape["fsdp"])
        return P()

    def param_spec(self, mesh: Mesh, path: str, aval) -> P:
        spec = self._rule_spec(mesh, path, aval)
        if spec is not None:
            return spec
        return self._fsdp_fallback(mesh, aval)

    def opt_spec(self, mesh: Mesh, path: str, aval) -> P:
        spec = self._rule_spec(mesh, path, aval)
        if spec is not None and len(spec) == getattr(aval, "ndim", 0):
            return spec
        return self._fsdp_fallback(mesh, aval)

    def batch_spec(self, mesh: Mesh, ndim: int) -> P:
        if ndim == 0:
            return P()
        data = (self.data_axis_names if len(self.data_axis_names) > 1
                else self.data_axis_names[0])
        if (self.shard_sequence_dim and ndim >= 2
                and mesh.shape.get("sequence", 1) > 1):
            return P(data, "sequence")
        return P(data)

    def kv_cache_spec(self, mesh: Mesh, ndim: int = 5) -> P:
        """Slots on the data axes plus heads on ``tensor`` when the mesh
        has one — the decode attention is head-parallel the same way the
        training attention is (gpt_partition_rules)."""
        spec = list(super().kv_cache_spec(mesh, ndim))
        if ndim >= 4 and mesh.shape.get("tensor", 1) > 1:
            spec[3] = "tensor"
        return P(*spec)

    def step_collective_bytes(self, mesh: Mesh, abstract_state,
                              comm=None) -> dict:
        """Approximate SPMD step traffic for the planner/metrics byte
        model: an active ``fsdp`` axis gathers params at use in forward
        and backward and reduce-scatters grads (the FSDP story); an
        active ``data`` axis additionally all-reduces grads across
        replicas.  Tensor/sequence-rule traffic (activation
        collectives) is NOT modeled — rule-driven layouts are
        hand-written configurations the planner does not enumerate.
        The comm plane declines SPMD, so ``comm`` never applies."""
        del comm
        out: dict = {}
        params = self._tree_bytes(abstract_state.params)
        if mesh_axis_size(mesh, "fsdp") > 1:
            out["param_all_gather"] = 2 * params
            out["grad_reduce_scatter"] = params
        if mesh_axis_size(mesh, "data") > 1:
            out["grad_all_reduce"] = params
        return out

    @classmethod
    def plan_mesh_options(cls, n_devices: int) -> tuple:
        """Every ``data × fsdp`` factorization with a non-trivial fsdp
        axis (fsdp=1 would duplicate the plain DDP candidate).  The
        planner's generic SPMD candidate is rule-less — params fall to
        the fsdp-shard fallback — so the fsdp axis is the dimension
        that matters; rule-driven tensor/sequence layouts stay a
        hand-written ``SpmdStrategy`` concern."""
        return tuple({"data": n_devices // f, "fsdp": f}
                     for f in range(2, n_devices + 1)
                     if n_devices % f == 0)

    @classmethod
    def from_plan(cls, axis_sizes: dict) -> "SpmdStrategy":
        return cls(axis_names=("data", "fsdp"),
                   axis_sizes={"fsdp": int(axis_sizes.get("fsdp", 1))})


class AutoStrategy(ShardingStrategy):
    """Sentinel for ``Trainer(strategy="auto")``: the planner plane
    (ray_lightning_tpu/plan/) resolves it into a concrete strategy —
    plus a comm policy, donation and microbatch decision — once the
    module, example batch and device topology are known inside
    ``_run_stage``.  Carries an optional :class:`plan.PlanConfig`
    override; holds no other state, so it pickles driver→worker like
    any strategy.  Using it unresolved is a wiring bug and fails
    loudly."""

    name = "auto"

    def __init__(self, plan=None):
        self.plan = plan

    def build_mesh(self, devices=None, batch_hint=None) -> Mesh:
        raise RuntimeError(
            "strategy='auto' must be resolved by the planner before a "
            "mesh can be built (Trainer._resolve_auto_strategy); "
            "constructing AutoStrategy outside a Trainer is unsupported")


_STRATEGIES = {
    "ddp": DataParallelStrategy,
    "dp": DataParallelStrategy,
    "zero1": Zero1Strategy,
    "sharded": Zero1Strategy,       # reference-name alias (RayShardedPlugin)
    "fsdp": FullyShardedStrategy,
    "zero3": FullyShardedStrategy,
    "spmd": SpmdStrategy,
    "auto": AutoStrategy,
}


def strategy_names() -> list:
    """Every accepted ``Trainer(strategy=...)`` string, sorted (single
    source of truth for error messages, the planner inventory and the
    README table).  ``"mpmd"`` resolves lazily (the MPMD plane imports
    this module) and stays OUT of ``_STRATEGIES`` — it is a routing
    strategy the planner/comm planes never enumerate."""
    return sorted([*_STRATEGIES, "mpmd"])


def resolve_strategy(strategy: "str | ShardingStrategy | None") -> ShardingStrategy:
    """Resolve ``Trainer(strategy=...)`` into a :class:`ShardingStrategy`.

    Accepted values — an instance passes through; ``None`` defaults to
    DDP; a string selects by name (THE canonical list; the README
    "Parallelism" table mirrors it):

    =====================  ===============================================
    name                   strategy
    =====================  ===============================================
    ``"ddp"`` / ``"dp"``   :class:`DataParallelStrategy` — state
                           replicated, batch sharded, XLA psums grads
    ``"zero1"`` /          :class:`Zero1Strategy` — optimizer state
    ``"sharded"``          sharded across data ranks (FairScale-OSS
                           parity; "sharded" is the reference's name)
    ``"fsdp"`` /           :class:`FullyShardedStrategy` — params AND
    ``"zero3"``            optimizer state sharded, gathered at use
    ``"spmd"``             :class:`SpmdStrategy` — general multi-axis
                           mesh with regex partition rules
    ``"auto"``             :class:`AutoStrategy` — the planner plane
                           (ray_lightning_tpu/plan/) picks strategy,
                           mesh, comm policy, donation and microbatch
                           from a cost model over the candidates above
    ``"mpmd"``             ``MpmdPipelineStrategy`` — pipeline
                           parallelism as N per-stage programs over
                           DCN with driver-side schedules
                           (ray_lightning_tpu/mpmd/; ``RLT_MPMD*``
                           env knobs configure it)
    =====================  ===============================================

    Unknown names raise a ``ValueError`` listing the valid set.
    """
    if strategy is None:
        return DataParallelStrategy()
    if isinstance(strategy, ShardingStrategy):
        return strategy
    if isinstance(strategy, str):
        key = strategy.lower()
        if key == "mpmd":
            from ray_lightning_tpu.mpmd.strategy import (
                MpmdPipelineStrategy)
            return MpmdPipelineStrategy()
        if key not in _STRATEGIES:
            raise ValueError(
                f"Unknown strategy {strategy!r}; valid strategy names: "
                f"{strategy_names()} (see resolve_strategy's docstring "
                f"or the README 'Parallelism' table for what each "
                f"selects)")
        return _STRATEGIES[key]()
    raise TypeError(f"Bad strategy: {strategy!r}")
