"""Driver-side plumbing (reference: ray_lightning/util.py:47-90).

``process_results`` is the driver's poll loop: wait on worker futures
while draining the worker→driver queue and executing relayed callables
(Tune reports/checkpoints) in the driver process — the "relay the
side-effect, not the call" pattern (SURVEY.md §3.3).  Telemetry items
(span batches, heartbeats — telemetry/) ride the same queue and are
routed to the active aggregator instead of executed; each poll
iteration also runs the heartbeat watchdog, so a dead or wedged worker
gets a named driver log line instead of a silent hang.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

from ray_lightning_tpu.cluster.backend import ClusterBackend, Future
from ray_lightning_tpu.telemetry.aggregator import get_active
from ray_lightning_tpu.utils.states import load_state_stream, to_state_stream

__all__ = ["process_results", "to_state_stream", "load_state_stream"]


def _handle_queue_item(item: Any) -> None:
    """Execute one queue item on the driver.  Items are ``(rank, payload)``
    tuples; telemetry-marked payloads feed the active aggregator;
    callable payloads are invoked here so driver-context APIs (e.g. the
    tune session) work (util.py:47-52 analog)."""
    if isinstance(item, tuple) and len(item) == 2:
        _rank, payload = item
    else:
        payload = item
    agg = get_active()
    if agg is not None and agg.maybe_ingest(payload):
        return
    from ray_lightning_tpu.core.datacheck import get_active_validator
    dc = get_active_validator()
    if dc is not None and dc.maybe_ingest(payload):
        return
    if callable(payload):
        payload()


def process_results(futures: Sequence[Future], backend: ClusterBackend,
                    poll_interval: float = 0.02) -> list:
    """Busy-poll worker futures, relaying queue items as they arrive
    (util.py:55-68 analog).  A worker error raises immediately, failing
    the whole run (parity with ray.get semantics, util.py:61-63) — with
    a per-rank telemetry diagnosis logged first when available."""
    pending = list(futures)
    while not all(f.done() for f in pending):
        drained = False
        while True:
            item = backend.queue_get_nowait()
            if item is None:
                break
            drained = True
            _handle_queue_item(item)
        agg = get_active()
        if agg is not None:
            agg.watchdog_check()
        from ray_lightning_tpu.core.datacheck import get_active_validator
        dc = get_active_validator()
        if dc is not None:
            dc.verify()  # raises on rank divergence (core/datacheck.py)
        for f in pending:
            if f.done():
                try:
                    f.result()  # raise worker errors eagerly
                except BaseException:
                    if agg is not None:
                        agg.log_failure_diagnosis()
                    raise
        if not drained:
            time.sleep(poll_interval)
    # final drain: items enqueued just before workers finished
    while True:
        item = backend.queue_get_nowait()
        if item is None:
            break
        _handle_queue_item(item)
    from ray_lightning_tpu.core.datacheck import get_active_validator
    dc = get_active_validator()
    if dc is not None:
        dc.verify()  # divergence relayed in the final flush still raises
    return [f.result() for f in pending]
