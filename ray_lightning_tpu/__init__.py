"""ray_lightning_tpu — a TPU-native distributed training framework.

Built from scratch with the capability surface of ``ray_lightning``
(`/root/reference`): drop-in trainer plugins that launch and manage
distributed training workers from a single driver script, plus Tune-style
hyperparameter sweeps.  Where the reference glues together PyTorch
Lightning + Ray + torch.distributed (NCCL), this framework is one coherent
TPU-first system:

- compute path: JAX/XLA — every training step is a single pjit'd SPMD
  program over a ``jax.sharding.Mesh``; gradient sync, ZeRO sharding and
  tensor/sequence parallelism are expressed as sharding annotations and
  compiled to ICI/DCN collectives by XLA (vs. the reference's
  DistributedDataParallel allreduce hooks, ray_ddp.py:467-468).
- orchestration: an actor runtime (``ray_lightning_tpu.cluster``) with a
  built-in subprocess backend and an optional Ray backend — one actor per
  TPU host (vs. one process per GPU, ray_ddp.py:174-186).
- rendezvous: the PJRT coordination service (``jax.distributed``) replaces
  the MASTER_ADDR/MASTER_PORT TCP store (ray_ddp.py:206-219).

Public API parity map (reference → here):
  ``RayPlugin``            → :class:`RayXlaPlugin`        (data parallel)
  ``RayShardedPlugin``     → :class:`RayXlaShardedPlugin` (ZeRO-1)
  ``HorovodRayPlugin``     → subsumed by :class:`RayXlaPlugin` (single
                             collective fabric on TPU; BASELINE north star)
  ``pl.Trainer``           → :class:`Trainer`
  ``pl.LightningModule``   → :class:`LightningModule`
  ``ray_lightning.tune``   → :mod:`ray_lightning_tpu.tune`
"""

from ray_lightning_tpu.core.module import LightningModule, StepContext
from ray_lightning_tpu.core.datamodule import LightningDataModule
from ray_lightning_tpu.core.data import DataLoader
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.core.callbacks import (
    Callback,
    EarlyStopping,
    ModelCheckpoint,
    ShardedCheckpoint,
)
from ray_lightning_tpu.utils.seed import seed_everything
from ray_lightning_tpu.utils.logger import CSVLogger
from ray_lightning_tpu.utils.profiling import (
    JaxProfilerCallback,
    ThroughputMonitor,
)
from ray_lightning_tpu.plugins import (
    RayXlaPlugin,
    RayXlaShardedPlugin,
    RayXlaSpmdPlugin,
)
from ray_lightning_tpu.comm import CommPolicy
from ray_lightning_tpu.elastic import ElasticConfig
from ray_lightning_tpu.plan import PlanConfig

__version__ = "0.1.0"


def __getattr__(name):
    # Server imports lazily (PEP 562): the serve plane is driver-side
    # API surface that fit-only worker subprocesses never touch, and
    # every actor spawn pays this package's import cost
    if name == "Server":
        from ray_lightning_tpu.serve import Server
        return Server
    if name == "FleetServer":
        from ray_lightning_tpu.serve.fleet import FleetServer
        return FleetServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "LightningModule",
    "StepContext",
    "LightningDataModule",
    "DataLoader",
    "Trainer",
    "Callback",
    "EarlyStopping",
    "ModelCheckpoint",
    "ShardedCheckpoint",
    "seed_everything",
    "CSVLogger",
    "ThroughputMonitor",
    "JaxProfilerCallback",
    "RayXlaPlugin",
    "RayXlaShardedPlugin",
    "RayXlaSpmdPlugin",
    "CommPolicy",
    "ElasticConfig",
    "PlanConfig",
    "Server",
    "FleetServer",
    "__version__",
]
