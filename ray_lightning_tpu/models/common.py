"""Shared scaffolding for classification-style LightningModules.

BERT fine-tuning, ResNet image classification (and any user model with
the logits→cross-entropy→accuracy shape) differ only in how they compute
logits and materialize data; the step/loader plumbing is identical.
Subclasses implement :meth:`compute_logits` and :meth:`make_dataset`.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.data import DataLoader
from ray_lightning_tpu.core.module import LightningModule


class ClassificationModule(LightningModule):
    """Cross-entropy classification over ``(inputs, int_labels)`` batches.

    Subclass contract:
      - ``compute_logits(ctx, inputs) -> [B, num_classes]``
      - ``make_dataset(n, seed) -> ArrayDataset`` of (inputs, labels)
      - attributes ``batch_size``, ``train_size``, ``val_size``
    """

    def compute_logits(self, ctx, inputs):
        raise NotImplementedError

    def make_dataset(self, n: int, seed: int):
        raise NotImplementedError

    # -- steps ------------------------------------------------------------

    def _logits_loss_acc(self, ctx, batch):
        inputs, labels = batch
        logits = self.compute_logits(ctx, inputs)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        acc = jnp.mean((jnp.argmax(logits, -1) == labels)
                       .astype(jnp.float32))
        return logits, loss, acc

    def training_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("loss", loss)
        ctx.log("train_accuracy", acc)
        return loss

    def validation_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("val_loss", loss)
        ctx.log("val_accuracy", acc)

    def test_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("test_loss", loss)
        ctx.log("test_accuracy", acc)

    def predict_step(self, ctx, batch):
        inputs = batch[0] if isinstance(batch, (tuple, list)) else batch
        return jnp.argmax(self.compute_logits(ctx, inputs), -1)

    # -- loaders ----------------------------------------------------------

    def _loader(self, n, seed, shuffle=False):
        return DataLoader(self.make_dataset(n, seed),
                          batch_size=self.batch_size, shuffle=shuffle,
                          drop_last=True)

    def train_dataloader(self):
        return self._loader(self.train_size, 0, shuffle=True)

    def val_dataloader(self):
        return self._loader(self.val_size, 1)

    def test_dataloader(self):
        return self._loader(self.val_size, 2)

    def predict_dataloader(self):
        return self.test_dataloader()
