"""GPT with pipeline-parallel blocks (parallel/pipeline.py).

Same transformer math as :mod:`models.gpt` — it literally reuses that
module's flax ``Block`` — but the blocks' parameters are *stacked* with
a leading layer dim so they can shard over the ``stage`` mesh axis and
run under the GPipe schedule.  This module manages raw parameters
through ``init_params`` / pure functions (the framework's
``configure_model() -> None`` escape hatch, core/module.py): flax's
module system wants one object per layer, while pipelining wants one
parameter tree scanned over — stacking at init is the TPU-native shape.

Beyond reference parity (SURVEY.md §2.3: PP absent there).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.data import DataLoader
from ray_lightning_tpu.core.module import LightningModule
from ray_lightning_tpu.models.gpt import (CONFIGS, Block, GPTConfig,
                                          _remat_policy,
                                          synthetic_lm_dataset)
from ray_lightning_tpu.parallel.pipeline import pipeline_forward


def _layernorm(x, scale, bias, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(
        x.dtype)


class PipelinedGPT(LightningModule):
    """Decoder LM whose blocks run under the GPipe schedule.

    ``n_microbatches`` divides the per-data-shard batch; bubble overhead
    shrinks as it grows ((S-1)/(M+S-1)).  On a mesh without a ``stage``
    axis the same code is a plain sequential scan — one model,
    any mesh.
    """

    def __init__(self, config: "GPTConfig | str" = "tiny",
                 n_microbatches: int = 2, lr: float = 3e-4,
                 weight_decay: float = 0.01, dataset_size: int = 256,
                 batch_size: int = 8):
        super().__init__()
        if isinstance(config, str):
            config = CONFIGS[config]
        if config.n_experts > 0:
            # GPT enables MoEMLP per layer (gpt.py Block use_moe); here
            # every block is dense, and the expert all-to-all would also
            # nest a shard_map inside the pipeline's manual region —
            # reject rather than silently train a different model
            raise ValueError(
                "PipelinedGPT does not support MoE configs yet; set "
                "GPTConfig(n_experts=0)")
        if config.dropout > 0:
            # dropout needs a per-layer RNG stream threaded through the
            # GPipe scan; silently training without it would diverge from
            # the equivalent GPT run, so fail loudly instead
            raise ValueError(
                "PipelinedGPT does not support dropout yet; set "
                "GPTConfig(dropout=0.0)")
        if config.attention_impl in ("auto", "ring"):
            # the pipeline body is already a manual (shard_map) region:
            # mesh-consulting impls would open a nested shard_map there
            # (trace error on multi-chip).  "local" = per-device flash on
            # TPU / dot elsewhere — the right choice inside the schedule.
            config = dataclasses.replace(config, attention_impl="local")
        self.config = config
        self.n_microbatches = n_microbatches
        self.save_hyperparameters("lr", "weight_decay", "batch_size")
        self.lr = lr
        self.weight_decay = weight_decay
        self.dataset_size = dataset_size
        self.batch_size = batch_size
        self._block = Block(config)

    # -- params ----------------------------------------------------------

    def init_params(self, rng, batch):
        cfg = self.config
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        k_emb, k_pos, k_blocks = jax.random.split(rng, 3)
        h0 = jnp.zeros((1, x.shape[1], cfg.n_embd), cfg.dtype)
        block_keys = jax.random.split(k_blocks, cfg.n_layer)
        # stacked block params: every leaf gains a leading n_layer dim —
        # the axis PipelineStrategy shards on `stage`
        blocks = jax.vmap(
            lambda k: self._block.init(k, h0, True)["params"])(block_keys)
        params = {
            "wte": jax.random.normal(k_emb, (cfg.vocab_size, cfg.n_embd),
                                     jnp.float32) * 0.02,
            "wpe": jax.random.normal(k_pos, (cfg.block_size, cfg.n_embd),
                                     jnp.float32) * 0.02,
            "blocks": blocks,
            "ln_f": {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                     "bias": jnp.zeros((cfg.n_embd,), jnp.float32)},
        }
        return {"params": params}

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=self.weight_decay,
                           b1=0.9, b2=0.95)

    # -- remat ladder (core/remat.py; planner axis) ----------------------

    def configure_remat(self):
        """Same ladder as GPT minus the MoE save lists (this model
        rejects MoE configs); one probe block kind — the scanned
        ``Block`` every stage runs."""
        from ray_lightning_tpu.core import remat as _rm

        policies = tuple(_rm.POLICY_LADDER)

        def apply(policy: str) -> None:
            if policy not in policies:
                raise ValueError(f"remat policy {policy!r}; this "
                                 f"config's ladder: {list(policies)}")
            cfg = self.config
            self.config = dataclasses.replace(
                cfg, remat=(policy != "off"),
                remat_policy=(policy if policy != "off"
                              else cfg.remat_policy))
            self._block = Block(self.config)

        def probe(policy: str, batch) -> _rm.RematProbe:
            cfg = self.config
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            B, T = int(x.shape[0]), int(x.shape[1])
            h = jax.ShapeDtypeStruct((B, T, cfg.n_embd), cfg.dtype)
            params = jax.eval_shape(
                lambda k: self._block.init(
                    k, jnp.zeros((1, T, cfg.n_embd), cfg.dtype),
                    True)["params"],
                jax.random.PRNGKey(0))

            def base_fn(p, hh):
                return self._block.apply({"params": p}, hh, True)

            if policy == "off":
                fn = base_fn
            else:
                pol = _rm.policy_object(policy)

                def fn(p, hh):
                    return jax.checkpoint(base_fn, policy=pol)(p, hh)

            s, f = _rm.block_cost(fn, base_fn, params, h)
            return _rm.RematProbe(saved_bytes=cfg.n_layer * s,
                                  recompute_flops=cfg.n_layer * f,
                                  n_blocks=cfg.n_layer, batch=B)

        return _rm.RematSpec(
            policies=policies,
            default=(self.config.remat_policy if self.config.remat
                     else "off"),
            apply=apply, probe=probe)

    # -- MPMD partition (ray_lightning_tpu/mpmd/) ------------------------

    def configure_mpmd(self):
        """Describe this model for the MPMD stage partitioner
        (``Trainer(strategy="mpmd")``): embedding and head as pure
        functions over their own param keys, one layer as the scanned
        ``stage_fn`` — the exact math of :meth:`_forward`/:meth:`_loss`
        split at the same seams the GPipe scan uses.  ``wte`` is tied:
        the embedding owns it, the head reads a mirror (the engine
        ships the head's wte grad back over the channel and
        re-broadcasts the updated value)."""
        import optax

        from ray_lightning_tpu.mpmd.partition import MpmdSpec

        cfg = self.config
        block = self._block

        def embed_fn(params, x):
            T = x.shape[1]
            return (params["wte"][x] + params["wpe"][:T]).astype(cfg.dtype)

        def stage_fn(layer_params, h):
            out = block.apply({"params": layer_params}, h, True)
            return out

        if cfg.remat:
            # same policy ladder as GPT (was boolean-only full remat):
            # MPMD stage programs can now trade stash memory against
            # recompute per policy — ROADMAP item 1c's prerequisite
            stage_fn = jax.checkpoint(
                stage_fn, policy=_remat_policy(cfg.remat_policy))

        def head_loss_fn(params, h, batch):
            _, y = batch
            h = _layernorm(h, params["ln_f"]["scale"],
                           params["ln_f"]["bias"])
            logits = jnp.einsum(
                "btc,vc->btv", h,
                params["wte"].astype(cfg.dtype)).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return MpmdSpec(n_layers=cfg.n_layer, embed_fn=embed_fn,
                        stage_fn=stage_fn, head_loss_fn=head_loss_fn,
                        stacked_key="blocks",
                        embed_keys=("wte", "wpe"),
                        head_keys=("ln_f",), tied_keys=("wte",))

    # -- compute ---------------------------------------------------------

    def _forward(self, params, idx):
        cfg = self.config
        T = idx.shape[1]
        h = (params["wte"][idx]
             + params["wpe"][:T]).astype(cfg.dtype)

        def stage_fn(layer_params, x):
            return self._block.apply({"params": layer_params}, x, True)

        if cfg.remat:
            # same HBM-for-FLOPs trade GPT applies via nn.remat
            # (gpt.py Block wrapping), at the SAME policy ladder —
            # replacing the old boolean-only (always-full) checkpoint
            stage_fn = jax.checkpoint(
                stage_fn, policy=_remat_policy(cfg.remat_policy))
        h = pipeline_forward(stage_fn, params["blocks"], h,
                             n_microbatches=self.n_microbatches)
        h = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        return jnp.einsum("btc,vc->btv", h,
                          params["wte"].astype(cfg.dtype)
                          ).astype(jnp.float32)

    def _loss(self, ctx, batch):
        x, y = batch
        logits = self._forward(ctx.params, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def training_step(self, ctx, batch):
        loss = self._loss(ctx, batch)
        ctx.log("loss", loss)
        return loss

    def validation_step(self, ctx, batch):
        ctx.log("val_loss", self._loss(ctx, batch))

    def test_step(self, ctx, batch):
        ctx.log("test_loss", self._loss(ctx, batch))

    def predict_step(self, ctx, batch):
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return jnp.argmax(self._forward(ctx.params, x), axis=-1)

    # -- data ------------------------------------------------------------

    def _loader(self, seed):
        ds = synthetic_lm_dataset(self.dataset_size, self.config.block_size,
                                  self.config.vocab_size, seed)
        return DataLoader(ds, batch_size=self.batch_size, drop_last=True)

    def train_dataloader(self):
        return self._loader(0)

    def val_dataloader(self):
        return self._loader(1)

    def test_dataloader(self):
        return self._loader(2)

    def predict_dataloader(self):
        return self._loader(3)
