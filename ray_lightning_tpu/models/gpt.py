"""GPT-style decoder LM — the flagship model family, designed TPU-first.

The reference's only "big model" is pl_bolts ImageGPT consumed as an
opaque import in its sharded example
(reference: examples/ray_ddp_sharded_example.py:8); the BASELINE configs
ask for GPT-2-1.3B multi-host sharded (config #5).  This is a from-scratch
flax implementation shaped for the TPU, not a port of any torch model:

- **MXU-friendly**: all FLOPs live in large batched matmuls
  (qkv/proj/mlp, logits); compute dtype is bfloat16 with fp32 params and
  fp32 softmax accumulation.
- **Static shapes / compiler-friendly**: fixed block size, causal mask
  built with ``jnp`.tril`` at trace time, no data-dependent Python.
- **Remat**: each block can be wrapped in ``jax.checkpoint`` (HBM for
  FLOPs trade, the standard long-sequence lever).
- **Sharding-ready**: ``gpt_partition_rules()`` gives SpmdStrategy
  regex rules for 2-D (data × tensor) or (data × fsdp) meshes; the
  attention core is pluggable (``attention_impl``) so ring attention
  (sequence parallelism) and the pallas flash kernel slot in.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
from ray_lightning_tpu.core.module import LightningModule
from ray_lightning_tpu.ops.attention import (  # noqa: F401  (re-export:
    MultiHeadAttention,           # tests and user code import the attention
    dot_product_attention,        # entry points from the model module)
    resolve_attention,
)

# back-compat alias (attention dispatch now lives in ops/attention.py)
_resolve_attention = resolve_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # multiple of 128 → clean MXU tiling
    block_size: int = 256
    n_layer: int = 4
    n_head: int = 4
    n_embd: int = 256
    dropout: float = 0.0
    remat: bool = True
    # Which intermediates the block remat SAVES instead of recomputing
    # (jax.checkpoint_policies): "full" = nothing saveable (max memory
    # savings, max recompute); "dots" = keep matmul outputs (recompute
    # only the cheap elementwise chains); "dots_no_batch" = keep only
    # batch-free matmul outputs (≈ params-shaped, tiny);
    # "dots_moe_act" / "dots_moe" = dots plus the named MoE
    # intermediates (ops/moe.py checkpoint_names — measured SLOWER than
    # plain dots on gpt2-moe-8e, kept as documented options);
    # "off" = save everything.  The policy is THE lever of the
    # memory-bound regime — measured walk in benchmarks/README.md
    # (gpt2-medium).  ``RLT_REMAT_POLICY`` overrides at model build for
    # A/B sweeps.
    remat_policy: str = "full"
    dtype: Any = jnp.bfloat16        # compute dtype; params stay fp32
    # "auto" | "dot" | "flash" | "ring" | "local" (ops/attention.py;
    # "local" = per-device flash/dot for manual shard_map regions)
    attention_impl: str = "auto"
    # >0: compute the LM loss with chunked_softmax_cross_entropy over this
    # many row chunks instead of full fp32 logits — the memory opt-in for
    # long-seq × large-vocab configs (ops/losses.py); 0 = fused full-vocab
    # loss (faster when the logits fit, measured on v5e)
    chunked_ce: int = 0
    # Mixture-of-Experts (ops/moe.py; beyond reference parity).  >0 swaps
    # the MLP of every ``moe_every``-th block for a routed MoEMLP whose
    # expert weights shard on the ``expert`` mesh axis.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_every: int = 2
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# Named configs.  "gpt2-1p3b" is the BASELINE #5 target (GPT-2-1.3B class).
CONFIGS = {
    "tiny": GPTConfig(vocab_size=512, block_size=64, n_layer=2, n_head=2,
                      n_embd=64, remat=False),
    # remat off: B=8xT=1024 activations fit a single chip's HBM easily and
    # recompute costs ~20% steps/sec (measured v5e); larger configs below
    # keep remat for memory headroom.
    "gpt2-small": GPTConfig(block_size=1024, n_layer=12, n_head=12,
                            n_embd=768, remat=False),
    # dots_saveable: keep matmul outputs, recompute only elementwise
    # chains — measured +17% steps/s over full remat on v5e (150.3 vs
    # 177.4 ms/step device) and still fits with 6+ GB to spare; policy
    # "off" needs 18.95 GB and OOMs (benchmarks/README.md round-4 walk)
    "gpt2-medium": GPTConfig(block_size=1024, n_layer=24, n_head=16,
                             n_embd=1024, remat_policy="dots"),
    # 1.3B class: remat + chunked CE — at T=2048 the full fp32 logits
    # alone would be ~1.6GB/example-batch; the chunked loss streams them
    "gpt2-1p3b": GPTConfig(block_size=2048, n_layer=24, n_head=32,
                           n_embd=2048, chunked_ce=16),
    # MoE variants (beyond parity): routed FFN every other block, expert
    # weights sharded on the `expert` mesh axis (ops/moe.py)
    "moe-tiny": GPTConfig(vocab_size=512, block_size=64, n_layer=2,
                          n_head=2, n_embd=64, remat=False, n_experts=4),
    # dots remat beats BOTH full remat (92.7 ms) and no remat (95.3 ms)
    # here: the dispatch/combine and expert-FFN intermediates are huge,
    # and recomputing their elementwise chains is cheaper than
    # round-tripping them through HBM (benchmarks/README.md round-4 MoE
    # table; 80.1 ms/step, MFU 0.44 → 0.535)
    "gpt2-moe-8e": GPTConfig(block_size=1024, n_layer=12, n_head=12,
                             n_embd=768, n_experts=8,
                             remat_policy="dots"),
}


class MLP(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="fc")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    config: GPTConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, deterministic: bool = True, *,
                 decode_cache=None, positions=None, page_table=None):
        cfg = self.config
        attn = MultiHeadAttention(
            n_head=cfg.n_head, causal=True, dropout=cfg.dropout,
            dtype=cfg.dtype, attention_impl=cfg.attention_impl,
            name="attn")
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        new_cache = None
        if decode_cache is not None:
            # serve-plane decode: the attention returns the updated slot
            # cache alongside its output (ops/attention.py)
            a, new_cache = attn(h, deterministic,
                                decode_cache=decode_cache,
                                positions=positions,
                                page_table=page_table)
            x = x + a
        else:
            x = x + attn(h, deterministic)
        if self.use_moe:
            from ray_lightning_tpu.ops.moe import MoEMLP
            ffn = MoEMLP(n_experts=cfg.n_experts, d_ff=4 * cfg.n_embd,
                         top_k=cfg.moe_top_k,
                         capacity_factor=cfg.moe_capacity_factor,
                         dtype=cfg.dtype, name="moe")
        else:
            ffn = MLP(cfg, name="mlp")
        x = x + ffn(nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x),
                    deterministic)
        return x if new_cache is None else (x, new_cache)


def _remat_policy(name: str):
    """jax.checkpoint policy for a config/env name (None = save nothing,
    jax's default — the max-recompute end of the walk).  The canonical
    name → policy mapping lives in core/remat.py ``policy_object`` (the
    planner's ``configure_remat`` machinery shares it); this wrapper
    keeps the ``RLT_REMAT_POLICY`` per-model-build override, which the
    planner pins its sweep to when set (plan/candidates.py
    ``resolve_remat_options``)."""
    from ray_lightning_tpu.core.remat import policy_object
    return policy_object(os.environ.get("RLT_REMAT_POLICY") or name)


class GPT(nn.Module):
    """Decoder-only transformer; ``__call__(tokens) -> logits``.

    ``hidden()`` exposes the pre-head representation so losses can chunk
    the vocab projection (ops/losses.py) instead of materializing the
    full fp32 [B·T, V] logits tensor — at V=50k that tensor dominates
    HBM traffic in the loss.  setup-style so both methods share the
    submodules; param paths are identical to the previous compact form.
    """

    config: GPTConfig

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, name="wte",
                            dtype=cfg.dtype)
        self.wpe = self.param("wpe", nn.initializers.normal(0.02),
                              (cfg.block_size, cfg.n_embd))
        block = Block
        if cfg.remat:
            # trade FLOPs for HBM: recompute block activations on
            # backward, keeping whatever the policy marks saveable
            block = nn.remat(Block, static_argnums=(2,),
                             policy=_remat_policy(cfg.remat_policy))
        self.blocks = [
            block(cfg, use_moe=(cfg.n_experts > 0
                                and i % cfg.moe_every == cfg.moe_every - 1),
                  name=f"h{i}")
            for i in range(cfg.n_layer)]
        self.ln_f = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")

    def hidden(self, idx, deterministic: bool = True):
        """Pre-head representation ``[B, T, C]`` in the compute dtype."""
        cfg = self.config
        B, T = idx.shape
        x = self.wte(idx) + self.wpe[:T].astype(cfg.dtype)
        for blk in self.blocks:
            x = blk(x, deterministic)
        return self.ln_f(x)

    @property
    def embedding_table(self):
        return self.wte.embedding

    def __call__(self, idx, deterministic: bool = True):
        x = self.hidden(idx, deterministic)
        # tied output head: attend promotes operands to the compute dtype
        # (bf16 on the MXU, fp32 accumulation implicit on TPU); logits
        # upcast to fp32 only for the loss softmax.
        return self.wte.attend(x).astype(jnp.float32)

    def decode(self, tokens, positions, k_caches, v_caches,
               page_table=None):
        """One continuous-batching decode step over ``S`` batch slots
        (the serve plane's hot program, ray_lightning_tpu/serve/).

        ``tokens`` [S] int32 — each slot's current token; ``positions``
        [S] int32 — that token's absolute position; ``k_caches`` /
        ``v_caches`` [n_layer, S, L, H, D] — the slot-indexed KV cache.
        Writes each token's K/V at its slot position and returns
        ``(logits [S, V] fp32, new_k, new_v)``.  Traces with STATIC
        shapes regardless of which slots are live — in-flight request
        insertion/eviction happens by slot index, never by re-trace.

        Use through ``configure_decode_model()`` (remat/dropout off);
        MoE configs are rejected by the serve engine (token routing is
        batch-shaped, unsupported in the decode path).  ``page_table``
        ([S, pages_per_slot] int32, serve/fleet/pages.py) rides down to
        ``cached_attention`` for the paged flash-decode kernel; ``None``
        keeps the slot-contiguous layout.
        """
        cfg = self.config
        x = self.wte(tokens[:, None])
        x = x + jnp.take(self.wpe, positions, axis=0)[:, None, :].astype(
            cfg.dtype)
        new_k, new_v = [], []
        for i, blk in enumerate(self.blocks):
            x, (k, v) = blk(x, True,
                            decode_cache=(k_caches[i], v_caches[i]),
                            positions=positions, page_table=page_table)
            new_k.append(k)
            new_v.append(v)
        x = self.ln_f(x)
        logits = self.wte.attend(x).astype(jnp.float32)
        return logits[:, 0], jnp.stack(new_k), jnp.stack(new_v)

    def verify(self, tokens, positions, k_caches, v_caches,
               page_table=None):
        """Multi-token decode over ``S`` slots — the speculative-decode
        verify forward (core/steps.py ``build_verify_step``).

        ``tokens`` / ``positions`` [S, T] int32 — per slot, the last
        emitted token followed by the k drafted tokens at consecutive
        positions (T = k+1); caches as in :meth:`decode`.  ONE batched
        target forward writes every query's K/V row and scores each
        query under its own position bound (ops/attention.py
        multi-query ``cached_attention``), so the argmax at query j is
        numerically THE token plain decode would emit after accepting
        drafts 1..j — greedy parity is exact by construction, not by
        tolerance.  Rows written for later-rejected drafts are stale
        but masked (never at or below any live query's bound) and are
        overwritten by the next round, which restarts at the first
        corrected position.  Returns ``(logits [S, T, V] fp32, new_k,
        new_v)``.
        """
        cfg = self.config
        x = self.wte(tokens)
        # gather clamps out-of-range positions (slots speculating past
        # the cache end read wpe[-1]; their outputs are truncated by the
        # scheduler's max_new cap before anything is emitted)
        x = x + jnp.take(self.wpe, positions, axis=0).astype(cfg.dtype)
        new_k, new_v = [], []
        for i, blk in enumerate(self.blocks):
            x, (k, v) = blk(x, True,
                            decode_cache=(k_caches[i], v_caches[i]),
                            positions=positions, page_table=page_table)
            new_k.append(k)
            new_v.append(v)
        x = self.ln_f(x)
        logits = self.wte.attend(x).astype(jnp.float32)
        return logits, jnp.stack(new_k), jnp.stack(new_v)


def gpt_partition_rules(tensor_axis: str = "tensor") -> list[tuple[str, P]]:
    """SpmdStrategy rules for a (data, [fsdp,] tensor) mesh.

    Megatron-style: qkv/fc column-split, proj/out row-split; embeddings
    vocab-split.  XLA inserts the matching all-reduces on ``tensor``
    (riding ICI because tensor is the innermost mesh axis,
    parallel/mesh.py).
    """
    from ray_lightning_tpu.ops.moe import moe_partition_rules
    return moe_partition_rules(tensor_axis=tensor_axis) + [
        (r"wte/embedding", P(tensor_axis, None)),
        (r"attn/qkv/kernel", P(None, tensor_axis)),
        (r"attn/proj/kernel", P(tensor_axis, None)),
        (r"mlp/fc/kernel", P(None, tensor_axis)),
        (r"mlp/out/kernel", P(tensor_axis, None)),
        # no wpe rule: position embeddings fall through to the fsdp
        # fallback — sharded when an fsdp axis exists (at T=2048 C=2048
        # they are 4M params; pinning them replicated was waste),
        # replicated otherwise
    ]


def synthetic_lm_dataset(n: int, block_size: int, vocab_size: int,
                         seed: int = 0) -> ArrayDataset:
    """Deterministic token sequences with learnable structure (each token
    depends on the previous one), so loss decreases measurably fast."""
    rng = np.random.default_rng(seed)
    perm = np.random.default_rng(7).permutation(vocab_size)
    first = rng.integers(0, vocab_size, size=(n, 1))
    seqs = [first]
    for _ in range(block_size):
        # next token = perm[prev] with 10% noise
        nxt = perm[seqs[-1]]
        noise = rng.integers(0, vocab_size, size=(n, 1))
        mask = rng.random((n, 1)) < 0.1
        seqs.append(np.where(mask, noise, nxt))
    toks = np.concatenate(seqs, axis=1).astype(np.int32)
    return ArrayDataset(toks[:, :-1], toks[:, 1:])


class GPTLightningModule(LightningModule):
    """LM training module over :class:`GPT` (next-token cross-entropy)."""

    def __init__(self, config: "GPTConfig | str" = "tiny",
                 lr: float = 3e-4, weight_decay: float = 0.01,
                 warmup_steps: int = 10, dataset_size: int = 256,
                 batch_size: int = 8):
        super().__init__()
        if isinstance(config, str):
            config = CONFIGS[config]
        self.config = config
        self.save_hyperparameters("lr", "weight_decay", "batch_size")
        self.lr = lr
        self.weight_decay = weight_decay
        self.warmup_steps = warmup_steps
        self.dataset_size = dataset_size
        self.batch_size = batch_size

    def configure_model(self):
        return GPT(self.config)

    def configure_remat(self):
        """Planner-plane remat surface (core/remat.py): the GPT policy
        ladder — plus the ``checkpoint_name``-based MoE save lists when
        this config routes experts — with a per-block probe pricing any
        policy from avals alone.  ``apply`` folds a policy back into the
        config the way ``RLT_REMAT_POLICY`` used to per-build ("off"
        drops the ``nn.remat`` wrap entirely, matching the tiny/small
        configs' ``remat=False``)."""
        from ray_lightning_tpu.core import remat as _rm

        policies = list(_rm.POLICY_LADDER)
        if self.config.n_experts > 0:
            policies += list(_rm.MOE_POLICIES)

        def apply(policy: str) -> None:
            if policy not in policies:
                raise ValueError(f"remat policy {policy!r}; this "
                                 f"config's ladder: {policies}")
            cfg = self.config
            self.config = dataclasses.replace(
                cfg, remat=(policy != "off"),
                remat_policy=(policy if policy != "off"
                              else cfg.remat_policy))
            self.model = None   # next setup_model() rebuilds the wrap

        _base_flops: dict = {}   # (use_moe, B, T) -> baseline bwd flops

        def probe(policy: str, batch) -> _rm.RematProbe:
            cfg = self.config
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            B, T = int(x.shape[0]), int(x.shape[1])
            h = jax.ShapeDtypeStruct((B, T, cfg.n_embd), cfg.dtype)
            n_moe = sum(
                1 for i in range(cfg.n_layer)
                if cfg.n_experts > 0
                and i % cfg.moe_every == cfg.moe_every - 1)
            saved = flops = 0
            for count, use_moe in ((cfg.n_layer - n_moe, False),
                                   (n_moe, True)):
                if count == 0:
                    continue

                def base_fn(p, hh, _moe=use_moe):
                    return Block(cfg, use_moe=_moe).apply(
                        {"params": p}, hh, True)

                params = jax.eval_shape(
                    lambda k, _moe=use_moe: Block(cfg, use_moe=_moe).init(
                        k, jnp.zeros((1, T, cfg.n_embd), cfg.dtype),
                        True)["params"],
                    jax.random.PRNGKey(0))
                key = (use_moe, B, T)
                if key not in _base_flops:
                    _base_flops[key] = _rm.grad_dot_flops(base_fn,
                                                          params, h)
                if policy == "off":
                    fn = base_fn
                else:
                    blk = nn.remat(
                        Block, static_argnums=(2,),
                        policy=_rm.policy_object(policy))(
                            cfg, use_moe=use_moe)

                    def fn(p, hh, _b=blk):
                        return _b.apply({"params": p}, hh, True)

                s, f = _rm.block_cost(fn, base_fn, params, h,
                                      base_flops=_base_flops[key])
                saved += count * s
                flops += count * f
            return _rm.RematProbe(saved_bytes=saved,
                                  recompute_flops=flops,
                                  n_blocks=self.config.n_layer, batch=B)

        return _rm.RematSpec(
            policies=tuple(policies),
            default=(self.config.remat_policy if self.config.remat
                     else "off"),
            apply=apply, probe=probe)

    def configure_decode_model(self):
        """Serve-plane model (serve/engine.py): the SAME param tree as
        the training model — remat off (no backward pass to save memory
        for; kwargs-through-remat is also fragile) and dropout off
        (generation is deterministic)."""
        if self.config.n_experts > 0:
            raise ValueError(
                "serve decode does not support MoE configs: expert "
                "routing is batch-shaped and has no single-token cache "
                "path yet (models/gpt.py GPT.decode)")
        return GPT(dataclasses.replace(self.config, remat=False,
                                       dropout=0.0))

    def configure_draft(self, layers: "int | None" = None):
        """Speculative-decode draft sibling (serve/engine.py): the SAME
        architecture truncated to the first ``layers`` blocks (default
        ``n_layer // 2``), sharing the target's weights — ``wte``,
        ``wpe``, ``h0..h{layers-1}`` and ``ln_f`` are a subtree of the
        target param tree, so the engine derives draft params by path
        with ZERO extra HBM (unless ``RLT_DRAFT_QUANT`` opts into an
        int8 resident copy).  A layer-truncated residual LM is the
        classic self-speculation draft: early blocks carry most of the
        next-token signal, so acceptance is real without any separate
        draft training.  ``layers == n_layer`` is the degenerate
        full-clone draft (acceptance 1.0 — the test fixture for the
        accept-k pattern)."""
        if self.config.n_experts > 0:
            raise ValueError(
                "speculative decode does not support MoE configs: the "
                "draft/verify path rides GPT.decode/verify, which "
                "reject expert routing (configure_decode_model)")
        cfg = self.config
        n = int(layers) if layers else max(1, cfg.n_layer // 2)
        if not 1 <= n <= cfg.n_layer:
            raise ValueError(
                f"draft layers {n} must be in [1, {cfg.n_layer}]")
        return GPT(dataclasses.replace(cfg, n_layer=n, remat=False,
                                       dropout=0.0))

    @property
    def param_dtype(self):
        # bf16-resident params (RLT_BF16_PARAMS=0 opts out): deletes the
        # per-step fp32->bf16 kernel casts (~8.7 ms/step of dtype-convert
        # fusions in the gpt2-small device trace) and halves DDP gradient
        # bytes; the fp32 master copy in the optimizer state
        # (ops/optim.py fp32_master) keeps update precision
        return (jnp.bfloat16
                if os.environ.get("RLT_BF16_PARAMS", "1") != "0" else None)

    def configure_optimizers(self):
        sched = optax.linear_schedule(0.0, self.lr, self.warmup_steps)
        # bf16 first moment (RLT_BF16_MOMENTS=0 opts out): halves mu's
        # HBM traffic in the optimizer update with no measurable quality
        # change on the LM objective (nu stays fp32 — optax exposes only
        # mu_dtype, and the second moment is variance-scale sensitive)
        mu_dtype = (jnp.bfloat16
                    if os.environ.get("RLT_BF16_MOMENTS", "1") != "0"
                    else None)
        tx = optax.adamw(sched, weight_decay=self.weight_decay,
                         b1=0.9, b2=0.95, mu_dtype=mu_dtype)
        if self.param_dtype is not None:
            from ray_lightning_tpu.ops.optim import fp32_master
            tx = fp32_master(tx)
        return tx

    def _loss(self, ctx, batch):
        x, y = batch
        if self.config.chunked_ce > 0:
            # memory-lean loss: never materialize full fp32 logits
            # (ops/losses.py; the opt-in for long-seq × 50k-vocab configs)
            from ray_lightning_tpu.ops.losses import (
                chunked_softmax_cross_entropy)
            h = ctx.apply(x, not ctx.training, method=GPT.hidden)
            # read the tied table from params directly: a second
            # ctx.apply would consume an extra dropout-RNG split and
            # change training trajectories vs the full-vocab path
            table = ctx.params["wte"]["embedding"]
            return chunked_softmax_cross_entropy(
                h, table, y, self.config.chunked_ce)
        if os.environ.get("RLT_FUSED_CE", "1") != "0":
            # default full-vocab loss: bf16-resident logits, fp32
            # accumulation inside the reduction fusions (ops/losses.py
            # fused_lm_cross_entropy — measured win on the v5e headline;
            # RLT_FUSED_CE=0 restores the fp32-logits path)
            from ray_lightning_tpu.ops.losses import fused_lm_cross_entropy
            h = ctx.apply(x, not ctx.training, method=GPT.hidden)
            table = ctx.params["wte"]["embedding"]
            return fused_lm_cross_entropy(h, table, y)
        logits = ctx.apply(x, not ctx.training)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def training_step(self, ctx, batch):
        loss = self._loss(ctx, batch)
        if self.config.n_experts > 0:
            # routed layers sowed their load-balance losses during the
            # forward pass (mutable collections only flow back to the
            # context under training, core/module.py ctx.apply)
            from ray_lightning_tpu.ops.moe import total_aux_loss
            aux = total_aux_loss(ctx.model_state)
            if aux is not None:
                ctx.log("moe_aux", aux)
                loss = loss + self.config.moe_aux_weight * aux
        ctx.log("loss", loss)
        return loss

    def validation_step(self, ctx, batch):
        ctx.log("val_loss", self._loss(ctx, batch))

    def test_step(self, ctx, batch):
        ctx.log("test_loss", self._loss(ctx, batch))

    def predict_step(self, ctx, batch):
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return jnp.argmax(ctx.apply(x, True), axis=-1)

    def _loader(self, seed):
        ds = synthetic_lm_dataset(self.dataset_size, self.config.block_size,
                                  self.config.vocab_size, seed)
        return DataLoader(ds, batch_size=self.batch_size, drop_last=True)

    def train_dataloader(self):
        return self._loader(0)

    def val_dataloader(self):
        return self._loader(1)

    def test_dataloader(self):
        return self._loader(2)

    def predict_dataloader(self):
        return self._loader(3)
