"""ResNet family (v1.5 bottleneck) for image classification.

BASELINE.md config #2: "ResNet-50 / CIFAR-10 LightningModule via
RayXlaPlugin DDP".  The reference trains vision models only through
pl_bolts imports (examples/ray_ddp_sharded_example.py:8); here the model
family is in-tree and TPU-first:

- NHWC layout throughout — the native TPU convolution layout (XLA lowers
  NHWC convs straight onto the MXU without transposes);
- bf16 compute with fp32 params and fp32 BatchNorm statistics (the
  running means/vars live in the ``batch_stats`` collection, threaded
  through the compiled step by StepContext — core/module.py:94-102);
- synthetic CIFAR-10-shaped data for hermetic learning-signal tests
  (no downloads in CI, same device as models/boring.py synthetic_mnist).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ray_lightning_tpu.core.data import ArrayDataset
from ray_lightning_tpu.models.common import ClassificationModule


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # resnet-50
    bottleneck: bool = True
    num_classes: int = 10
    width: int = 64
    # cifar stem: 3x3/s1 conv, no max-pool (32x32 inputs); imagenet stem:
    # 7x7/s2 + 3x3 max-pool
    cifar_stem: bool = True
    dtype: Any = jnp.bfloat16


CONFIGS = {
    "resnet18": ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False),
    "resnet34": ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=False),
    "resnet50": ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True),
    "resnet101": ResNetConfig(stage_sizes=(3, 4, 23, 3), bottleneck=True),
}


class ResNetBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        # zero-init the last norm's scale: residual branches start as
        # identity, the standard trick for stable large-batch training
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """``__call__(images[N,H,W,C], train) -> logits``; NHWC, bf16."""

    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        x = x.astype(cfg.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=cfg.dtype)
        if cfg.cifar_stem:
            x = nn.Conv(cfg.width, (3, 3), use_bias=False,
                        dtype=cfg.dtype, name="stem")(x)
        else:
            x = nn.Conv(cfg.width, (7, 7), (2, 2), use_bias=False,
                        dtype=cfg.dtype, name="stem")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        if not cfg.cifar_stem:
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        block = BottleneckBlock if cfg.bottleneck else ResNetBlock
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for b in range(n_blocks):
                strides = 2 if stage > 0 and b == 0 else 1
                x = block(cfg.width * 2 ** stage, strides, cfg.dtype,
                          name=f"s{stage}b{b}")(x, train)
        x = jnp.mean(x, axis=(1, 2))                    # global avg pool
        # head in fp32: tiny matmul, and logits feed the loss softmax
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


def synthetic_cifar10(n: int, seed: int = 0) -> ArrayDataset:
    """Separable CIFAR-10-shaped data: class-dependent mean images plus
    noise (hermetic learning-signal tests, models/boring.py pattern)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    base = np.random.default_rng(1234).standard_normal(
        (10, 32, 32, 3)).astype(np.float32)
    x = base[labels] + 0.4 * rng.standard_normal(
        (n, 32, 32, 3)).astype(np.float32)
    return ArrayDataset(x.astype(np.float32), labels.astype(np.int32))


class ResNetLightningModule(ClassificationModule):
    """Image-classification module (BASELINE config #2 workload)."""

    def __init__(self, config: "ResNetConfig | str" = "resnet50",
                 lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 1e-4, batch_size: int = 32,
                 train_size: int = 512, val_size: int = 128):
        super().__init__()
        if isinstance(config, str):
            config = CONFIGS[config]
        self.config = config
        self.save_hyperparameters("lr", "momentum", "batch_size")
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.batch_size = batch_size
        self.train_size = train_size
        self.val_size = val_size

    def configure_model(self):
        return ResNet(self.config)

    def configure_optimizers(self):
        return optax.chain(
            optax.add_decayed_weights(self.weight_decay),
            optax.sgd(self.lr, momentum=self.momentum, nesterov=True))

    def compute_logits(self, ctx, images):
        return ctx.apply(images, ctx.training)

    def make_dataset(self, n, seed):
        return synthetic_cifar10(n, seed)
