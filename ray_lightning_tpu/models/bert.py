"""BERT-style bidirectional encoder for fine-tuning.

BASELINE.md config #4: "BERT-base fine-tune via RayXlaShardedPlugin
(FairScale OSS → XLA ZeRO-1)".  The reference has no in-tree language
models at all (only pl_bolts imports); this family supplies the
fine-tune workload TPU-first:

- bf16 compute / fp32 params (gpt.py pattern), bidirectional attention
  through the same attention impls as GPT (``dot`` XLA attention or the
  Pallas flash kernel with ``causal=False``);
- a classification head for sequence-level fine-tuning;
- synthetic class-dependent token data for hermetic learning tests;
- Megatron-style partition rules (qkv/mlp-in column, proj/mlp-out row)
  reusable by SpmdStrategy for tensor-parallel fine-tunes.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
from ray_lightning_tpu.core.module import LightningModule
from ray_lightning_tpu.models.common import ClassificationModule
from ray_lightning_tpu.ops.attention import MultiHeadAttention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30592          # 30522 padded to a multiple of 128
    max_len: int = 512
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    intermediate: int = 3072
    num_classes: int = 2
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"
    # remat lever, same ladder as GPT (models/gpt.py GPTConfig): off by
    # default — fine-tune batches fit easily — but present so the
    # planner's remat axis (plan/) covers the BERT family too
    remat: bool = False
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


CONFIGS = {
    "tiny": BertConfig(vocab_size=512, max_len=64, n_layer=2, n_head=2,
                       n_embd=64, intermediate=128),
    "bert-base": BertConfig(),
    "bert-large": BertConfig(n_layer=24, n_head=16, n_embd=1024,
                             intermediate=4096),
}


class EncoderLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        x = x + MultiHeadAttention(
            n_head=cfg.n_head, causal=False,  # bidirectional encoder
            dropout=cfg.dropout, dtype=cfg.dtype,
            attention_impl=cfg.attention_impl, name="attn")(
            h, deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = nn.gelu(nn.Dense(cfg.intermediate, dtype=cfg.dtype,
                             name="fc")(h))
        h = nn.Dense(C, dtype=cfg.dtype, name="out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h


class BertEncoder(nn.Module):
    """``__call__(tokens[B,T]) -> hidden[B,T,C]`` (pre-LN encoder)."""

    config: BertConfig

    @nn.compact
    def __call__(self, idx, deterministic: bool = True):
        cfg = self.config
        B, T = idx.shape
        tok = nn.Embed(cfg.vocab_size, cfg.n_embd, name="wte",
                       dtype=cfg.dtype)(idx)
        pos = self.param("wpe", nn.initializers.normal(0.02),
                         (cfg.max_len, cfg.n_embd))
        x = tok + pos[:T].astype(cfg.dtype)
        layer = EncoderLayer
        if cfg.remat:
            # HBM-for-FLOPs trade per encoder layer, same policy ladder
            # as GPT's Block wrap (models/gpt.py)
            from ray_lightning_tpu.models.gpt import _remat_policy
            layer = nn.remat(EncoderLayer, static_argnums=(2,),
                             policy=_remat_policy(cfg.remat_policy))
        for i in range(cfg.n_layer):
            x = layer(cfg, name=f"h{i}")(x, deterministic)
        return nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)


class BertClassifier(nn.Module):
    """Sequence classification: mean-pooled encoder output → classes."""

    config: BertConfig

    @nn.compact
    def __call__(self, idx, deterministic: bool = True):
        cfg = self.config
        h = BertEncoder(cfg, name="encoder")(idx, deterministic)
        pooled = jnp.mean(h.astype(jnp.float32), axis=1)
        pooled = jnp.tanh(nn.Dense(cfg.n_embd, dtype=jnp.float32,
                                   name="pooler")(pooled))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


class BertForMaskedLM(nn.Module):
    """Masked-LM head over the encoder: ``[B, T] -> [B, T, V]`` logits
    (fp32 for the loss softmax; the matmul runs in the compute dtype)."""

    config: BertConfig

    @nn.compact
    def __call__(self, idx, deterministic: bool = True):
        cfg = self.config
        h = BertEncoder(cfg, name="encoder")(idx, deterministic)
        return nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                        name="mlm_head")(h).astype(jnp.float32)


def _bert_remat_spec(module):
    """Shared ``configure_remat()`` body for both BERT modules (they
    differ only in the head; the remat lever wraps the encoder layers
    both share).  Same spec shape as GPT's (models/gpt.py), no MoE
    extras."""
    from ray_lightning_tpu.core import remat as _rm

    policies = tuple(_rm.POLICY_LADDER)

    def apply(policy: str) -> None:
        if policy not in policies:
            raise ValueError(f"remat policy {policy!r}; this config's "
                             f"ladder: {list(policies)}")
        cfg = module.config
        module.config = dataclasses.replace(
            cfg, remat=(policy != "off"),
            remat_policy=(policy if policy != "off"
                          else cfg.remat_policy))
        module.model = None

    def probe(policy: str, batch) -> _rm.RematProbe:
        cfg = module.config
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        B, T = int(x.shape[0]), int(x.shape[1])
        h = jax.ShapeDtypeStruct((B, T, cfg.n_embd), cfg.dtype)
        params = jax.eval_shape(
            lambda k: EncoderLayer(cfg).init(
                k, jnp.zeros((1, T, cfg.n_embd), cfg.dtype),
                True)["params"],
            jax.random.PRNGKey(0))

        def base_fn(p, hh):
            return EncoderLayer(cfg).apply({"params": p}, hh, True)

        if policy == "off":
            fn = base_fn
        else:
            lyr = nn.remat(EncoderLayer, static_argnums=(2,),
                           policy=_rm.policy_object(policy))(cfg)

            def fn(p, hh):
                return lyr.apply({"params": p}, hh, True)

        s, f = _rm.block_cost(fn, base_fn, params, h)
        return _rm.RematProbe(saved_bytes=cfg.n_layer * s,
                              recompute_flops=cfg.n_layer * f,
                              n_blocks=cfg.n_layer, batch=B)

    return _rm.RematSpec(
        policies=policies,
        default=(module.config.remat_policy if module.config.remat
                 else "off"),
        apply=apply, probe=probe)


class BertMLMModule(LightningModule):
    """Masked-LM pretraining (BERT's pretext task, TPU-first).

    Masking happens *inside the compiled step* with the step's PRNG
    stream — static shapes, no host-side mask generation per batch: a
    Bernoulli(mask_prob) mask selects positions, masked inputs are
    replaced by the reserved last vocab id, and the loss averages
    cross-entropy over masked positions only.
    """

    def __init__(self, config: "BertConfig | str" = "tiny",
                 lr: float = 1e-4, weight_decay: float = 0.01,
                 mask_prob: float = 0.15, batch_size: int = 8,
                 train_size: int = 256, val_size: int = 64):
        super().__init__()
        if isinstance(config, str):
            config = CONFIGS[config]
        self.config = config
        self.save_hyperparameters("lr", "mask_prob", "batch_size")
        self.lr = lr
        self.weight_decay = weight_decay
        self.mask_prob = mask_prob
        self.batch_size = batch_size
        self.train_size = train_size
        self.val_size = val_size

    def configure_model(self):
        return BertForMaskedLM(self.config)

    def configure_remat(self):
        return _bert_remat_spec(self)

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=self.weight_decay)

    def _mlm_loss(self, ctx, tokens, rng):
        mask_token = self.config.vocab_size - 1
        mask = jax.random.bernoulli(rng, self.mask_prob, tokens.shape)
        inputs = jnp.where(mask, mask_token, tokens)
        logits = ctx.apply(inputs, not ctx.training)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tokens)
        weights = mask.astype(jnp.float32)
        return (ce * weights).sum() / jnp.maximum(weights.sum(), 1.0)

    def training_step(self, ctx, batch):
        tokens = batch[0] if isinstance(batch, (tuple, list)) else batch
        loss = self._mlm_loss(ctx, tokens, ctx.make_rng())
        ctx.log("loss", loss)
        return loss

    def validation_step(self, ctx, batch):
        tokens = batch[0] if isinstance(batch, (tuple, list)) else batch
        # fixed eval mask: deterministic metric across runs
        ctx.log("val_loss", self._mlm_loss(
            ctx, tokens, jax.random.PRNGKey(0)))

    def _loader(self, n, seed, shuffle=False):
        from ray_lightning_tpu.models.gpt import synthetic_lm_dataset
        # the steps unpack batch[0], so the (inputs, targets) dataset can
        # pass through as-is — no need to copy the token matrix out
        ds = synthetic_lm_dataset(n, self.config.max_len,
                                  self.config.vocab_size - 1, seed)
        return DataLoader(ds, batch_size=self.batch_size, shuffle=shuffle,
                          drop_last=True)

    def train_dataloader(self):
        return self._loader(self.train_size, 0, shuffle=True)

    def val_dataloader(self):
        return self._loader(self.val_size, 1)


def bert_partition_rules(tensor_axis: str = "tensor") -> list:
    """SpmdStrategy rules: Megatron column/row splits (gpt.py pattern)."""
    t = tensor_axis
    return [
        ("wte/embedding", P(t, None)),
        ("qkv/kernel", P(None, t)),
        ("proj/kernel", P(t, None)),
        ("fc/kernel", P(None, t)),
        ("out/kernel", P(t, None)),
        ("mlm_head/kernel", P(None, t)),   # vocab-split MLM projection
        # no catch-all: unmatched params fall through to SpmdStrategy's
        # replicate-or-fsdp fallback (strategy.py _fsdp_fallback)
    ]


def synthetic_classification(n: int, cfg: BertConfig,
                             seed: int = 0) -> ArrayDataset:
    """Class-dependent token distributions: each class draws tokens from
    its own vocab band, so a short fine-tune must become separable."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, cfg.num_classes, size=n)
    band = cfg.vocab_size // max(2, cfg.num_classes)
    tokens = (rng.integers(0, band, size=(n, cfg.max_len))
              + labels[:, None] * band)
    return ArrayDataset(tokens.astype(np.int32), labels.astype(np.int32))


class BertLightningModule(ClassificationModule):
    """Sequence-classification fine-tune (BASELINE config #4 workload)."""

    def __init__(self, config: "BertConfig | str" = "tiny",
                 lr: float = 5e-5, weight_decay: float = 0.01,
                 warmup_steps: int = 10, batch_size: int = 8,
                 train_size: int = 256, val_size: int = 64):
        super().__init__()
        if isinstance(config, str):
            config = CONFIGS[config]
        self.config = config
        self.save_hyperparameters("lr", "batch_size")
        self.lr = lr
        self.weight_decay = weight_decay
        self.warmup_steps = warmup_steps
        self.batch_size = batch_size
        self.train_size = train_size
        self.val_size = val_size

    def configure_model(self):
        return BertClassifier(self.config)

    def configure_remat(self):
        return _bert_remat_spec(self)

    def configure_optimizers(self):
        sched = optax.linear_schedule(0.0, self.lr, self.warmup_steps)
        return optax.adamw(sched, weight_decay=self.weight_decay)

    def compute_logits(self, ctx, tokens):
        return ctx.apply(tokens, not ctx.training)

    def make_dataset(self, n, seed):
        return synthetic_classification(n, self.config, seed)
