"""Test-fixture models (reference: ray_lightning/tests/utils.py:16-148).

``BoringModel``: linear 32→2 regression against zeros — the minimal model
that exercises the full train/val/test/predict surface (utils.py:28-96).
``LightningMNISTClassifier``: 3-layer MLP over a synthetic MNIST-shaped
dataset (utils.py:99-148) — end-to-end learning-signal tests assert its
accuracy.  Both are flax modules driven through the framework's
LightningModule contract.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
from ray_lightning_tpu.core.module import LightningModule


class RandomDataset(ArrayDataset):
    """(size, length) gaussian dataset (tests/utils.py:16-25 analog)."""

    def __init__(self, size: int, length: int, seed: int = 0):
        rng = np.random.default_rng(seed)
        super().__init__(rng.standard_normal((length, size),
                                             dtype=np.float32))


class _Linear(nn.Module):
    features: int = 2

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.features)(x)


class BoringModel(LightningModule):
    """Minimal end-to-end module (tests/utils.py:28-96 analog)."""

    uses_rng = False    # deterministic linear model

    def __init__(self, lr: float = 0.1, dataset_length: int = 64,
                 batch_size: int = 2):
        super().__init__()
        self.save_hyperparameters()
        self.lr = lr
        self.dataset_length = dataset_length
        self.batch_size = batch_size

    def configure_model(self):
        return _Linear(2)

    def configure_optimizers(self):
        return optax.sgd(self.lr)

    def _loss(self, ctx, batch):
        out = ctx.apply(batch)
        return jnp.mean(out ** 2)  # drive outputs toward zero

    def training_step(self, ctx, batch):
        loss = self._loss(ctx, batch)
        ctx.log("loss", loss)
        return loss

    def validation_step(self, ctx, batch):
        ctx.log("val_loss", self._loss(ctx, batch))

    def test_step(self, ctx, batch):
        ctx.log("test_loss", self._loss(ctx, batch))

    def predict_step(self, ctx, batch):
        return ctx.apply(batch)

    def _loader(self, seed=0):
        return DataLoader(RandomDataset(32, self.dataset_length, seed),
                          batch_size=self.batch_size)

    def train_dataloader(self):
        return self._loader(0)

    def val_dataloader(self):
        return self._loader(1)

    def test_dataloader(self):
        return self._loader(2)

    def predict_dataloader(self):
        return self._loader(3)


class _MLP(nn.Module):
    hidden1: int = 128
    hidden2: int = 256
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden1)(x))
        x = nn.relu(nn.Dense(self.hidden2)(x))
        return nn.Dense(self.num_classes)(x)


def synthetic_mnist(n: int, seed: int = 0) -> ArrayDataset:
    """Separable MNIST-shaped data: class-dependent mean patterns.  Keeps
    learning-signal tests hermetic (no downloads in this image) while
    preserving the ≥0.5-accuracy-after-short-training assertion shape
    (tests/utils.py:194-210)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    # class prototypes shared by every split (train/val/test must agree)
    base = np.random.default_rng(42).standard_normal(
        (10, 28 * 28)).astype(np.float32)
    x = base[labels] + 0.3 * rng.standard_normal(
        (n, 28 * 28)).astype(np.float32)
    return ArrayDataset(x.reshape(n, 28, 28).astype(np.float32),
                        labels.astype(np.int32))


class LightningMNISTClassifier(LightningModule):
    """3-layer MLP classifier (tests/utils.py:99-148 analog)."""

    uses_rng = False    # no dropout: the step skips per-step PRNG work

    def __init__(self, config: Optional[dict] = None, data_dir: str = "",
                 train_size: int = 512, val_size: int = 128):
        super().__init__()
        config = config or {}
        self.save_hyperparameters()
        self.lr = config.get("lr", 1e-2)
        self.batch_size = int(config.get("batch_size", 32))
        self.hidden1 = int(config.get("layer_1", 128))
        self.hidden2 = int(config.get("layer_2", 256))
        self.data_dir = data_dir
        self.train_size = train_size
        self.val_size = val_size

    def configure_model(self):
        return _MLP(self.hidden1, self.hidden2)

    def configure_optimizers(self):
        return optax.adam(self.lr)

    def _logits_loss_acc(self, ctx, batch):
        x, y = batch
        logits = ctx.apply(x)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return logits, loss, acc

    def training_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("ptl/train_loss", loss)
        ctx.log("ptl/train_accuracy", acc)
        return loss

    def validation_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("ptl/val_loss", loss)
        ctx.log("ptl/val_accuracy", acc)
        ctx.log("val_loss", loss)
        ctx.log("val_accuracy", acc)

    def test_step(self, ctx, batch):
        _, loss, acc = self._logits_loss_acc(ctx, batch)
        ctx.log("test_loss", loss)
        ctx.log("test_accuracy", acc)

    def predict_step(self, ctx, batch):
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return jnp.argmax(ctx.apply(x), -1)

    def train_dataloader(self):
        return DataLoader(synthetic_mnist(self.train_size, seed=0),
                          batch_size=self.batch_size, shuffle=True)

    def val_dataloader(self):
        return DataLoader(synthetic_mnist(self.val_size, seed=1),
                          batch_size=self.batch_size)

    def test_dataloader(self):
        return DataLoader(synthetic_mnist(self.val_size, seed=2),
                          batch_size=self.batch_size)

    def predict_dataloader(self):
        return self.test_dataloader()
