from ray_lightning_tpu.models.boring import (
    BoringModel,
    LightningMNISTClassifier,
    RandomDataset,
)

__all__ = [
    "BoringModel",
    "LightningMNISTClassifier",
    "RandomDataset",
]
