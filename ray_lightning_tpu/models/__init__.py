from ray_lightning_tpu.models.boring import (
    BoringModel,
    LightningMNISTClassifier,
    RandomDataset,
)
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, GPTLightningModule
from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT
from ray_lightning_tpu.models.resnet import (
    ResNet,
    ResNetConfig,
    ResNetLightningModule,
)
from ray_lightning_tpu.models.bert import (
    BertClassifier,
    BertConfig,
    BertEncoder,
    BertForMaskedLM,
    BertLightningModule,
    BertMLMModule,
)

__all__ = [
    "BoringModel",
    "LightningMNISTClassifier",
    "RandomDataset",
    "GPT",
    "GPTConfig",
    "GPTLightningModule",
    "PipelinedGPT",
    "ResNet",
    "ResNetConfig",
    "ResNetLightningModule",
    "BertClassifier",
    "BertConfig",
    "BertEncoder",
    "BertLightningModule",
    "BertForMaskedLM",
    "BertMLMModule",
]
