"""Machine-readable plan report.

``PlanReport`` is the planner's single output artifact: every
enumerated combination with its status (``pruned`` / ``rejected`` /
``scored`` / ``compiled`` / ``winner``) and — for pruned/rejected
entries — the NAMED reason, plus the winner and the planning-cost
accounting (seconds, compile-cache misses).  It surfaces in three
places: ``trainer._plan_report`` (the dict form), the bench JSON
``plan`` line (benchmarks/bench_plan.py), and the ``rlt_plan_*``
metrics gauges.  The dict schema is pinned by plan/selfcheck.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

#: top-level keys every ``PlanReport.to_dict()`` carries (schema pinned
#: by plan/selfcheck.py; bench_plan.py and the tests consume these).
#: ``remat`` is the per-policy ladder summary at the winner's other
#: axes (None when the module has no configure_remat() ladder).
REPORT_KEYS = ("winner", "topk", "plan_seconds", "cache_misses",
               "reused", "enumerated", "pruned", "rejected", "scored",
               "compiled", "candidates", "remat", "observed")

#: keys every per-candidate entry carries
ENTRY_KEYS = ("label", "strategy", "mesh", "comm", "donate",
              "microbatch", "remat", "status", "reason")

STATUSES = ("pruned", "rejected", "scored", "compiled", "winner")


@dataclasses.dataclass
class PlanReport:
    """The planner's verdict (plan/planner.py builds it)."""

    entries: list                      # per-candidate dicts (ENTRY_KEYS
    #                                    + optional modeled/measured)
    winner_label: Optional[str]
    topk: int
    plan_seconds: float = 0.0
    cache_misses: int = 0
    reused: bool = False
    #: the winning Candidate / CommPolicy objects (not serialized —
    #: the trainer applies them; the dict form carries the label)
    winner_candidate: object = None
    winner_policy: object = None

    def _count(self, status: str) -> int:
        return sum(1 for e in self.entries if e["status"] == status)

    def _remat_summary(self) -> "Optional[dict]":
        """Per-policy ladder at the winner's OTHER axes: the one-look
        answer to "what did each remat policy model to" — modeled HBM
        peak / activation bytes / remat seconds per policy, with the
        winner's policy named.  ``None`` when the module declared no
        remat ladder (no candidate carries a policy)."""
        win = next((e for e in self.entries if e["status"] == "winner"),
                   None)
        if win is None or not win.get("remat"):
            return None

        def axes(e):
            return (e.get("strategy"), str(e.get("mesh")), e.get("comm"),
                    e.get("donate"), e.get("microbatch"))

        policies = {}
        for e in self.entries:
            if not e.get("remat") or axes(e) != axes(win):
                continue
            m = e.get("modeled") or {}
            policies[e["remat"]] = {
                "status": e["status"],
                "peak_bytes": m.get("peak_bytes"),
                "act_bytes": m.get("act_bytes"),
                "remat_seconds": m.get("remat_seconds"),
                "reason": e.get("reason"),
            }
        return {"winner": win["remat"], "policies": policies}

    def to_dict(self) -> dict:
        compiled = sum(1 for e in self.entries
                       if e["status"] in ("compiled", "winner")
                       and e.get("measured") is not None)
        return {
            "winner": self.winner_label,
            "topk": self.topk,
            "plan_seconds": round(self.plan_seconds, 6),
            "cache_misses": self.cache_misses,
            "reused": self.reused,
            "enumerated": len(self.entries),
            "pruned": self._count("pruned"),
            "rejected": self._count("rejected"),
            "scored": sum(1 for e in self.entries
                          if e["status"] != "pruned"),
            "compiled": compiled,
            "candidates": list(self.entries),
            "remat": self._remat_summary(),
            # measured-vs-modeled divergence for the WINNER, attached
            # after the run by Trainer._attach_observed_divergence()
            # when anatomy windows landed: {step_wall_s, exposed_comm_s,
            # modeled_comm_s, ratio}.  None until a run measures it.
            "observed": None,
        }

    def summary(self) -> str:
        d = self.to_dict()
        return (f"winner={d['winner']} from {d['enumerated']} candidates "
                f"({d['pruned']} pruned, {d['rejected']} rejected, "
                f"{d['compiled']} AOT-compiled/top-{d['topk']}) in "
                f"{d['plan_seconds']:.2f}s"
                + (" [reused]" if d["reused"] else ""))


def make_entry(candidate, status: str, reason: Optional[str] = None,
               modeled: Optional[dict] = None,
               measured: Optional[dict] = None) -> dict:
    """One report row (candidate may be a Candidate or a bare label for
    pruned subtrees that never became full candidates)."""
    if hasattr(candidate, "to_dict"):
        entry = candidate.to_dict()
    else:
        entry = {"label": str(candidate), "strategy": None, "mesh": None,
                 "comm": None, "donate": None, "microbatch": None,
                 "remat": None}
    entry["status"] = status
    entry["reason"] = reason
    entry["modeled"] = modeled
    entry["measured"] = measured
    return entry
