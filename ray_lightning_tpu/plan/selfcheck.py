"""Planner-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the comm/compile/serve/elastic selfchecks: cheap,
deterministic, no pytest — validates the invariants that would
otherwise only fail deep inside a planning run:

1. ``PlanConfig`` validation + ``RLT_PLAN*`` env round-trip
   (``worker_env`` → ``resolve`` reproduces the config);
2. enumeration sanity: the canonical inventory appears, spmd mesh
   factorizations are exact divisors, statically-infeasible combos are
   pruned with named reasons, labels are unique;
3. remat axis: option resolution (no-ladder collapse, unknown-policy
   prunes, ``RLT_REMAT_POLICY`` pin), enumeration multiplication with
   unique labels, and ``remat_terms`` score monotonicity;
4. score monotonicity: ``bytes_to_seconds`` is strictly monotone in
   bytes and inversely so in bandwidth (the ranking invariant);
4. report schema: ``PlanReport.to_dict()`` carries every pinned key
   and candidate entries carry the entry schema;
5. every ``rlt_plan_*`` metric name is Prometheus-clean (the PR 2
   lint).
"""

from __future__ import annotations


def _check_config() -> None:
    import os
    from ray_lightning_tpu.plan.config import PlanConfig

    cfg = PlanConfig(topk=2, ici_gbps=42.0, dcn_gbps=3.5,
                     strategies=("ddp", "zero1"), microbatch=(1, 4),
                     remat=("dots", "off"), hbm_gbps=500.0,
                     device_tflops=90.0,
                     hbm_budget_bytes=1 << 30, headroom=0.8)
    saved = {k: os.environ.get(k) for k in list(os.environ)
             if k.startswith("RLT_PLAN")}
    try:
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(cfg.worker_env())
        got = PlanConfig.resolve(None)
        assert got == cfg, f"env round-trip drifted: {got} != {cfg}"
    finally:
        for k in list(os.environ):
            if k.startswith("RLT_PLAN"):
                os.environ.pop(k, None)
        os.environ.update({k: v for k, v in saved.items() if v is not None})
    assert PlanConfig.resolve(None) == PlanConfig()
    for bad in (dict(topk=-1), dict(ici_gbps=0), dict(headroom=0),
                dict(headroom=1.5), dict(strategies=("warp",)),
                dict(microbatch=(0,)), dict(max_candidates=0),
                dict(hbm_gbps=0), dict(device_tflops=-1),
                dict(remat=("",))):
        try:
            PlanConfig(**bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"expected ValueError for {bad}")
    print("plan selfcheck: config validation + env round-trip OK")


def _check_enumeration() -> None:
    from ray_lightning_tpu.plan.candidates import enumerate_candidates
    from ray_lightning_tpu.plan.config import PlanConfig

    cfg = PlanConfig(microbatch=(1, 2))
    cands, pruned = enumerate_candidates(8, 16, cfg, process_count=2)
    labels = [c.label for c in cands]
    assert len(set(labels)) == len(labels), "duplicate candidate labels"
    strategies = {c.strategy for c in cands}
    assert strategies == {"ddp", "zero1", "fsdp", "spmd"}, strategies
    spmd_meshes = {c.mesh_sizes["fsdp"] for c in cands
                   if c.strategy == "spmd"}
    assert spmd_meshes == {2, 4, 8}, spmd_meshes
    assert any(c.comm for c in cands if c.strategy == "ddp")
    assert not any(c.comm for c in cands if c.strategy == "fsdp")
    reasons = {r for _, r in pruned}
    assert any(r.startswith("comm_unsupported") for r in reasons), reasons
    # microbatch 2 over 8 shards needs batch 16 to split 16/(8*2)=1: ok;
    # a batch of 12 cannot divide across 8 shards at all
    cands12, pruned12 = enumerate_candidates(8, 12, cfg, process_count=2)
    assert any(r.startswith("batch_indivisible")
               for _, r in pruned12), pruned12
    # single-process: comm pruned with the no-DCN reason
    _, pruned1p = enumerate_candidates(8, 16, cfg, process_count=1)
    assert any(r.startswith("comm_no_dcn") for _, r in pruned1p)
    print("plan selfcheck: enumeration coverage + pruning reasons OK")


def _check_remat_axis() -> None:
    """Remat-axis invariants: option resolution (no-ladder collapse +
    named prunes, unknown-policy prunes, env pin), enumeration
    multiplication with unique labels, and remat_terms score
    monotonicity (more saved bytes → more peak + traffic seconds, more
    recompute FLOPs → more seconds, "off" pays no region overhead,
    microbatching divides residency but not traffic)."""
    import os

    from ray_lightning_tpu.core.remat import RematProbe, RematSpec
    from ray_lightning_tpu.plan.candidates import (enumerate_candidates,
                                                   resolve_remat_options)
    from ray_lightning_tpu.plan.config import PlanConfig
    from ray_lightning_tpu.plan.cost import remat_terms

    spec = RematSpec(policies=("off", "dots", "full"), default="off",
                     apply=lambda p: None,
                     probe=lambda p, b: None)
    cfg = PlanConfig()
    opts, pruned = resolve_remat_options(spec, cfg)
    assert opts == ("off", "dots", "full") and not pruned, (opts, pruned)
    opts, pruned = resolve_remat_options(
        spec, PlanConfig(remat=("dots", "warp")))
    assert opts == ("dots",), opts
    assert any(r.startswith("remat_unsupported") for _, r in pruned)
    opts, pruned = resolve_remat_options(None, PlanConfig(remat=("dots",)))
    assert opts == ("",), opts
    assert any(r.startswith("remat_unsupported") for _, r in pruned)
    saved = os.environ.get("RLT_REMAT_POLICY")
    try:
        os.environ["RLT_REMAT_POLICY"] = "full"
        opts, _ = resolve_remat_options(spec, cfg)
        assert opts == ("full",), opts
    finally:
        if saved is None:
            os.environ.pop("RLT_REMAT_POLICY", None)
        else:
            os.environ["RLT_REMAT_POLICY"] = saved

    flat, _ = enumerate_candidates(8, 16, cfg)
    swept, _ = enumerate_candidates(8, 16, cfg,
                                    remat_options=("off", "dots"))
    assert len(swept) == 2 * len(flat), (len(swept), len(flat))
    labels = [c.label for c in swept]
    assert len(set(labels)) == len(labels), "duplicate remat labels"
    assert any(lb.endswith("rm-dots") for lb in labels)

    def terms(saved_b=1 << 24, flops=1 << 30, policy="dots", mb=1):
        return remat_terms(RematProbe(saved_bytes=saved_b,
                                      recompute_flops=flops,
                                      n_blocks=4, batch=8),
                           policy, cfg, process_count=1, dp=1,
                           microbatch=mb)

    act1, sec1 = terms()
    act2, sec2 = terms(saved_b=2 << 24)
    assert act2 > act1 and sec2 > sec1, "saved bytes must raise both"
    _, sec3 = terms(flops=2 << 30)
    assert sec3 > sec1, "recompute flops must raise seconds"
    _, sec_off = terms(policy="off", flops=0)
    _, sec_dots = terms(flops=0)
    assert sec_dots > sec_off, "'off' must skip the region overhead"
    act_mb, sec_mb = terms(mb=4)
    assert act_mb < act1, "microbatching must divide residency"
    assert sec_mb > sec1, "microbatching must not divide traffic"
    print("plan selfcheck: remat axis enumeration + score monotonicity OK")


def _check_monotonicity() -> None:
    from ray_lightning_tpu.comm.audit import bytes_to_seconds

    prev = -1.0
    for nbytes in (0, 1, 1024, 1 << 20, 1 << 30, 1 << 40):
        s = bytes_to_seconds(nbytes, 12.5)
        assert s > prev or nbytes == 0, (nbytes, s, prev)
        prev = s
    assert bytes_to_seconds(1 << 30, 100.0) \
        < bytes_to_seconds(1 << 30, 12.5), "faster link must score lower"
    assert bytes_to_seconds({"a": 512, "b": 512}, 1.0) \
        == bytes_to_seconds(1024, 1.0), "dict form must sum"
    print("plan selfcheck: byte→seconds monotone in bytes and bandwidth")


def _check_report_schema() -> None:
    from ray_lightning_tpu.plan.candidates import Candidate
    from ray_lightning_tpu.plan.report import (ENTRY_KEYS, REPORT_KEYS,
                                               PlanReport, make_entry)

    cand = Candidate(strategy="ddp", axis_sizes=(("data", 8),))
    entries = [
        make_entry("zz:pruned", "pruned", "batch_indivisible: …"),
        make_entry(cand, "rejected", "hbm_over_budget: …"),
        make_entry(cand, "winner", modeled={"comm_seconds": 0.0},
                   measured={"compile_seconds": 0.1}),
    ]
    d = PlanReport(entries=entries, winner_label=cand.label,
                   topk=3, plan_seconds=0.5, cache_misses=1).to_dict()
    for k in REPORT_KEYS:
        assert k in d, f"report missing {k!r}"
    for e in d["candidates"]:
        for k in ENTRY_KEYS:
            assert k in e, f"entry missing {k!r}: {e}"
    assert d["enumerated"] == 3 and d["pruned"] == 1 \
        and d["rejected"] == 1 and d["compiled"] == 1
    assert d["winner"] == cand.label
    assert d["observed"] is None, \
        "observed divergence must be None until a run measures it"
    print("plan selfcheck: report schema pinned")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import validate_metric_name
    for name in ("rlt_plan_candidates_total", "rlt_plan_pruned_total",
                 "rlt_plan_rejected_total", "rlt_plan_compiled_total",
                 "rlt_plan_seconds"):
        validate_metric_name(name)
    print("plan selfcheck: metric names Prometheus-clean")


def _main(argv: list) -> int:
    _check_config()
    _check_enumeration()
    _check_remat_axis()
    _check_monotonicity()
    _check_report_schema()
    _check_metric_names()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
