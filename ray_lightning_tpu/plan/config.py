"""Planner configuration (``Trainer(strategy="auto")`` knobs).

``PlanConfig`` is the frozen, picklable settings object of the planner
plane, following the ``CommPolicy`` / ``ElasticConfig`` construction
pattern (first match wins):

- ``Trainer(plan=PlanConfig(...))`` — full control;
- ``Trainer(plan={...})`` — kwargs dict;
- ``AutoStrategy(plan=...)`` — per-strategy override;
- ``RLT_PLAN_TOPK`` / ``RLT_PLAN_ICI_GBPS`` / ``RLT_PLAN_DCN_GBPS`` /
  ``RLT_PLAN_STRATEGIES`` / ``RLT_PLAN_MICROBATCH`` /
  ``RLT_PLAN_REMAT`` / ``RLT_PLAN_HBM_GBPS`` / ``RLT_PLAN_TFLOPS`` /
  ``RLT_PLAN_HBM_BYTES`` / ``RLT_PLAN_HEADROOM`` — env knobs, read when
  the Trainer arg is ``None``.
- ``RLT_PLAN_CALIBRATE=1`` — replace the bandwidth constants with
  MEASURED link speeds (comm/calibrate.py: a tiny collective
  microbench, run once and cached per topology fingerprint).
  ``RLT_PLAN_CALIBRATE=live`` (or ``anatomy``) goes further: the last
  instrumented run's anatomy-measured exposed-comm vs modeled-comm
  ratio scales the constants (comm/calibrate.py live_calibration),
  falling back to the microbench when no live sample exists yet.
  Explicit ``RLT_PLAN_{ICI,DCN}_GBPS`` values still win.

The resolved config pickles driver→worker on the Trainer and
round-trips through ``worker_env()`` like the comm/compile/elastic
knobs do, so every rank of a fleet plans from identical inputs — the
planner's ranking keys are deterministic by construction (see
plan/planner.py) and identical config is what keeps an SPMD fleet
agreeing on one winner without a collective.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from ray_lightning_tpu.comm.audit import DCN_GBPS, ICI_GBPS

#: strategy names the planner may enumerate (canonical spellings only —
#: aliases like "dp"/"sharded" resolve to the same classes)
PLANNABLE_STRATEGIES = ("ddp", "zero1", "fsdp", "spmd")

ENV_TOPK = "RLT_PLAN_TOPK"
ENV_ICI = "RLT_PLAN_ICI_GBPS"
ENV_DCN = "RLT_PLAN_DCN_GBPS"
ENV_STRATEGIES = "RLT_PLAN_STRATEGIES"
ENV_MICROBATCH = "RLT_PLAN_MICROBATCH"
ENV_HBM = "RLT_PLAN_HBM_BYTES"
ENV_HEADROOM = "RLT_PLAN_HEADROOM"
ENV_CALIBRATE = "RLT_PLAN_CALIBRATE"
ENV_REMAT = "RLT_PLAN_REMAT"
ENV_HBM_GBPS = "RLT_PLAN_HBM_GBPS"
ENV_TFLOPS = "RLT_PLAN_TFLOPS"
ENV_KNOBS = (ENV_TOPK, ENV_ICI, ENV_DCN, ENV_STRATEGIES, ENV_MICROBATCH,
             ENV_HBM, ENV_HEADROOM, ENV_CALIBRATE, ENV_REMAT,
             ENV_HBM_GBPS, ENV_TFLOPS)

#: modeled HBM bandwidth the remat cost term charges saved-activation
#: round-trips at (v5e-class default, same convention as the comm-plane
#: link constants); override per device generation
HBM_GBPS = 819.0
#: modeled ACHIEVED matmul rate for recompute chains — deliberately
#: below a v5e's ~197 bf16 peak TFLOPs because remat'd forward
#:re-execution runs inside backward fusions at well under peak MFU
#: (calibrated against the measured gpt2-medium full-vs-dots walk,
#: benchmarks/README.md round 4)
DEVICE_TFLOPS = 65.0


@dataclasses.dataclass(frozen=True)
class PlanConfig:
    """What the planner enumerates and how it scores.

    topk: how many model-ranked survivors get AOT-compiled for the
        verify stage (0 skips verification: pick on the byte model
        alone).  The compile-cache miss counters bound the real compile
        work at ``topk`` — the acceptance invariant tests/test_plan.py
        pins.
    ici_gbps / dcn_gbps: modeled per-link payload bandwidths for the
        byte→seconds conversion (comm/audit.py constants by default;
        override per fabric generation).
    strategies: candidate strategy inventory (subset of
        :data:`PLANNABLE_STRATEGIES`).
    microbatch: candidate ``accumulate_grad_batches`` values.  ``(1,)``
        by default — microbatching only trades step time for memory, so
        it is an opt-in dimension.
    remat: candidate remat-policy names.  ``()`` (the default) sweeps
        every policy the module's ``configure_remat()`` ladder
        declares; a non-empty tuple restricts the sweep (unsupported
        names are pruned as ``remat_unsupported``).  An
        ``RLT_REMAT_POLICY`` env override pins the axis to that single
        policy (plan/candidates.py ``resolve_remat_options``) — the
        sweep would compile programs the env forces to one policy
        anyway.
    hbm_gbps: modeled HBM bandwidth for the remat activation-traffic
        term (saved activations cost one store + one load per step).
    device_tflops: modeled achieved matmul rate for the remat
        recompute-FLOPs term (below peak — see DEVICE_TFLOPS note).
    hbm_budget_bytes: per-device memory budget override (None = ask the
        device, like the donation heuristic does).
    headroom: fraction of the budget modeled residents may use (the
        rest absorbs XLA workspace/fragmentation — same 0.9 convention
        as tests/test_memory_fit.py).
    activation_factor: crude activations-per-batch-byte multiplier for
        the no-compile peak estimate; the AOT verify stage replaces it
        with the compiled program's real ``memory_analysis`` bytes.
    max_candidates: hard cap on scored candidates; overflow is recorded
        in the report (never silently dropped).
    reuse: allow per-trial plan reuse inside a tune experiment (the
        memoized report short-circuits re-planning for same-shaped
        trials; the shared compile cache already makes their verify
        compiles warm).
    """

    topk: int = 3
    ici_gbps: float = ICI_GBPS
    dcn_gbps: float = DCN_GBPS
    strategies: tuple = PLANNABLE_STRATEGIES
    microbatch: tuple = (1,)
    remat: tuple = ()
    hbm_gbps: float = HBM_GBPS
    device_tflops: float = DEVICE_TFLOPS
    hbm_budget_bytes: Optional[int] = None
    headroom: float = 0.9
    activation_factor: float = 8.0
    # the remat axis multiplies the space (a 6-policy MoE ladder over
    # the PR-8 axes lands near 100); the cap exists against runaway
    # enumeration, not to truncate the default sweep
    max_candidates: int = 256
    reuse: bool = True

    def __post_init__(self):
        if self.topk < 0:
            raise ValueError("plan topk must be >= 0")
        if self.ici_gbps <= 0 or self.dcn_gbps <= 0:
            raise ValueError("plan bandwidths must be positive")
        if not (0.0 < self.headroom <= 1.0):
            raise ValueError("plan headroom must be in (0, 1]")
        if self.max_candidates < 1:
            raise ValueError("plan max_candidates must be >= 1")
        object.__setattr__(self, "strategies", tuple(self.strategies))
        unknown = [s for s in self.strategies
                   if s not in PLANNABLE_STRATEGIES]
        if unknown:
            raise ValueError(
                f"unplannable strategies {unknown}; "
                f"options: {PLANNABLE_STRATEGIES}")
        mb = tuple(int(m) for m in self.microbatch)
        if not mb or any(m < 1 for m in mb):
            raise ValueError("plan microbatch values must be >= 1")
        object.__setattr__(self, "microbatch", mb)
        rm = tuple(str(p) for p in self.remat)
        if any(not p for p in rm):
            raise ValueError("plan remat policy names must be non-empty")
        object.__setattr__(self, "remat", rm)
        if self.hbm_gbps <= 0 or self.device_tflops <= 0:
            raise ValueError(
                "plan hbm_gbps / device_tflops must be positive")

    # -- construction ----------------------------------------------------

    @classmethod
    def resolve(cls, value) -> "PlanConfig":
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        if value is not None:
            raise TypeError(f"bad plan config: {value!r}")
        kw = {}
        raw = os.environ.get(ENV_TOPK, "").strip()
        if raw:
            kw["topk"] = int(raw)
        raw_cal = os.environ.get(ENV_CALIBRATE, "").strip().lower()
        if raw_cal in ("1", "true"):
            # measured link bandwidths (cached per topology) replace
            # the constants; explicit RLT_PLAN_*_GBPS still win below
            from ray_lightning_tpu.comm.calibrate import calibrated_gbps
            kw["ici_gbps"], kw["dcn_gbps"] = calibrated_gbps()
        elif raw_cal in ("live", "anatomy"):
            # live anatomy calibration (ROADMAP 5(a) leg): the previous
            # instrumented run's measured-exposed / modeled-comm ratio
            # (comm/calibrate.py save_live_calibration) scales BOTH link
            # constants — modeled comm seconds are linear in 1/gbps, so
            # dividing by comm_scale makes the next plan's model match
            # what the fabric delivered.  No stored sample yet falls
            # back to the microbench path.
            from ray_lightning_tpu.comm import calibrate as _cal
            live = _cal.live_calibration()
            if live is not None:
                scale = float(live["comm_scale"])
                kw["ici_gbps"] = round(_cal.ICI_GBPS / scale, 3)
                kw["dcn_gbps"] = round(_cal.DCN_GBPS / scale, 3)
            else:
                kw["ici_gbps"], kw["dcn_gbps"] = _cal.calibrated_gbps()
        raw = os.environ.get(ENV_ICI, "").strip()
        if raw:
            kw["ici_gbps"] = float(raw)
        raw = os.environ.get(ENV_DCN, "").strip()
        if raw:
            kw["dcn_gbps"] = float(raw)
        raw = os.environ.get(ENV_STRATEGIES, "").strip()
        if raw:
            kw["strategies"] = tuple(s for s in raw.split(",") if s)
        raw = os.environ.get(ENV_MICROBATCH, "").strip()
        if raw:
            kw["microbatch"] = tuple(int(m) for m in raw.split(",") if m)
        raw = os.environ.get(ENV_REMAT, "").strip()
        if raw:
            kw["remat"] = tuple(p for p in raw.split(",") if p)
        raw = os.environ.get(ENV_HBM_GBPS, "").strip()
        if raw:
            kw["hbm_gbps"] = float(raw)
        raw = os.environ.get(ENV_TFLOPS, "").strip()
        if raw:
            kw["device_tflops"] = float(raw)
        raw = os.environ.get(ENV_HBM, "").strip()
        if raw:
            kw["hbm_budget_bytes"] = int(raw)
        raw = os.environ.get(ENV_HEADROOM, "").strip()
        if raw:
            kw["headroom"] = float(raw)
        return cls(**kw)

    # -- env round-trip --------------------------------------------------

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process (only non-default fields are emitted — a default
        config leaves the worker env untouched)."""
        default = PlanConfig()
        env = {}
        if self.topk != default.topk:
            env[ENV_TOPK] = str(self.topk)
        if self.ici_gbps != default.ici_gbps:
            env[ENV_ICI] = repr(self.ici_gbps)
        if self.dcn_gbps != default.dcn_gbps:
            env[ENV_DCN] = repr(self.dcn_gbps)
        if self.strategies != default.strategies:
            env[ENV_STRATEGIES] = ",".join(self.strategies)
        if self.microbatch != default.microbatch:
            env[ENV_MICROBATCH] = ",".join(str(m) for m in self.microbatch)
        if self.remat != default.remat:
            env[ENV_REMAT] = ",".join(self.remat)
        if self.hbm_gbps != default.hbm_gbps:
            env[ENV_HBM_GBPS] = repr(self.hbm_gbps)
        if self.device_tflops != default.device_tflops:
            env[ENV_TFLOPS] = repr(self.device_tflops)
        if self.hbm_budget_bytes is not None:
            env[ENV_HBM] = str(self.hbm_budget_bytes)
        if self.headroom != default.headroom:
            env[ENV_HEADROOM] = repr(self.headroom)
        return env
