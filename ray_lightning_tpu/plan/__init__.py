"""Planner plane: cost-model-driven auto-parallelism.

``Trainer(strategy="auto")`` routes here: enumerate candidate plans
(strategy × mesh × comm × donation × microbatch × remat policy), score
them from the byte/HBM models WITHOUT compiling, AOT-verify the top-k
through the
persistent compile cache, and pick deterministically — emitting a
machine-readable :class:`PlanReport` on ``trainer._plan_report``, in
bench JSON, and as ``rlt_plan_*`` metrics.  See plan/planner.py for
the full pipeline and the cross-rank determinism contract.
"""

from ray_lightning_tpu.plan.candidates import (Candidate,
                                               enumerate_candidates,
                                               policy_for_candidate,
                                               resolve_remat_options)
from ray_lightning_tpu.plan.config import ENV_KNOBS, PlanConfig
from ray_lightning_tpu.plan.cost import (Estimate, estimate_candidate,
                                         rank_key, remat_terms,
                                         sharded_bytes)
from ray_lightning_tpu.plan.planner import Planner, clear_plan_memo
from ray_lightning_tpu.plan.report import (ENTRY_KEYS, REPORT_KEYS,
                                           PlanReport, make_entry)

__all__ = [
    "Candidate",
    "ENTRY_KEYS",
    "ENV_KNOBS",
    "Estimate",
    "Planner",
    "PlanConfig",
    "PlanReport",
    "REPORT_KEYS",
    "clear_plan_memo",
    "enumerate_candidates",
    "estimate_candidate",
    "make_entry",
    "policy_for_candidate",
    "rank_key",
    "remat_terms",
    "resolve_remat_options",
    "sharded_bytes",
]
