"""Compile-free candidate scoring: bytes → seconds, avals → HBM fit.

Two estimates per candidate, both computed WITHOUT compiling anything:

- **communication seconds**: the strategy's own per-step traffic
  declaration (``step_collective_bytes`` — the same numbers the metrics
  plane charges, pinned against audited HLO wire bytes by
  tests/test_plan.py's drift guard) converted through the per-link
  bandwidth model (comm/audit.py ``bytes_to_seconds``): each op is
  scored at ITS link's bandwidth — ``_ici``-suffixed ops (the fp32
  intra-host phases of a hierarchical sync) always ride ICI, everything
  else rides DCN when the run spans processes (the mesh construction
  puts the data axis across hosts) and ICI otherwise.  Without the
  split, a hierarchical candidate's 8-bytes/element ICI phases would be
  charged at DCN speed and the planner would mis-rank it below the flat
  codec it strictly beats on the slow link.
- **HBM peak**: the sharded TrainState residency from ``eval_shape``
  avals + the strategy's shardings (exact per-leaf shard bytes, the
  tests/test_memory_fit.py account), plus the big transients (grads at
  param dtype and fp32 update deltas, mirroring the PARAM sharding —
  replicated-param strategies materialize them full-size, param-sharded
  ones keep them shard-sized) and an activation term: when the module
  declares a ``configure_remat()`` ladder, the candidate policy's
  SAVED-ACTIVATION bytes (core/remat.py probe — eval_shape of each
  block's saveable residual set, scaled to the candidate's per-device
  microbatch and damped by :data:`REMAT_RESIDENCY_FACTOR` for XLA's
  buffer sharing); otherwise the crude batch-proportional proxy of
  PR 8.  Donation follows the measured decision logic: an un-donated
  step carries a second state copy (old + new — the
  ``Trainer._donation_cutoff`` story).
- **remat seconds** (:func:`remat_terms`): what the candidate's remat
  policy costs per step — saved activations pay one HBM store + one
  load (``2·bytes / hbm_gbps``), recomputed matmuls pay
  ``flops / device_tflops`` at the deliberately-sub-peak achieved
  rate, and every remat region pays a small fixed scheduling overhead
  per microbatch (:data:`REMAT_BLOCK_OVERHEAD_S`) — the term that
  makes "off" win on small models where recompute latency, not bytes,
  dominates.  This is the score that trades memory against recompute
  against comm: it adds to the comm seconds in :func:`rank_key`.

Candidates whose modeled peak exceeds the headroom-scaled budget are
rejected with a named reason; the AOT verify stage later replaces these
estimates with the compiled program's real ``memory_analysis`` bytes
and audited wire bytes for the top-k survivors.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np

from ray_lightning_tpu.comm.audit import bytes_to_seconds
from ray_lightning_tpu.plan.candidates import Candidate
from ray_lightning_tpu.plan.config import PlanConfig


def sharded_bytes(abstract_tree, shardings_tree) -> int:
    """Per-device bytes of ``abstract_tree`` under the given shardings
    (exact: per-leaf ``shard_shape``)."""
    leaves = jax.tree_util.tree_leaves(abstract_tree)
    shs = jax.tree_util.tree_leaves(
        shardings_tree, is_leaf=lambda x: hasattr(x, "spec"))
    total = 0
    for aval, sh in zip(leaves, shs):
        shape = sh.shard_shape(aval.shape) \
            if hasattr(sh, "shard_shape") else aval.shape
        total += int(np.prod(shape, dtype=np.int64)) * aval.dtype.itemsize
    return total


def _sharded_elements(abstract_tree, shardings_tree) -> int:
    leaves = jax.tree_util.tree_leaves(abstract_tree)
    shs = jax.tree_util.tree_leaves(
        shardings_tree, is_leaf=lambda x: hasattr(x, "spec"))
    total = 0
    for aval, sh in zip(leaves, shs):
        shape = sh.shard_shape(aval.shape) \
            if hasattr(sh, "shard_shape") else aval.shape
        total += int(np.prod(shape, dtype=np.int64))
    return total


#: fraction of a policy's RAW saved-residual bytes modeled as live HBM
#: (and round-tripped): ``saved_residuals`` lists every residual at its
#: own dtype while XLA's buffer assignment shares/dedups aggressively —
#: calibrated against compiled ``memory_analysis`` temp deltas of the
#: tiny-GPT programs (tests/test_plan.py remat drift leg) and the
#: measured gpt2-medium walk (off 18.95 GB vs dots ~10 GB,
#: benchmarks/README.md round 4)
REMAT_RESIDENCY_FACTOR = 0.3

#: modeled fixed cost of one remat region's backward re-entry (extra
#: kernel launches + the fusion break at the region boundary) per
#: microbatch — the term that keeps "off" the winner on tiny models
#: where the saved bytes are microseconds of traffic
REMAT_BLOCK_OVERHEAD_S = 5e-6


def remat_terms(probe, policy: str, config: PlanConfig,
                process_count: int, dp: int,
                microbatch: int) -> "tuple[int, float]":
    """(peak activation bytes, remat seconds) for one candidate.

    ``probe`` is the module's :class:`~ray_lightning_tpu.core.remat.
    RematProbe` at the process-LOCAL example batch; every probe
    quantity is linear in batch, so the per-device step scale is
    ``process_count / dp`` (global batch = local × processes, split
    over dp data shards).  Peak residency divides by the microbatch
    count (only one microbatch's activations are live); traffic and
    recompute do not (every microbatch pays them each step).
    """
    scale = process_count / max(1, dp)
    saved = probe.saved_bytes * REMAT_RESIDENCY_FACTOR * scale
    act_bytes = int(saved / max(1, microbatch))
    seconds = bytes_to_seconds(2 * saved, config.hbm_gbps)
    seconds += (probe.recompute_flops * scale
                / (config.device_tflops * 1e12))
    if policy != "off":
        seconds += probe.n_blocks * microbatch * REMAT_BLOCK_OVERHEAD_S
    return act_bytes, seconds


def link_gbps(op: str, config: PlanConfig, process_count: int) -> float:
    """The modeled bandwidth ONE declared collective op rides (module
    docstring): ``_ici``-suffixed ops always score at ICI speed; every
    other op crosses DCN exactly when the run spans processes."""
    if op.endswith("_ici"):
        return config.ici_gbps
    return config.dcn_gbps if process_count > 1 else config.ici_gbps


#: modeled fraction of a ``_bucketed`` collective's time that stays
#: EXPOSED after XLA's latency-hiding scheduler overlaps it with
#: adjacent compute.  Deliberately conservative (half hidden): the
#: planner must not promise overlap the fabric can't deliver; the
#: measured judge is bench_comm's anatomy exposed-comm A/B, and the
#: declared bytes stay the full payload (only seconds are discounted —
#: bucketing moves WHEN bytes travel, never how many).
BUCKETED_EXPOSED_FRACTION = 0.5


def op_overlap_factor(op: str) -> float:
    """Multiplier on one declared op's modeled seconds: ``_bucketed``
    ops (the latency-hidden ZeRO-1 param gather,
    comm/collectives.py ``regather_params``) count only their modeled
    exposed fraction; every other op is fully exposed."""
    return BUCKETED_EXPOSED_FRACTION if op.endswith("_bucketed") else 1.0


def device_memory_budget(device, config: PlanConfig) -> Optional[int]:
    """Per-device HBM budget: the config override, the runtime's
    reported limit, or the known-HBM-by-kind table the donation
    heuristic uses (core/trainer.py) — ``None`` when nothing knows
    (virtual CPU meshes), in which case memory never rejects."""
    if config.hbm_budget_bytes is not None:
        return int(config.hbm_budget_bytes)
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    if getattr(device, "platform", None) == "tpu":
        from ray_lightning_tpu.core.trainer import Trainer
        return Trainer._HBM_BY_KIND.get(getattr(device, "device_kind", ""))
    return None


@dataclasses.dataclass
class Estimate:
    """One candidate's compile-free score."""

    comm_bytes: int
    comm_seconds: float
    state_bytes: int           # sharded TrainState residency per device
    peak_bytes: int            # state + transients (+ un-donated copy)
    budget: Optional[int]
    donate_preferred: bool     # what the measured donation heuristic
    #                            would pick for this state/budget pair
    reason: Optional[str] = None   # rejection reason (None = fits)
    remat_policy: str = ""     # candidate's policy ("" = no remat axis)
    act_bytes: int = 0         # modeled live activations (remat-aware
    #                            when the module declares a ladder)
    remat_seconds: float = 0.0  # traffic + recompute + region overhead

    @property
    def fits(self) -> bool:
        return self.reason is None

    @property
    def step_seconds(self) -> float:
        """The modeled per-step cost that ranks: comm + remat."""
        return self.comm_seconds + self.remat_seconds

    def to_dict(self) -> dict:
        return {
            "comm_bytes": int(self.comm_bytes),
            "comm_seconds": float(self.comm_seconds),
            "state_bytes": int(self.state_bytes),
            "peak_bytes": int(self.peak_bytes),
            "budget_bytes": self.budget,
            "donate_preferred": self.donate_preferred,
            "remat_policy": self.remat_policy or None,
            "act_bytes": int(self.act_bytes),
            "remat_seconds": float(self.remat_seconds),
        }


def estimate_candidate(
    candidate: Candidate,
    strategy,
    mesh,
    abstract_state,
    shardings,
    batch_bytes_global: int,
    config: PlanConfig,
    process_count: int,
    grad_sync=None,
    remat_probe=None,
) -> Estimate:
    """Score one candidate from avals alone (module docstring).

    ``remat_probe`` is the module's priced :class:`RematProbe` for THIS
    candidate's policy (None when the module has no remat ladder — the
    activation term then falls back to the PR-8 batch proxy)."""
    from ray_lightning_tpu.core.trainer import Trainer

    op_bytes = strategy.step_collective_bytes(mesh, abstract_state,
                                              comm=grad_sync)
    comm_bytes = int(sum(op_bytes.values()))
    comm_seconds = sum(
        bytes_to_seconds(b, link_gbps(op, config, process_count))
        * op_overlap_factor(op)
        for op, b in op_bytes.items())

    state_bytes = sharded_bytes(abstract_state, shardings)
    # grads mirror the param sharding at param dtype; fp32 update deltas
    # likewise (replicated-param strategies materialize both full-size —
    # the audited f32 all-gather of updates, tests/test_memory_fit.py)
    grads_bytes = sharded_bytes(abstract_state.params, shardings.params)
    updates_bytes = 4 * _sharded_elements(abstract_state.params,
                                          shardings.params)
    dp = max(1, strategy.data_parallel_size(mesh))
    remat_seconds = 0.0
    if remat_probe is not None:
        act_bytes, remat_seconds = remat_terms(
            remat_probe, candidate.remat, config, process_count, dp,
            max(1, candidate.microbatch))
    else:
        act_bytes = int(batch_bytes_global / dp * config.activation_factor
                        / max(1, candidate.microbatch))
    peak = (state_bytes * (1 if candidate.donate else 2)
            + grads_bytes + updates_bytes + act_bytes)

    budget = device_memory_budget(mesh.devices.flat[0], config)
    donate_preferred = True if budget is None \
        else Trainer._donation_cutoff(state_bytes, budget)
    reason = None
    if budget is not None and peak > config.headroom * budget:
        reason = (f"hbm_over_budget: modeled peak {peak >> 20} MiB "
                  f"({'donated' if candidate.donate else 'un-donated'}) "
                  f"> {int(config.headroom * budget) >> 20} MiB "
                  f"({config.headroom:.0%} of {budget >> 20} MiB/device)")
    return Estimate(comm_bytes=comm_bytes, comm_seconds=comm_seconds,
                    state_bytes=state_bytes, peak_bytes=peak,
                    budget=budget, donate_preferred=donate_preferred,
                    reason=reason, remat_policy=candidate.remat,
                    act_bytes=act_bytes, remat_seconds=remat_seconds)


def rank_key(candidate: Candidate, est: Estimate) -> tuple:
    """Deterministic ranking key for modeled scores: fewest modeled
    per-step seconds first (comm + remat — the remat term is what lets
    recompute-vs-HBM trade against wire bytes in one total order);
    between otherwise-equal candidates the donation flag agreeing with
    the MEASURED donation heuristic wins (small states run faster
    un-donated, large/unknown donate — ``Trainer._donation_cutoff``);
    then lower peak, then label (total order — every rank of an SPMD
    fleet computes the same key from the same pickled config, which is
    what lets ``strategy="auto"`` agree on one winner without a
    collective)."""
    mismatch = 0 if candidate.donate == est.donate_preferred else 1
    return (est.step_seconds, mismatch, est.peak_bytes, candidate.label)


def expected_accepted(acceptance: float, k: int) -> float:
    """Expected draft tokens accepted per spec-decode round at
    per-token acceptance probability ``acceptance`` and depth ``k``:
    the mean of the truncated geometric run-length,
    ``sum_{m=1..k} a^m = a(1 - a^k)/(1 - a)``.  The verify's corrected
    token rides on top, so tokens-per-target-forward is
    ``1 + expected_accepted`` — the serve plane's measured
    ``tokens_per_target_forward`` converges to this (scheduler spec
    block; serve/selfcheck.py pins the shape)."""
    a = min(1.0, max(0.0, float(acceptance)))
    k = max(1, int(k))
    if a >= 1.0:
        return float(k)
    return a * (1.0 - a ** k) / (1.0 - a)


def speculative_speedup(acceptance: float, k: int,
                        draft_cost_ratio: float) -> float:
    """Modeled wall-clock speedup of speculative decoding over plain
    decode.  One spec round emits ``1 + expected_accepted`` tokens for
    the price of one target forward plus ``k`` draft forwards, each
    ``draft_cost_ratio`` of a target forward (layer-truncated drafts:
    roughly ``draft_layers / n_layer``).  Plain decode pays one target
    forward per token, so::

        speedup = (1 + E[accepted]) / (1 + k * draft_cost_ratio)

    < 1 means speculation LOSES at this operating point (acceptance
    collapsed or the draft is too expensive) — the scheduler's
    ``min_accept`` fallback exists precisely for that regime."""
    r = max(0.0, float(draft_cost_ratio))
    return (1.0 + expected_accepted(acceptance, k)) \
        / (1.0 + max(1, int(k)) * r)
