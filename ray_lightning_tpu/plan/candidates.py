"""Candidate-plan enumeration.

A *candidate* is one complete parallelism configuration the trainer
could run: strategy × mesh factorization × comm policy on/off ×
donation on/off × grad-accumulation microbatch × remat policy (when
the module declares a ``configure_remat()`` ladder —
``resolve_remat_options``).  Enumeration here is
purely combinatorial — strategies self-describe their feasible meshes
via the ``plan_mesh_options`` / ``from_plan`` hooks
(parallel/strategy.py) — and prunes statically-infeasible combinations
up front with a NAMED reason (batch indivisible across the data shards,
comm on a param-sharded strategy, no DCN hop to compress, microbatch
not dividing the per-shard batch).  Budget-dependent rejection needs
avals and happens later, in plan/cost.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_lightning_tpu.plan.config import PlanConfig


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One enumerated plan (hashable; the planner memo keys on it)."""

    strategy: str                 # canonical name ("ddp"/"zero1"/...)
    axis_sizes: tuple             # sorted ((axis, size), ...) pairs
    comm: bool = False            # compressed gradient collectives on?
    donate: bool = True           # donate the TrainState into the step?
    microbatch: int = 1           # accumulate_grad_batches
    remat: str = ""               # remat policy ("" = module default /
    #                               no configure_remat() ladder)

    @property
    def label(self) -> str:
        mesh = "x".join(f"{a}{s}" for a, s in self.axis_sizes)
        parts = [f"{self.strategy}[{mesh}]"]
        if self.comm:
            parts.append("comm")
        if not self.donate:
            parts.append("nodonate")
        if self.microbatch > 1:
            parts.append(f"mb{self.microbatch}")
        if self.remat:
            parts.append(f"rm-{self.remat}")
        return ":".join(parts)

    @property
    def mesh_sizes(self) -> dict:
        return dict(self.axis_sizes)

    def data_parallel_size(self) -> int:
        """Product of the batch-sharding axes (data + fsdp — the axes
        every built-in strategy declares as ``data_axis_names``)."""
        sizes = self.mesh_sizes
        return sizes.get("data", 1) * sizes.get("fsdp", 1)

    def build_strategy(self):
        from ray_lightning_tpu.parallel.strategy import _STRATEGIES
        return _STRATEGIES[self.strategy].from_plan(self.mesh_sizes)

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "strategy": self.strategy,
            "mesh": self.mesh_sizes,
            "comm": self.comm,
            "donate": self.donate,
            "microbatch": self.microbatch,
            "remat": self.remat or None,
        }


def policy_for_candidate(candidate: Candidate, base_policy=None):
    """The :class:`CommPolicy` a comm-on candidate runs under: the
    user's own policy when one is active (the planner then decides
    WHETHER to apply it, not how), else the default aggressive setting
    — int8 on the data axis with the two-level hierarchy armed
    (``HIER_AUTO``: fp32 inside each host's ICI group, codec only
    across DCN — inert when one host holds the whole axis), the
    EQuARX-style DCN compression the comm plane was built for.  The
    hierarchical declaration splits bytes by link tier, which is what
    lets plan/cost.py score these candidates at per-link bandwidths
    instead of mis-charging the fp32 ICI phases at DCN speed.
    ``None`` for comm-off candidates."""
    if not candidate.comm:
        return None
    from ray_lightning_tpu.comm import CommPolicy
    from ray_lightning_tpu.comm.policy import HIER_AUTO
    if base_policy is not None and base_policy.enabled:
        return base_policy
    return CommPolicy(compress="int8", axes=("data",), hierarchy=HIER_AUTO)


def resolve_remat_options(spec, config: PlanConfig
                          ) -> "tuple[tuple, list[tuple[str, str]]]":
    """The remat-policy axis for this module: ``(options, pruned)``.

    ``spec`` is the module's ``configure_remat()`` result (or ``None``).
    No spec → the axis collapses to ``("",)`` (module default), with a
    named ``remat_unsupported`` prune entry when a sweep was explicitly
    requested (``config.remat`` / ``RLT_REMAT_POLICY``).  With a spec,
    ``config.remat`` (default: the module's whole ladder) is validated
    against the ladder — unknown names prune by name — and a set
    ``RLT_REMAT_POLICY`` pins the axis to that single policy, because
    the model-build override would force every candidate's compiled
    program to it anyway (models/gpt.py ``_remat_policy``).
    """
    import os
    pruned: list[tuple[str, str]] = []
    env = os.environ.get("RLT_REMAT_POLICY", "").strip()
    if spec is None:
        if config.remat or env:
            pruned.append((
                "remat",
                "remat_unsupported: module declares no configure_remat() "
                "ladder (core/module.py hook); the remat axis is skipped"))
        return ("",), pruned
    requested = (env,) if env else (tuple(config.remat)
                                    or tuple(spec.policies))
    options: list = []
    for p in requested:
        if p not in spec.policies:
            pruned.append((
                f"rm-{p}",
                f"remat_unsupported: policy {p!r} is not in this "
                f"module's ladder {tuple(spec.policies)}"))
            continue
        if p not in options:
            options.append(p)
    if not options:
        options = [spec.default]
    return tuple(options), pruned


def enumerate_candidates(
    n_devices: int,
    global_batch: Optional[int],
    config: PlanConfig,
    process_count: int = 1,
    microbatch_options: Optional[tuple] = None,
    comm_enabled_hint: bool = False,
    remat_options: tuple = ("",),
) -> "tuple[list[Candidate], list[tuple[str, str]]]":
    """All statically-feasible candidates plus the pruned combinations.

    Returns ``(candidates, pruned)`` where ``pruned`` is a list of
    ``(label, reason)`` — every reason names the violated constraint so
    the PlanReport can answer "why was X not considered".  Pruning
    happens at the outermost level where the constraint binds (one
    entry per pruned subtree, not one per leaf combination).

    ``comm_enabled_hint`` marks a user-supplied active comm policy:
    comm-on candidates are then enumerated even on a single process
    (the explicit-axes opt-in the CPU tests use); without it, a
    single-process run has no DCN hop worth compressing and comm-on is
    pruned.
    """
    from ray_lightning_tpu.parallel.strategy import _STRATEGIES

    microbatch = tuple(microbatch_options or config.microbatch)
    candidates: list[Candidate] = []
    pruned: list[tuple[str, str]] = []

    for name in config.strategies:
        cls = _STRATEGIES[name]
        for sizes in cls.plan_mesh_options(n_devices):
            axis_sizes = tuple(sorted(sizes.items()))
            base = Candidate(strategy=name, axis_sizes=axis_sizes)
            dp = base.data_parallel_size()
            if global_batch is not None and global_batch % dp:
                pruned.append((base.label,
                               f"batch_indivisible: global batch "
                               f"{global_batch} does not divide across "
                               f"{dp} data shards"))
                continue
            comm_options = [False]
            if cls.comm_compressible:
                if process_count > 1 or comm_enabled_hint:
                    comm_options.append(True)
                else:
                    pruned.append((
                        f"{base.label}:comm",
                        "comm_no_dcn: single-process mesh is all-ICI; "
                        "nothing to compress (pass an explicit "
                        "CommPolicy(axes=...) to opt in)"))
            elif process_count > 1 or comm_enabled_hint:
                pruned.append((
                    f"{base.label}:comm",
                    f"comm_unsupported: strategy {name!r} keeps params "
                    f"sharded across the reduction axes (comm plane "
                    f"declines, parallel/strategy.py comm_compressible)"))
            for comm in comm_options:
                for mb in microbatch:
                    if mb > 1 and global_batch is not None \
                            and global_batch % (dp * mb):
                        pruned.append((
                            dataclasses.replace(
                                base, comm=comm, microbatch=mb).label,
                            f"microbatch_indivisible: global batch "
                            f"{global_batch} does not split into "
                            f"{mb} microbatches over {dp} data shards"))
                        continue
                    for donate in (True, False):
                        for rp in remat_options:
                            candidates.append(dataclasses.replace(
                                base, comm=comm, donate=donate,
                                microbatch=mb, remat=rp))
    return candidates, pruned
