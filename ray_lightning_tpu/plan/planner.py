"""The planner: enumerate → score → verify → pick.

``Planner.plan`` turns (module, example batch, topology) into one
winning :class:`Candidate` plus a full :class:`PlanReport`:

1. **Enumerate** (plan/candidates.py): strategy × mesh factorization ×
   comm × donation × microbatch × remat policy (the module's
   ``configure_remat()`` ladder), statically-infeasible combinations
   pruned with named reasons.
2. **Score without compiling** (plan/cost.py): per-step communication
   seconds from each strategy's ``step_collective_bytes`` declaration
   through the per-link bandwidth model, HBM peak from ``eval_shape``
   avals + shardings + the measured donation decision logic (with the
   candidate policy's saved-activation bytes as the activation term),
   plus the remat policy's modeled traffic/recompute seconds;
   over-budget candidates rejected with named reasons.
3. **Verify cheaply** (compile/aot.py ``compile_scored``): AOT-compile
   only the top-k modeled survivors — in parallel, through the
   persistent compile cache, so the winner's first real dispatch is a
   disk retrieval and re-planning the same shapes is nearly free —
   then re-rank on the compiled programs' REAL ``memory_analysis``
   bytes and audited HLO wire bytes.

Determinism contract: every ranking key is a pure function of the
pickled inputs (config, avals, topology) — measured wall seconds are
*recorded* in the report but never rank — so all ranks of an SPMD
fleet running ``Trainer(strategy="auto")`` independently agree on the
same winner without a collective.

Per-trial plan reuse: inside a builtin tune experiment the report is
memoized by (model fingerprint, topology, config); same-shaped trials
reuse trial 0's plan outright, and their verify compiles would have
been shared-cache hits anyway (tune/runner.py points all trials at one
compile cache).
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import threading
import time
from typing import Optional

import jax
import numpy as np

from ray_lightning_tpu.plan.candidates import (Candidate,
                                               enumerate_candidates,
                                               policy_for_candidate,
                                               resolve_remat_options)
from ray_lightning_tpu.plan.config import PlanConfig
from ray_lightning_tpu.plan.cost import (estimate_candidate, rank_key,
                                         sharded_bytes)
from ray_lightning_tpu.plan.report import PlanReport, make_entry

_log = logging.getLogger(__name__)

#: memoized reports for per-trial reuse (tune experiments only; guarded
#: because the local tune runner executes trials in threads)
_MEMO: dict = {}
_MEMO_LOCK = threading.Lock()


def clear_plan_memo() -> None:
    """Drop memoized plans (tests; a new tune experiment gets fresh
    plans anyway because the config/topology key changes)."""
    with _MEMO_LOCK:
        _MEMO.clear()


def _tune_session_active() -> bool:
    try:
        from ray_lightning_tpu.tune.session import _get
        return _get() is not None
    except Exception:
        return False


def _batch_fingerprint(batch) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    return (str(treedef),
            tuple((tuple(np.shape(x)), str(np.asarray(x).dtype))
                  for x in leaves))


@dataclasses.dataclass
class _Built:
    """Everything the scoring stage materialized for one candidate and
    the verify stage reuses (nothing here is compiled)."""

    candidate: Candidate
    strategy: object
    mesh: object
    grad_sync: object
    tx: object
    abstract: object
    shardings: object
    estimate: object
    #: the module this candidate's programs build from — a per-policy
    #: reconfigured copy when the candidate carries a remat policy, the
    #: caller's module otherwise
    module: object = None


class Planner:
    """Cost-model-driven auto-parallelism (module docstring)."""

    def __init__(self, config=None):
        self.config = PlanConfig.resolve(config)

    # -- candidate materialization ----------------------------------------

    def _build(self, module, cand: Candidate, devices, batch_hint,
               example_batch, tx_factory, base_policy,
               abstract_cache: dict):
        strategy = cand.build_strategy()
        mesh = strategy.build_mesh(devices, batch_hint=batch_hint)
        policy = policy_for_candidate(cand, base_policy)
        grad_sync = strategy.grad_transform(mesh, policy) \
            if policy is not None else None
        if cand.comm and grad_sync is None:
            raise _Infeasible(
                "comm_inert: the comm policy resolves to no compressible "
                "axis on this mesh (comm/collectives.py build_grad_sync)")
        # the abstract state depends only on the tx wrap (the CommState
        # error-feedback residual adds [world, ...] leaves), not on the
        # strategy — cache the eval_shape per (comm, world) so scoring
        # dozens of candidates traces init O(distinct shapes) times
        from ray_lightning_tpu.core.steps import build_init_fn
        world = grad_sync.world if grad_sync is not None \
            and hasattr(grad_sync, "world") else 0
        key = (cand.comm, world)
        tx = tx_factory(grad_sync)
        if key not in abstract_cache:
            abstract_cache[key] = jax.eval_shape(
                build_init_fn(module, tx), jax.random.PRNGKey(0),
                example_batch)
        abstract = abstract_cache[key]
        shardings = strategy.state_shardings(mesh, abstract)
        if grad_sync is not None:
            shardings = shardings.replace(
                opt_state=grad_sync.fix_opt_shardings(
                    shardings.opt_state, abstract.opt_state))
        return strategy, mesh, grad_sync, tx, abstract, shardings

    @staticmethod
    def _module_for_policy(module, spec, policy: str, cache: dict):
        """The module a candidate's programs trace through: for a
        non-default remat policy, a ``copy.copy`` clone reconfigured
        via its own ``configure_remat().apply`` (the clone's spec binds
        the clone, so the caller's module stays on its default until
        the trainer applies the winner)."""
        if spec is None or not policy or policy == spec.default:
            return module
        if policy not in cache:
            clone = copy.copy(module)
            clone.configure_remat().apply(policy)
            cache[policy] = clone
        return cache[policy]

    def _jitted_step(self, built: _Built, gb_abstract):
        """The candidate's real train-step jit, wired exactly as the
        trainer's ``_build_compiled`` would wire it (through the
        candidate's own remat-configured module)."""
        from ray_lightning_tpu.core.steps import build_train_step
        cand = built.candidate
        step = build_train_step(built.module, built.tx, cand.microbatch,
                                grad_sync=built.grad_sync)
        kw = dict(out_shardings=(built.shardings, None))
        if cand.donate:
            kw["donate_argnums"] = 0
        if built.mesh.devices.size > 1:
            kw["in_shardings"] = (
                built.shardings,
                built.strategy.batch_shardings(built.mesh, gb_abstract))
        return jax.jit(step, **kw)

    # -- the plan ----------------------------------------------------------

    def plan(self, module, example_batch, *, devices=None,
             batch_hint: Optional[int] = None,
             process_count: Optional[int] = None,
             base_comm_policy=None, tx_factory=None,
             microbatch_options: Optional[tuple] = None) -> PlanReport:
        """Pick a plan for training ``module`` on this topology.

        ``example_batch`` is the (host-cast, process-local) peeked
        batch; ``batch_hint`` the global batch size; ``tx_factory`` maps
        a resolved GradSync (or None) to the optimizer transform — the
        trainer passes its own ``_configure_tx`` so gradient clipping
        and comm wrapping match the real run.  Raises ``ValueError``
        naming every reason when no candidate survives.
        """
        t0 = time.monotonic()
        cfg = self.config
        devices = list(devices) if devices is not None else jax.devices()
        pc = process_count if process_count is not None \
            else jax.process_count()
        if tx_factory is None:
            def tx_factory(gs):
                tx = module.configure_optimizers()
                if isinstance(tx, dict):
                    tx = tx["optimizer"]
                return gs.wrap_tx(tx) if gs is not None else tx

        memo_key = None
        if cfg.reuse and _tune_session_active():
            memo_key = (type(module).__qualname__,
                        _batch_fingerprint(example_batch),
                        len(devices), pc, batch_hint, cfg)
            with _MEMO_LOCK:
                hit = _MEMO.get(memo_key)
            if hit is not None:
                report = dataclasses.replace(
                    hit, reused=True, cache_misses=0,
                    plan_seconds=time.monotonic() - t0)
                self._note_tune(report)
                return report

        # remat axis: the module's configure_remat() ladder (None = no
        # lever) priced per policy from avals BEFORE enumeration, so a
        # policy whose probe fails drops out with a named prune instead
        # of sinking every candidate that carries it
        spec = module.configure_remat()
        remat_options, remat_pruned = resolve_remat_options(spec, cfg)
        probes: dict = {}
        if spec is not None:
            options = []
            for p in remat_options:
                try:
                    probes[p] = spec.probe(p, example_batch)
                    options.append(p)
                except Exception as e:   # noqa: BLE001 - per-policy soft
                    remat_pruned.append((
                        f"rm-{p}",
                        f"remat_probe_error: {type(e).__name__}: {e}"))
            remat_options = tuple(options) or ("",)

        comm_hint = base_comm_policy is not None and base_comm_policy.enabled
        candidates, pruned = enumerate_candidates(
            len(devices), batch_hint, cfg, process_count=pc,
            microbatch_options=microbatch_options,
            comm_enabled_hint=comm_hint,
            remat_options=remat_options)
        entries = [make_entry(label, "pruned", reason)
                   for label, reason in list(remat_pruned) + list(pruned)]
        if len(candidates) > cfg.max_candidates:
            for cand in candidates[cfg.max_candidates:]:
                entries.append(make_entry(
                    cand, "pruned",
                    f"max_candidates: enumeration capped at "
                    f"{cfg.max_candidates} scored candidates"))
            candidates = candidates[:cfg.max_candidates]

        batch_bytes = sum(
            int(np.asarray(leaf).nbytes)
            for leaf in jax.tree_util.tree_leaves(example_batch)) * pc

        # -- score (no compiles) ------------------------------------------
        abstract_cache: dict = {}
        policy_modules: dict = {}
        built: list[_Built] = []
        for cand in candidates:
            cand_module = self._module_for_policy(module, spec,
                                                  cand.remat,
                                                  policy_modules)
            try:
                strategy, mesh, gs, tx, abstract, shardings = self._build(
                    cand_module, cand, devices, batch_hint, example_batch,
                    tx_factory, base_comm_policy, abstract_cache)
            except _Infeasible as e:
                entries.append(make_entry(cand, "rejected", str(e)))
                continue
            except Exception as e:   # noqa: BLE001 - per-candidate soft
                entries.append(make_entry(
                    cand, "rejected",
                    f"build_error: {type(e).__name__}: {e}"))
                continue
            est = estimate_candidate(cand, strategy, mesh, abstract,
                                     shardings, batch_bytes, cfg, pc,
                                     grad_sync=gs,
                                     remat_probe=probes.get(cand.remat))
            if not est.fits:
                entries.append(make_entry(cand, "rejected", est.reason,
                                          modeled=est.to_dict()))
                continue
            built.append(_Built(cand, strategy, mesh, gs, tx, abstract,
                                shardings, est, module=cand_module))

        built.sort(key=lambda b: rank_key(b.candidate, b.estimate))

        # -- verify (AOT-compile top-k through the persistent cache) ------
        from ray_lightning_tpu.compile import cache as compile_cache
        from ray_lightning_tpu.compile.aot import (compile_scored,
                                                   global_batch_abstract)
        gb_abstract = global_batch_abstract(example_batch, pc)
        top = built[:cfg.topk] if cfg.topk > 0 else []
        rest = built[len(top):]
        misses_before = compile_cache.stats().misses
        programs = []
        for b in top:
            try:
                jitted = self._jitted_step(b, gb_abstract)
            except Exception as e:   # noqa: BLE001 - per-candidate soft
                entries.append(make_entry(
                    b.candidate, "rejected",
                    f"jit_error: {type(e).__name__}: {e}",
                    modeled=b.estimate.to_dict()))
                continue
            programs.append((b.candidate.label, jitted,
                             (b.abstract, gb_abstract),
                             b.strategy.data_parallel_size(b.mesh),
                             getattr(b.grad_sync, "ici_size", 0)
                             if getattr(b.grad_sync, "hierarchical",
                                        False) else 0))
        scored = compile_scored(programs)
        cache_misses = compile_cache.stats().misses - misses_before

        verified: list[tuple[tuple, _Built, dict]] = []
        for b in top:
            sc = scored.get(b.candidate.label)
            if sc is None:
                continue        # jit_error entry already recorded
            if not sc.ok:
                entries.append(make_entry(
                    b.candidate, "rejected",
                    f"compile_error: {sc.error}",
                    modeled=b.estimate.to_dict(),
                    measured=sc.to_dict()))
                continue
            budget = b.estimate.budget
            if budget is not None and sc.peak_bytes \
                    > cfg.headroom * budget:
                entries.append(make_entry(
                    b.candidate, "rejected",
                    f"hbm_over_budget_measured: compiled peak "
                    f"{sc.peak_bytes >> 20} MiB > "
                    f"{int(cfg.headroom * budget) >> 20} MiB budget",
                    modeled=b.estimate.to_dict(),
                    measured=sc.to_dict()))
                continue
            from ray_lightning_tpu.comm.audit import bytes_to_seconds
            gbps = cfg.dcn_gbps if pc > 1 else cfg.ici_gbps
            if sc.wire_bytes_dcn or sc.wire_bytes_ici:
                # hierarchical candidate: audited bytes re-rank at
                # per-link bandwidths, mirroring the modeled score
                # (plan/cost.py link_gbps) — charging the fp32 ICI
                # phases at DCN speed would un-rank the exact programs
                # the hierarchy exists to favor
                audited_seconds = (
                    bytes_to_seconds(sc.wire_bytes_dcn, gbps)
                    + bytes_to_seconds(sc.wire_bytes_ici, cfg.ici_gbps))
            else:
                audited_seconds = bytes_to_seconds(sc.wire_bytes, gbps)
            mismatch = 0 if b.candidate.donate \
                == b.estimate.donate_preferred else 1
            # the remat term stays modeled through the verify re-rank
            # (compiling changes what we know about MEMORY, not about
            # recompute seconds) — still a pure function of config+avals
            key = (audited_seconds + b.estimate.remat_seconds, mismatch,
                   sc.peak_bytes, b.candidate.label)
            measured = sc.to_dict()
            measured["audited_seconds"] = audited_seconds
            verified.append((key, b, measured))

        verified.sort(key=lambda t: t[0])
        winner: Optional[_Built] = None
        winner_measured = None
        if verified:
            winner = verified[0][1]
            winner_measured = verified[0][2]
            for _, b, measured in verified[1:]:
                entries.append(make_entry(b.candidate, "compiled",
                                          modeled=b.estimate.to_dict(),
                                          measured=measured))
        elif rest or (built and cfg.topk == 0):
            # verify stage produced nothing usable (topk=0, or every
            # top-k compile failed/over-budget): fall back to the best
            # remaining MODELED survivor rather than dying
            fallback = rest if cfg.topk > 0 else built
            winner = fallback[0]
            rest = fallback[1:]
            if cfg.topk > 0:
                _log.warning(
                    "plan: all top-%d verify candidates failed; falling "
                    "back to the best un-verified modeled candidate %s",
                    cfg.topk, winner.candidate.label)
        for b in rest:
            entries.append(make_entry(b.candidate, "scored",
                                      modeled=b.estimate.to_dict()))

        if winner is None:
            reasons = "; ".join(
                f"{e['label']}: {e['reason']}" for e in entries
                if e.get("reason"))
            raise ValueError(
                "strategy='auto' found no feasible plan — every "
                f"candidate was pruned or rejected: {reasons}")

        entries.append(make_entry(winner.candidate, "winner",
                                  modeled=winner.estimate.to_dict(),
                                  measured=winner_measured))
        report = PlanReport(
            entries=entries,
            winner_label=winner.candidate.label,
            topk=cfg.topk,
            plan_seconds=time.monotonic() - t0,
            cache_misses=cache_misses,
            winner_candidate=winner.candidate,
            winner_policy=policy_for_candidate(winner.candidate,
                                               base_comm_policy),
        )
        if memo_key is not None:
            with _MEMO_LOCK:
                _MEMO[memo_key] = report
        self._note_tune(report)
        return report

    @staticmethod
    def _note_tune(report: PlanReport) -> None:
        try:
            from ray_lightning_tpu.tune.session import note_plan_report
            note_plan_report(report.to_dict())
        except Exception:   # noqa: BLE001 - tune plane optional here
            pass


class _Infeasible(Exception):
    """A candidate that cannot be materialized (named reason)."""
