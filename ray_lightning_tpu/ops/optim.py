"""Optimizer-side mixed precision: low-precision resident params with an
fp32 master copy carried in the optimizer state.

TPU-first rationale: with fp32-resident params and bf16 compute (flax
``dtype=bfloat16``), every forward re-casts every kernel fp32->bf16 and
every backward produces an fp32 cotangent — on the gpt2-small headline
that is ~8.7 ms/step of pure dtype-convert fusions (benchmarks/README.md
device trace).  Keeping the *resident* params bf16 deletes those casts
from the hot program and halves param HBM residency.  (It does NOT
shrink the gradient all-reduce: the partitioner must resolve each
cross-replica partial sum at the f32-accumulating grad dot, BEFORE the
bf16 cotangent cast — summing bf16-rounded partials would change the
numerics — so gradient collectives ride at f32 by construction; audited
at the compiled-HLO level in tests/test_collective_audit.py.)  Full
precision is preserved where it matters — the optimizer update — by an
fp32 master copy inside the optimizer state.  This is the classic
mixed-precision recipe; on ZeRO-1/SPMD meshes the master shards with
the rest of the optimizer state, exactly as FairScale OSS shards its
fp32 copy across DDP ranks (reference: ray_ddp_sharded.py:17-34 — OSS
wraps the optimizer and owns the full-precision weights; here the same
ownership is a pytree inside ``opt_state`` whose leaves mirror the
param paths, so the strategies' path-regex sharding rules apply to the
master for free).

Exact-replacement semantics: the trainer applies updates with
``optax.apply_updates`` (``(p + u).astype(p.dtype)``, core/steps.py).
We return fp32 deltas ``cast(new_master) - p``; both operands are
bf16-representable values, so the fp32 subtraction and re-addition are
exact (a difference of two 8-bit-mantissa values fits fp32's 24 bits
whenever their exponents are within 16 — always true for a finite
optimizer step), and the final cast lands exactly on
``cast(new_master)``.  The resident params therefore track the master
bit-for-bit, with no drift between replicas.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class FP32MasterState(NamedTuple):
    """State of :func:`fp32_master`.

    ``master`` mirrors the param tree in fp32; it sits *before* the
    inner state so its pytree paths read ``.../master/<param path>`` and
    the strategies' path-embedding opt-state rules (parallel/strategy.py
    ``SpmdStrategy.opt_spec``) shard it like the param it shadows.
    """

    inner: Any
    master: Any


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def fp32_master(inner: optax.GradientTransformation
                ) -> optax.GradientTransformation:
    """Wrap ``inner`` to run against an fp32 master copy of the params.

    Use with low-precision resident params (``LightningModule.param_dtype
    = jnp.bfloat16``): gradients are upcast to fp32, ``inner`` updates
    the fp32 master, and the emitted update replaces the resident params
    with the master re-cast to their dtype (exactly — see module
    docstring).  Non-float leaves pass through untouched.
    """

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32) if _is_float(p) else p, params)
        return FP32MasterState(inner=inner.init(master), master=master)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fp32_master requires params in update()")
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) if _is_float(g) else g, grads)
        updates, new_inner = inner.update(g32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, updates)
        new_resident = jax.tree_util.tree_map(
            lambda m, p: m.astype(jnp.asarray(p).dtype), new_master, params)
        out = jax.tree_util.tree_map(
            lambda n, p: (n.astype(jnp.float32) - p.astype(jnp.float32))
            if _is_float(p) else jnp.zeros_like(p),
            new_resident, params)
        return out, FP32MasterState(inner=new_inner, master=new_master)

    return optax.GradientTransformation(init, update)
