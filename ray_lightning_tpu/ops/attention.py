"""Attention dispatch + the shared multi-head attention block.

One home for the attention path selection used by every model family
(GPT decoder, BERT encoder) so kernel improvements land in one place:

- :func:`dot_product_attention` — XLA reference attention (materialized
  scores, fp32 softmax);
- the Pallas flash kernel (ops/flash_attention.py) — streaming online
  softmax, the fast path on TPU;
- ring attention (parallel/ring.py) — sequence-parallel flash whose KV
  blocks rotate around the mesh;
- :func:`auto_attention` — trace-time choice: on TPU, the flash kernel
  (measured faster at every seq length on v5e, and the only path at
  T≥8k) — directly on one chip, via :func:`sharded_flash_attention`'s
  shard_map over batch/head axes on multi-chip meshes whose shapes
  divide evenly; dot attention elsewhere (CPU tests; sequence-sharded
  meshes belong to ring attention; uneven shapes stay on GSPMD dot,
  which pads).

:class:`MultiHeadAttention` carries the qkv/attend/proj plumbing shared
by the model families; its submodule names (``qkv``, ``proj``) are part
of the checkpoint/partition-rule contract (``attn/qkv/kernel`` etc. in
``gpt_partition_rules`` / ``bert_partition_rules``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def dot_product_attention(q, k, v, *, causal: bool = True,
                          dtype=jnp.bfloat16):
    """Reference attention: one fused softmax(QKᵀ)V in fp32 accumulation.

    q,k,v: [B, T, H, D].  XLA fuses mask+softmax into the matmuls; for
    long T prefer the pallas flash kernel (ops/flash_attention.py).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / np.sqrt(d)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), tk - tq)
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def auto_attention(q, k, v, **kw):
    """Trace-time attention choice (see module docstring)."""
    if jax.devices()[0].platform != "tpu":
        return dot_product_attention(q, k, v, **kw)
    from ray_lightning_tpu.ops.flash_attention import flash_attention
    if jax.device_count() == 1:
        return flash_attention(q, k, v, **kw)
    from ray_lightning_tpu.parallel.mesh import (
        get_current_mesh, mesh_axis_size)
    mesh = get_current_mesh()
    if mesh is not None and mesh.shape.get("sequence", 1) == 1:
        # multi-chip without sequence sharding: batch rides data/fsdp,
        # heads ride tensor — both per-device under shard_map, so the
        # kernel applies unchanged on each device's local shard.  Only
        # when shapes divide evenly: shard_map has no padding, GSPMD
        # dot does — uneven configs keep working via the dot path.
        dp_size = mesh_axis_size(mesh, "data", "fsdp")
        t_size = mesh_axis_size(mesh, "tensor")
        if q.shape[0] % dp_size == 0 and q.shape[2] % t_size == 0:
            return sharded_flash_attention(q, k, v, mesh=mesh, **kw)
    # sequence-sharded meshes use ring attention (attention_impl="ring");
    # no mesh / uneven shapes → the XLA path, which GSPMD partitions
    return dot_product_attention(q, k, v, **kw)


def sharded_flash_attention(q, k, v, *, mesh, causal: bool = True,
                            dtype=jnp.bfloat16, **kw):
    """Flash attention over a (data[, fsdp][, tensor]) mesh: shard_map
    over batch (data/fsdp) and heads (tensor); each device runs the
    Pallas kernel on its local [b_local, T, h_local, D] block.  No
    collectives are needed — attention mixes only T and D, which stay
    unsharded here (sequence sharding is ring attention's job)."""
    from ray_lightning_tpu.ops.flash_attention import flash_attention
    from ray_lightning_tpu.parallel.mesh import (data_and_tensor_axes,
                                                 shard_map_compat)
    from jax.sharding import PartitionSpec as P

    dp, tensor = data_and_tensor_axes(mesh)
    spec = P(dp, None, tensor, None)

    def inner(ql, kl, vl):
        return flash_attention(ql, kl, vl, causal=causal, dtype=dtype,
                               **kw)

    fn = shard_map_compat(inner, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)


def local_attention(q, k, v, **kw):
    """Per-device attention for MANUAL (shard_map) regions — e.g. inside
    the pipeline schedule (parallel/pipeline.py), where the mesh axes
    are already manual and opening another shard_map (as auto_attention's
    sharded path would) is a trace error.  Picks the pallas flash kernel
    on TPU, the XLA dot path elsewhere; never consults the mesh."""
    if jax.devices()[0].platform == "tpu":
        from ray_lightning_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, **kw)
    return dot_product_attention(q, k, v, **kw)


def cached_attention(q, k_cache, v_cache, positions, *,
                     dtype=jnp.bfloat16, impl=None, page_table=None):
    """Single-token attention against a slot-indexed KV cache (the serve
    plane's decode core, ray_lightning_tpu/serve/).

    ``q``: [S, 1, H, D] — one new token per batch slot; ``k_cache`` /
    ``v_cache``: [S, L, H, D] — each slot's full context; ``positions``:
    [S] — the absolute position of slot s's current token.  Slot s
    attends cache indices <= positions[s]: indices beyond its position
    hold stale prefill padding or a previous tenant's leftovers, which
    decode must never read (serve/kvcache.py invariant).

    ``impl`` picks the kernel (explicit > ``RLT_DECODE_IMPL`` env >
    ``auto``): ``dense`` is the masked einsum below; ``flash_decode`` is
    the length-aware Pallas kernel (ops/flash_decode.py) that reads only
    live KV blocks; ``paged`` additionally walks ``page_table``
    ([S, pages_per_slot] int32, serve/fleet/pages.py) so the fetch is
    page-indirect.  Unsupported geometry falls back to dense — same
    numbers, no surprise crash on odd head shapes.

    Multi-query form (speculative-decode verify, core/steps.py
    ``build_verify_step``): ``q`` [S, T, H, D] with ``positions``
    [S, T] — T queries per slot at consecutive positions, each masked
    to its OWN position bound, so one batched target forward scores all
    T drafted tokens under exactly the masks T sequential decode steps
    would have used.  Lowered as T single-query attentions (each free
    to take the flash/paged kernel) — T is the small speculation depth
    k+1, and this keeps the per-query length masking identical to plain
    decode, which is what makes greedy parity exact by construction.
    """
    from ray_lightning_tpu.ops.flash_decode import (
        NEG_INF, decode_kernel_supported, flash_decode_attention,
        resolve_decode_impl)

    if positions.ndim == 2:
        if q.shape[1] == 1:
            positions = positions[:, 0]
        else:
            return jnp.concatenate(
                [cached_attention(q[:, j:j + 1], k_cache, v_cache,
                                  positions[:, j], dtype=dtype, impl=impl,
                                  page_table=page_table)
                 for j in range(q.shape[1])], axis=1)

    impl = resolve_decode_impl(impl)
    if impl == "paged" and page_table is None:
        impl = "flash_decode"  # no table plumbed: slot-contiguous kernel
    if impl in ("flash_decode", "paged"):
        S, _, H, D = q.shape
        L = k_cache.shape[1]
        bk = (L // page_table.shape[1] if impl == "paged"
              else None)
        from ray_lightning_tpu.ops.flash_decode import _pick_block_k
        if decode_kernel_supported(L, H, D,
                                   block_k=bk or _pick_block_k(L),
                                   dtype=q.dtype):
            return flash_decode_attention(
                q, k_cache, v_cache, positions, dtype=dtype,
                page_table=page_table if impl == "paged" else None)
    d = q.shape[-1]
    scores = jnp.einsum("sqhd,slhd->shql", q, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores / np.sqrt(d)
    valid = jnp.arange(k_cache.shape[1])[None, :] <= positions[:, None]
    # NEG_INF (-1e30), not finfo.min: the flash kernels' NaN-free
    # masking constant — finfo.min survives one subtract in fp32 but a
    # fully-masked row would softmax over exact -inf after scaling
    # drift; -1e30 keeps exp/log finite everywhere
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("shql,slhd->sqhd", probs, v_cache)


def resolve_attention(impl: str) -> Callable:
    if impl == "auto":
        return auto_attention
    if impl == "dot":
        return dot_product_attention
    if impl == "local":
        return local_attention
    if impl == "flash":
        from ray_lightning_tpu.ops.flash_attention import flash_attention
        return flash_attention
    if impl == "ring":
        from ray_lightning_tpu.parallel.ring import ring_attention
        return ring_attention
    if impl == "flash_decode":
        # decode-path signature: (q, k_cache, v_cache, positions) — the
        # serve plane's cached_attention kernel (ops/flash_decode.py),
        # auto-selected on TPU the way auto_attention picks flash
        from ray_lightning_tpu.ops.flash_decode import (
            flash_decode_attention)
        return flash_decode_attention
    raise ValueError(f"Unknown attention_impl {impl!r}")


class MultiHeadAttention(nn.Module):
    """Fused-QKV multi-head attention: ``[B,T,C] -> [B,T,C]``.

    Shared by the GPT decoder (causal=True) and BERT encoder
    (causal=False).  Submodule names qkv/proj are load-bearing for
    partition rules and checkpoints.
    """

    n_head: int
    causal: bool = True
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, deterministic: bool = True, *,
                 decode_cache=None, positions=None, page_table=None):
        B, T, C = x.shape
        head_dim = C // self.n_head
        qkv = nn.Dense(3 * C, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.n_head, head_dim)
        q, k, v = (a.reshape(shape) for a in (q, k, v))
        if decode_cache is not None:
            # serve-plane decode: B = batch slots, T = 1.  Write this
            # token's k/v at each slot's own position, then attend the
            # query over the (just-updated) cache — mask handled by
            # cached_attention's per-slot position bound.  Shapes are
            # static, so slots with no live request write too (the
            # scheduler sends tokens=0/positions=0 for them): a dummy
            # entry at position 0 the serve plane must overwrite via the
            # slot's admitting prefill BEFORE the slot decodes — hence
            # ServeWorker.serve_step dispatches decode before prefills.
            k_cache, v_cache = decode_cache
            slots = jnp.arange(B)
            if T == 1:
                k_cache = k_cache.at[slots, positions].set(k[:, 0])
                v_cache = v_cache.at[slots, positions].set(v[:, 0])
            else:
                # multi-query verify (T = speculation depth k+1,
                # positions [B, T]): write every query's K/V first,
                # then attend each query under its own position bound
                # (cached_attention's multi-query form) — causal by the
                # bound, so query j never sees rows j+1..T-1.  Rows at
                # positions >= L (slots speculating past the cache end,
                # and the paging dummy row's +j offsets) are DROPPED by
                # jax's out-of-bounds scatter semantics — no per-slot
                # gating, no shape change, no retrace.
                k_cache = k_cache.at[slots[:, None], positions].set(k)
                v_cache = v_cache.at[slots[:, None], positions].set(v)
            y = cached_attention(q, k_cache, v_cache, positions,
                                 dtype=self.dtype, page_table=page_table)
            y = nn.Dense(C, dtype=self.dtype,
                         name="proj")(y.reshape(B, T, C))
            return y, (k_cache, v_cache)
        # prefill capture: when the caller applies with
        # mutable=("kv_cache",) the per-layer K/V land in that collection
        # (serve/engine.py reads them into the slot cache); in every
        # other apply — training included — sow is a no-op.  Never sown
        # at init (init makes every collection mutable, which would leak
        # a kv_cache collection into the train state).
        if not self.is_initializing():
            self.sow("kv_cache", "kv", (k, v))
        attend = resolve_attention(self.attention_impl)
        y = attend(q, k, v, causal=self.causal, dtype=self.dtype)
        y = nn.Dense(C, dtype=self.dtype, name="proj")(y.reshape(B, T, C))
        if self.dropout > 0:
            y = nn.Dropout(self.dropout)(y, deterministic=deterministic)
        return y
