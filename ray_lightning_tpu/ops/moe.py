"""Mixture-of-Experts feed-forward layer with expert parallelism.

Beyond the reference's parity surface (SURVEY.md §2.3 marks EP absent);
built TPU-first rather than ported:

- **Static shapes**: GShard/Switch-style fixed expert *capacity* — every
  expert processes exactly ``capacity`` token slots per group, so the
  whole layer is three einsums XLA can tile onto the MXU.  No dynamic
  gather/scatter, no data-dependent shapes (SURVEY.md's XLA-semantics
  constraint).
- **Expert parallelism as sharding**: expert weights carry a leading
  ``[n_experts, ...]`` dim annotated on the ``expert`` mesh axis
  (``moe_partition_rules``); tokens stay sharded on ``data``.  GSPMD
  lowers the dispatch/combine einsums to the all-to-all over ICI —
  the same "parallelism is an annotation, collectives are compiler
  output" inversion as the rest of ``parallel/strategy.py``.
- **fp32 router**: gate logits/softmax in fp32 (bf16 routing is noisy
  enough to destabilize small models), expert FFN in the compute dtype.

The router sows its load-balance auxiliary loss into the ``losses``
variable collection (overwrite semantics, so the carried value stays a
scalar across steps); :func:`total_aux_loss` folds the collection into
the training loss.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def _overwrite(prev, new):
    """sow reduce_fn: keep the latest value (no unbounded tuple growth
    when the collection is threaded through successive train steps)."""
    del prev
    return new


class MoEMLP(nn.Module):
    """Drop-in MLP replacement routing each token to ``top_k`` experts.

    Input/output: ``[groups, tokens, d_model]`` (groups = the batch dim;
    capacity is computed per group).  Tokens beyond an expert's capacity
    are *dropped* — their output is zero, and the surrounding residual
    connection passes them through unchanged (the standard Switch
    behavior).
    """

    n_experts: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        del deterministic  # routing is deterministic; no dropout inside
        G, S, M = x.shape
        E, k = self.n_experts, self.top_k
        if not 1 <= k <= E:
            raise ValueError(f"top_k={k} must be in [1, {E}]")
        capacity = min(S, int(math.ceil(self.capacity_factor * k * S / E)))

        router = self.param("router", nn.initializers.normal(0.02), (M, E),
                            jnp.float32)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, M, self.d_ff), jnp.float32)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, self.d_ff, M), jnp.float32)

        gate_logits = jnp.einsum("gsm,me->gse", x.astype(jnp.float32), router)
        probs = jax.nn.softmax(gate_logits, axis=-1)          # [G,S,E] fp32

        gate_vals, gate_idx = jax.lax.top_k(probs, k)         # [G,S,k]
        if k > 1:
            gate_vals = gate_vals / (
                jnp.sum(gate_vals, axis=-1, keepdims=True) + 1e-9)
        # k == 1 keeps the RAW top-1 probability (Switch-Transformer
        # style): renormalizing would pin the combine weight at 1.0 and
        # sever the router's gradient path through the task loss.

        # Fill expert slots choice-by-choice; the per-expert position
        # counter carries across choices so a token's 2nd-choice expert
        # sees slots already taken by other tokens' 1st choices.
        # (A compute-dtype [G,S,E,cap] chain was tried in round 5 —
        # exact by disjointness — and measured 80.08 ms/step, identical
        # to this fp32 chain: the expert-bwd drag fusions' bytes are
        # einsum operand traffic, not chain dtype; see the README's
        # round-5 MoE rejected-experiment note.)
        dispatch = jnp.zeros((G, S, E, capacity), dtype=x.dtype)
        combine = jnp.zeros((G, S, E, capacity), dtype=jnp.float32)
        taken = jnp.zeros((G, 1, E), dtype=jnp.int32)
        for i in range(k):
            onehot = jax.nn.one_hot(gate_idx[..., i], E,
                                    dtype=jnp.int32)          # [G,S,E]
            pos = jnp.cumsum(onehot, axis=1) - 1 + taken      # slot index
            taken = taken + jnp.sum(onehot, axis=1, keepdims=True)
            keep = onehot * (pos < capacity)                  # overflow drop
            slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                                  dtype=jnp.float32)          # [G,S,E,cap]
            d_i = keep.astype(jnp.float32)[..., None] * slot
            dispatch = dispatch + d_i.astype(x.dtype)
            combine = combine + gate_vals[..., i, None, None] * d_i

        # Switch load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e);
        # 1.0 at perfect balance, grows as routing collapses.
        first = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32)
        frac = jnp.mean(first, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
        aux = E * jnp.sum(frac * mean_prob)
        self.sow("losses", "moe_aux", aux, reduce_fn=_overwrite,
                 init_fn=lambda: jnp.zeros((), jnp.float32))

        # dispatch → expert FFN → combine: three MXU einsums.  With w1/w2
        # sharded on the expert axis and tokens on data, GSPMD inserts the
        # token all-to-all around the FFN automatically.  The big
        # intermediates carry checkpoint_names so remat policies can save
        # them selectively (models/gpt.py "dots_moe_act"/"dots_moe") —
        # measured round 5: BOTH save-lists lose to plain "dots"
        # (81.97 / 83.12 vs 80.08 ms/step; the HBM round-trip of the
        # saved tensors exceeds the recompute it removes), so they exist
        # as documented rejected options, not defaults.
        from jax.ad_checkpoint import checkpoint_name as name
        dispatch = name(dispatch, "moe_dispatch")
        xe = jnp.einsum("gsec,gsm->egcm", dispatch, x)
        h = jnp.einsum("egcm,emh->egch", xe, w1.astype(self.dtype))
        h = name(nn.gelu(h), "moe_hact")
        out = jnp.einsum("egch,ehm->egcm", h, w2.astype(self.dtype))
        # the tag sits on the bf16-cast combine (the tensor the einsum
        # consumes), not the fp32 original — saving double-width bytes
        # would pessimize the save-list option for no consumer
        return jnp.einsum("gsec,egcm->gsm",
                          name(combine.astype(self.dtype), "moe_combine"),
                          out)


def moe_partition_rules(expert_axis: str = "expert",
                        tensor_axis: str = "tensor"):
    """SpmdStrategy rules for MoE parameters (prepend to the model's own
    rules).  Expert dim sharded on ``expert``; within each expert the FFN
    is Megatron-split on ``tensor``; the router stays replicated (it is
    tiny and every data shard needs it)."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"moe/w1$", P(expert_axis, None, tensor_axis)),
        (r"moe/w2$", P(expert_axis, tensor_axis, None)),
        (r"moe/router$", P()),
    ]


def total_aux_loss(model_state) -> "jax.Array | None":
    """Sum every sown ``losses`` leaf (one per MoE layer), or None if the
    model has no loss-sowing layers."""
    tree = (model_state or {}).get("losses")
    if not tree:
        return None
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return None
    total = leaves[0]
    for leaf in leaves[1:]:
        total = total + leaf
    return total
