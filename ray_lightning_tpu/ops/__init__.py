"""TPU Pallas kernels for the hot ops.

The reference has no custom kernels (its compute path is torch/CUDA via
DistributedDataParallel); here the hot attention op gets a hand-written
TPU kernel where XLA's generic fusion isn't enough (long sequences whose
full [T, T] score matrix would blow HBM).
"""

from ray_lightning_tpu.ops.flash_attention import flash_attention
from ray_lightning_tpu.ops.moe import (MoEMLP, moe_partition_rules,
                                       total_aux_loss)

__all__ = ["flash_attention", "MoEMLP", "moe_partition_rules",
           "total_aux_loss"]
