"""Memory-lean losses for large-vocabulary language models.

At V≈50k and B·T≈8k, the fp32 logits tensor of a full-vocab
cross-entropy is ~1.6 GB — written, read and differentiated every step,
it dominates the loss's HBM traffic (the TPU bottleneck, BASELINE.md).
:func:`chunked_softmax_cross_entropy` streams the vocab projection in
row chunks under ``lax.scan`` with per-chunk rematerialization: each
chunk computes its own [rows, V] logits on the MXU (bf16 operands, fp32
accumulation), folds them into the loss, and lets the backward pass
recompute them instead of storing residuals — peak logits memory drops
by the chunk factor while the extra FLOPs are one repeated head matmul
(a few % of a transformer step).

When to use: an OPT-IN for memory-bound configs (long sequence × 50k
vocab, e.g. the gpt2-1p3b class, where full fp32 logits cost multiple
GB).  At gpt2-small scale it measured ~8% slower than the fused
full-vocab loss on v5e — XLA's own fusion wins when the logits fit —
so the default loss path stays full-vocab.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import optax

_log = logging.getLogger(__name__)


def fused_lm_cross_entropy(hidden, table, targets):
    """Full-vocab CE that never writes fp32 logits to HBM.

    The naive tied-head path (``wte.attend(h).astype(f32)`` → optax CE)
    materializes BOTH an fp32 [B,T,V] logits tensor (~1.6 GB at
    gpt2-small scale) and a bf16 copy saved for the softmax recompute —
    measured 3.76 ms at 2.56 GB accessed for the forward head fusion
    alone (benchmarks/profile_headline.py roofline).  Here the head
    matmul emits logits in the compute dtype once, and the
    max/logsumexp/label-gather reductions upcast per-element *inside*
    their fusions (fp32 accumulators, nothing fp32 ever hits HBM).
    Forward precision matches the naive path: its fp32 logits were
    produced by a bf16-operand matmul, so they carry the same rounding
    this path keeps.

    hidden: [B, T, D] compute dtype; table: [V, D] tied embedding;
    targets: [B, T] int labels.  Returns mean token CE (fp32 scalar).
    """
    logits = jax.lax.dot_general(
        hidden, table.astype(hidden.dtype),
        (((2,), (1,)), ((), ())))                      # [B, T, V] bf16
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    # upcast BEFORE the max subtraction: both casts are exact (m is one
    # of the logits) and stay elementwise inside the reduction fusion,
    # so the exp argument carries full fp32 precision — identical to the
    # naive path — while still no fp32 [B,T,V] tensor hits HBM
    shifted = logits.astype(jnp.float32) - m.astype(jnp.float32)[..., None]
    sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
    lse = jnp.log(sumexp) + m.astype(jnp.float32)
    logit_y = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    return (lse - logit_y.astype(jnp.float32)).mean()


def chunked_softmax_cross_entropy(hidden, table, targets,
                                  n_chunks: int = 8):
    """Mean token cross-entropy of ``hidden @ table.T`` against targets,
    never materializing the full logits tensor.

    hidden: [B, T, D] (compute dtype, e.g. bf16)
    table:  [V, D] tied embedding table (any float dtype)
    targets:[B, T] int labels
    """
    B, T, D = hidden.shape
    rows_total = B * T
    requested = n_chunks
    n_chunks = max(1, min(n_chunks, rows_total))
    while rows_total % n_chunks:
        n_chunks -= 1
    if n_chunks < min(requested, rows_total):
        # silent degradation would reintroduce the very logits-memory
        # spike this function exists to avoid — make it visible
        _log.warning(
            "chunked CE: %d rows not divisible into %d chunks; using %d "
            "(peak logits memory grows by the same factor).",
            rows_total, requested, n_chunks)
    rows = rows_total // n_chunks

    h = hidden.reshape(n_chunks, rows, D)
    y = targets.reshape(n_chunks, rows)
    table = table.astype(hidden.dtype)

    def body(total, xs):
        hc, yc = xs
        logits = jax.lax.dot_general(
            hc, table, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [rows, V] f32
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, yc)
        return total + ce.sum(), None

    total, _ = jax.lax.scan(jax.checkpoint(body),
                            jnp.zeros((), jnp.float32), (h, y))
    return total / rows_total
