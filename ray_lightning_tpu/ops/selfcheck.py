"""Ops-plane selfcheck (wired into ``format.sh --check``).

Runs in a fresh interpreter pinned to CPU (the Pallas interpreter
executes the real kernel bodies there), then asserts the decode-kernel
invariants that don't need a device or a full serve run:

- ``resolve_decode_impl``: explicit arg beats ``RLT_DECODE_IMPL`` beats
  auto, every valid impl round-trips, junk raises;
- the ``kv_block_bound`` index-map clamp agrees EXACTLY with the kernel
  body's ``kb * block_k <= pos`` compute guard over an exhaustive grid
  — the DMA-skip and the masking must never disagree about which KV
  block is last;
- ``decode_kernel_supported`` geometry gating (lane alignment, sublane
  tiling) never throws, only declines;
- lowering sanity: the flash-decode kernel (interpret mode) matches the
  dense masked einsum at a ragged-position shape, fp32-tight;
- ``identity_page_table`` round-trips (flattens to ``arange``, rejects
  non-tiling page sizes) and the paged kernel over the identity table
  is BITWISE the slot-contiguous kernel at the same block size.
"""

from __future__ import annotations

import os


def _main(argv) -> int:   # noqa: ARG001 - argv kept for parity
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ray_lightning_tpu.ops.attention import cached_attention
    from ray_lightning_tpu.ops.flash_decode import (
        VALID_DECODE_IMPLS, decode_kernel_supported,
        flash_decode_attention, kv_block_bound, resolve_decode_impl)
    from ray_lightning_tpu.serve.fleet.pages import identity_page_table
    import jax.numpy as jnp

    problems: list[str] = []

    # 1. impl resolution precedence: explicit > env > auto
    saved = os.environ.get("RLT_DECODE_IMPL")
    try:
        os.environ["RLT_DECODE_IMPL"] = "flash_decode"
        if resolve_decode_impl("dense") != "dense":
            problems.append("explicit impl did not beat the env knob")
        if resolve_decode_impl(None) != "flash_decode":
            problems.append("RLT_DECODE_IMPL not honored")
        os.environ.pop("RLT_DECODE_IMPL")
        if resolve_decode_impl(None) not in VALID_DECODE_IMPLS:
            problems.append("auto resolution left the valid set")
        for impl in VALID_DECODE_IMPLS:
            if impl != "auto" and resolve_decode_impl(impl) != impl:
                problems.append(f"impl {impl!r} does not round-trip")
        try:
            resolve_decode_impl("warp")
        except ValueError:
            pass
        else:
            problems.append("junk impl did not raise")
    finally:
        if saved is None:
            os.environ.pop("RLT_DECODE_IMPL", None)
        else:
            os.environ["RLT_DECODE_IMPL"] = saved

    # 2. the grid-skip invariant: the index-map clamp and the compute
    # guard must agree on every (kb, pos) — a block the map refuses to
    # fetch must be one the body never reads, and vice versa
    block_k = 16
    for pos in range(0, 64):
        for kb in range(0, 4):
            clamped = int(kv_block_bound(kb, pos, block_k))
            live = kb * block_k <= pos
            if live and clamped != kb:
                problems.append(
                    f"kv_block_bound skipped a LIVE block: kb={kb} "
                    f"pos={pos} -> {clamped}")
            if not live and clamped == kb:
                problems.append(
                    f"kv_block_bound fetched a DEAD block: kb={kb} "
                    f"pos={pos}")
            if not 0 <= clamped <= kb:
                problems.append(
                    f"kv_block_bound left [0, kb]: kb={kb} pos={pos} "
                    f"-> {clamped}")

    # 3. geometry gating declines, never throws
    for args in ((96, 3, 24), (128, 2, 64), (2048, 8, 64)):
        try:
            decode_kernel_supported(*args, block_k=128,
                                    dtype=jnp.bfloat16)
        except Exception as e:   # noqa: BLE001 - report, don't crash
            problems.append(f"decode_kernel_supported{args} raised "
                            f"{e!r}")

    # 4. lowering sanity: kernel (interpret) vs dense masked einsum
    S, L, H, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (S, 1, H, D), jnp.float32)
    kc = jax.random.normal(ks[1], (S, L, H, D), jnp.float32)
    vc = jax.random.normal(ks[2], (S, L, H, D), jnp.float32)
    pos = jnp.asarray([3, L - 1], jnp.int32)
    dense = cached_attention(q, kc, vc, pos, dtype=jnp.float32,
                             impl="dense")
    flash = flash_decode_attention(q, kc, vc, pos, dtype=jnp.float32,
                                   block_k=16)
    err = float(jnp.max(jnp.abs(dense - flash)))
    if not err < 2e-5:
        problems.append(f"flash-decode kernel diverged from the dense "
                        f"reference: max abs err {err}")

    # 5. identity page table round-trip + paged == flat bitwise
    table = identity_page_table(S, L, 16)
    if not np.array_equal(table.reshape(-1), np.arange(S * L // 16)):
        problems.append("identity_page_table is not the identity")
    try:
        identity_page_table(2, 65, 16)
    except ValueError:
        pass
    else:
        problems.append("non-tiling page size did not raise")
    paged = flash_decode_attention(q, kc, vc, pos, dtype=jnp.float32,
                                   page_table=jnp.asarray(table))
    if not np.array_equal(np.asarray(paged), np.asarray(flash)):
        problems.append("paged kernel over the identity table is not "
                        "bitwise the slot-contiguous kernel")

    for p in problems:
        print(f"ops selfcheck: {p}")
    if not problems:
        print("ops selfcheck: impl resolution, grid-skip invariant, "
              "geometry gating, interpreter lowering parity, and paged "
              "round-trip OK")
    return 1 if problems else 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
