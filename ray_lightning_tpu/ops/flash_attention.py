"""Flash attention as a TPU Pallas kernel (forward + custom VJP).

Why a kernel at all: naive attention materializes the [T, T] score
matrix in HBM — at T=8k/bf16 that is 128 MB *per head* of traffic; HBM
bandwidth is the TPU bottleneck (BASELINE.md).  Flash attention streams
K/V blocks through VMEM with an online softmax, so HBM traffic stays
O(T·D) and the MXU stays busy on [block_q × D] @ [D × block_k] tiles.

Block sizes default to 512×512 (measured best on v5e across T=2k-8k:
3.3× over 128×128 at T=4096, and 2.8× over XLA's materialized-scores
attention, which stops compiling at all by T=8192); both are clamped to
the sequence length and halved until they divide it, so any
power-of-two-ish T works.  Causal masking skips fully-masked K blocks at
the grid level (``@pl.when``) — ~2× fewer FLOPs for causal LMs.

The backward pass follows the standard two-kernel flash decomposition
(dK/dV accumulate over Q blocks; dQ accumulates over K blocks) with the
softmax statistics (LSE) and ``delta = rowsum(dO ∘ O)`` carried from the
forward pass.

On non-TPU backends the same kernels run under the Pallas interpreter so
tests execute on CPU (the gloo-for-NCCL analog of the reference's CI,
reference: .github/workflows/test.yaml CPU jobs).

Interface matches ``models.gpt.dot_product_attention``:
``flash_attention(q, k, v, causal=..., dtype=...)`` with q/k/v shaped
``[B, T, H, D]`` and output ``[B, T, H, D]``.
"""

from __future__ import annotations

import functools
import math
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/log NaN-free


def _use_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _pick_block(t: int, preferred: int) -> int:
    b = min(preferred, t)
    while t % b:
        b //= 2
    return max(b, 1)


# -- triangular grid (causal, square blocks) --------------------------------
#
# A causal mask kills every block strictly above the diagonal.  Guarding
# those iterations with ``pl.when`` still pays their block prefetch and
# grid-step overhead (measured: 512-tiles LOSE to one full-T block at
# T=1024 despite skipping 25% of the FLOPs).  Instead, when blocks are
# square, the grid itself enumerates only the nq(nq+1)/2 valid (qi, kb)
# pairs: linear index i walks q-rows in order, kb = 0..qi within a row,
# so output blocks are revisited contiguously (the pipelining
# requirement) and no dead iteration exists at all.


def _tri_row(i):
    """Largest r with r(r+1)/2 <= i.  The float sqrt is only an
    ESTIMATE — TPU's sqrt is not correctly rounded (e.g. i=6 evaluates
    to 2.99999976 there), so the result is corrected with exact integer
    arithmetic; the estimate is within ±1 for any realistic count."""
    f = (jnp.sqrt(8.0 * jnp.float32(i) + 1.0) - 1.0) * 0.5
    r = f.astype(jnp.int32)
    r = jnp.where((r + 1) * (r + 2) // 2 <= i, r + 1, r)
    r = jnp.where(r * (r + 1) // 2 > i, r - 1, r)
    return r


def _tri_decode(i):
    """linear triangular index -> (qi, kb), kb <= qi."""
    qi = _tri_row(i)
    return qi, i - qi * (qi + 1) // 2


def _tri_decode_rev(i, n):
    """linear index -> (ki, qi) covering qi >= ki: group r = n-1-ki has
    r+1 entries (qi descending from n-1), reusing the same triangle."""
    r, c = _tri_decode(i)
    return n - 1 - r, n - 1 - c


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k, nk):
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: K block strictly above the diagonal touches no valid entry
    run = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        # operands stay in their storage dtype (bf16): the MXU takes
        # bf16 inputs with fp32 accumulation via preferred_element_type;
        # upcasting first would quarter matmul throughput.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale      # [bq, bk]
        if causal:
            rows = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0)
                    + qi * block_q)
            cols = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1)
                    + kb * block_k)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_ref[:]                                        # [bq, 128]
        s_max = jnp.max(s, axis=-1, keepdims=True)               # [bq, 1]
        m_new = jnp.maximum(m_prev, s_max)                       # [bq, 128]
        alpha = jnp.exp(m_prev - m_new)                          # [bq, 128]
        p = jnp.exp(s - m_new[:, :1])                            # [bq, bk] f32
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kb == nk - 1)
    def _final():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l)


def _fwd_tri_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                    l_ref, *, sm_scale, block: int):
    """Triangular-grid forward: program_id(1) enumerates only valid
    (qi, kb) pairs; same online-softmax math as _fwd_kernel."""
    qi, kb = _tri_decode(pl.program_id(1))

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    # only the diagonal block straddles the causal boundary; off-diagonal
    # blocks are entirely valid, their mask select folds to a no-op
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
    m_prev = m_ref[:]
    s_max = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, s_max)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_ref[:] = alpha * l_ref[:] + jnp.sum(p, -1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = m_new

    @pl.when(kb == qi)
    def _final():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l)


def _use_tri(causal: bool, bq: int, bk: int, nq: int) -> bool:
    return (causal and bq == bk and nq > 1
            and os.environ.get("RLT_FLASH_TRI", "1") != "0")


def _sub_block(t: int, causal: bool) -> int:
    """Causal staircase sub-block size for the single-block kernels
    (0 = no subtiling).

    A causal single-block kernel that computes the full [T, T] score
    matrix wastes half its MXU work on positions the mask throws away.
    Splitting the q rows into T/sub row-blocks and contracting each only
    against k[:row_end] keeps the staircase of valid blocks and skips
    the rest — at sub = T/4 that is 37.5% of the score-matrix FLOPs,
    with ZERO grid overhead because the loop unrolls statically inside
    the kernel (unlike the round-2 512×512 *grid* tiles, which lost to
    the single block on per-block prefetch + pl.when dead iterations).
    ``RLT_FLASH_SUB`` overrides (0 disables).
    """
    if not causal:
        return 0
    env = os.environ.get("RLT_FLASH_SUB")
    if env:   # empty string falls through to the default (cf. RLT_FLASH_BLOCK_Q)
        try:
            s = int(env)
        except ValueError:
            warnings.warn(
                f"RLT_FLASH_SUB={env!r} is not an integer; using the "
                "default staircase sub-block (set 0 to disable)")
        else:
            return s if s > 0 and t % s == 0 and s < t else 0
    return 256 if t % 256 == 0 and t >= 512 else 0


def _staircase_fold(sm_scale: float) -> bool:
    """Fold sm_scale into q when it is an exact power of two (1/√64 =
    1/8 for the d=64 model family): a [T, D] multiply instead of
    per-row [sub, u] score scaling, exact in bf16 because it only
    shifts the exponent."""
    return math.frexp(sm_scale)[0] == 0.5


def _staircase_slab(qs, k, r0, u, *, sm_scale, fold):
    """Masked fp32 score slab [sub, u] for staircase row-block
    [r0, u): the ONE place the fold/scale/mask recipe lives, shared by
    the forward and backward staircase so they cannot diverge (``qs``
    is pre-scaled iff ``fold``)."""
    s = jax.lax.dot_general(
        qs[r0:u], k[:u], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if not fold:
        s = s * sm_scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (u - r0, u), 0) + r0
    cols = jax.lax.broadcasted_iota(jnp.int32, (u - r0, u), 1)
    return jnp.where(rows >= cols, s, NEG_INF)


def _staircase_fwd_math(q, k, v, *, sm_scale, block, sub):
    """Causal single-block forward over staircase row-blocks.

    Each row-block sees its complete (causally valid) score row, so a
    plain max-shifted softmax applies — no online rescaling.  Returns
    (o fp32 [T, D], lse fp32 [T, 1]).
    """
    fold = _staircase_fold(sm_scale)
    qs = q * sm_scale if fold else q
    n = block // sub
    o_rows, lse_rows = [], []
    for qi in range(n):
        r0, u = qi * sub, (qi + 1) * sub
        s = _staircase_slab(qs, k, r0, u, sm_scale=sm_scale, fold=fold)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o_rows.append(jax.lax.dot_general(
            p.astype(v.dtype), v[:u], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) / l)
        lse_rows.append(m + jnp.log(l))
    return jnp.concatenate(o_rows), jnp.concatenate(lse_rows)


# -- head-packed single-block kernels (transpose-free fast path) ------------
#
# Mosaic requires a block's last dim to be a 128 multiple (or span the
# whole array), so slicing ONE d=64 head out of a [B, T, C] array is not
# expressible.  Packing ``128 // d`` heads into one 128-lane block is:
# the kernel loops over the packed heads with static column slices (the
# loop unrolls at trace time; slices are in-VMEM).  This keeps q/k/v in
# the qkv Dense's native [B, T, C] layout end-to-end — the old
# ``[B,T,H,D] → transpose → [B·H,T,D]`` fold cost ~3.6 ms/step of pure
# data-formatting on the gpt2-small headline (roofline trace).  Engaged
# for the single-block case (T ≤ 1024 by default), where a plain
# max-shifted softmax replaces the online rescaling (whole row visible)
# and ``delta`` is computed in-kernel; longer sequences keep the folded
# multi-block kernels below.


def _head_pack(d: int, h: int) -> int:
    """Heads per 128-lane block (0 = layout not packable)."""
    if d <= 128 and 128 % d == 0:
        pack = 128 // d
    elif d % 128 == 0:
        pack = 1
    else:
        return 0
    return pack if h % pack == 0 else 0


def _fwd_packed_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       *, sm_scale, causal, block, d, pack):
    sub = _sub_block(block, causal)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        q = q_ref[0][:, sl]
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        if sub:
            o, lse = _staircase_fwd_math(q, k, v, sm_scale=sm_scale,
                                         block=block, sub=sub)
            o_ref[0, :, sl] = o.astype(o_ref.dtype)
            lse_ref[0, 0, :, j:j + 1] = lse
            continue
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale      # [T, T]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)                   # [T, 1]
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[0, :, sl] = (acc / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, j:j + 1] = m + jnp.log(l)


def _bwd_packed_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                       dq_ref, dk_ref, dv_ref,
                       *, sm_scale, causal, block, d, pack):
    """Single-block packed backward: one :func:`_single_block_bwd_math`
    call per packed head, with in-kernel delta."""
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        o = o_ref[0][:, sl]
        do = do_ref[0][:, sl]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)                  # [T, 1]
        dq, dk, dv = _single_block_bwd_math(
            q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl], do,
            lse_ref[0, 0][:, j:j + 1], delta,
            sm_scale=sm_scale, causal=causal, block=block)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)
        dk_ref[0, :, sl] = dk.astype(dk_ref.dtype)
        dq_ref[0, :, sl] = dq.astype(dq_ref.dtype)


def _fwd_tri_packed_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                           acc_ref, m_ref, l_ref,
                           *, sm_scale, block, d, pack):
    """Triangular-grid forward on head-packed [B, T, C] blocks: the
    online-softmax math of ``_fwd_tri_kernel`` looped over the packed
    heads, with per-head scratch planes (``acc_ref[j]`` etc.)."""
    qi, kb = _tri_decode(pl.program_id(1))

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        q = q_ref[0][:, sl]
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
        m_prev = m_ref[j]
        s_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, s_max)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_ref[j] = alpha * l_ref[j] + jnp.sum(p, -1, keepdims=True)
        acc_ref[j] = acc_ref[j] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[j] = m_new

    @pl.when(kb == qi)
    def _final():
        for j in range(pack):
            sl = slice(j * d, (j + 1) * d)
            l = l_ref[j][:, :1]
            o_ref[0, :, sl] = (acc_ref[j] / l).astype(o_ref.dtype)
            lse_ref[0, 0, :, j:j + 1] = m_ref[j][:, :1] + jnp.log(l)


def _fwd_tri_packed(q, k, v, h, sm_scale, bq, nq, interpret):
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    n_tri = nq * (nq + 1) // 2
    kernel = functools.partial(_fwd_tri_packed_kernel, sm_scale=sm_scale,
                               block=bq, d=d, pack=pack)

    def q_map(g, i):
        return (g // g2, _tri_decode(i)[0], g % g2)

    def k_map(g, i):
        return (g // g2, _tri_decode(i)[1], g % g2)

    def r_map(g, i):
        return (g // g2, g % g2, _tri_decode(i)[0], 0)

    o, lse = pl.pallas_call(
        kernel,
        grid=(b * g2, n_tri),
        in_specs=[
            pl.BlockSpec((1, bq, w), q_map),
            pl.BlockSpec((1, bq, w), k_map),
            pl.BlockSpec((1, bq, w), k_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, w), q_map),
            pl.BlockSpec((1, 1, bq, pack), r_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, g2, t, pack), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((pack, bq, d), jnp.float32),
            pltpu.VMEM((pack, bq, 128), jnp.float32),
            pltpu.VMEM((pack, bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _fwd_packed(q, k, v, h, causal, sm_scale, interpret):
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    kernel = functools.partial(_fwd_packed_kernel, sm_scale=sm_scale,
                               causal=causal, block=t, d=d, pack=pack)
    x_spec = pl.BlockSpec((1, t, w), lambda g: (g // g2, 0, g % g2))
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * g2,),
        in_specs=[x_spec, x_spec, x_spec],
        out_specs=[
            x_spec,
            pl.BlockSpec((1, 1, t, pack), lambda g: (g // g2, g % g2, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, g2, t, pack), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_dkdv_tri_packed_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                                *, sm_scale, block, d, pack, n):
    """Triangular dk/dv on head-packed blocks (``_bwd_dkdv_tri_kernel``
    math looped over packed heads; per-head scratch planes)."""
    ki, qi = _tri_decode_rev(pl.program_id(1), n)

    @pl.when(qi == n - 1)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        q = q_ref[0][:, sl]
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        lse = lse_ref[0, 0][:, j:j + 1]
        delta = delta_ref[0, 0][:, j:j + 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where((qi == ki) & (rows < cols), NEG_INF, s)
        p = jnp.exp(s - lse)
        dv_acc[j] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[j] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == ki)
    def _final():
        for j in range(pack):
            sl = slice(j * d, (j + 1) * d)
            dk_ref[0, :, sl] = (dk_acc[j] * sm_scale).astype(dk_ref.dtype)
            dv_ref[0, :, sl] = dv_acc[j].astype(dv_ref.dtype)


def _bwd_dq_tri_packed_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, dq_ref, dq_acc,
                              *, sm_scale, block, d, pack):
    qi, kb = _tri_decode(pl.program_id(1))

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        q = q_ref[0][:, sl]
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        lse = lse_ref[0, 0][:, j:j + 1]
        delta = delta_ref[0, 0][:, j:j + 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[j] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == qi)
    def _final():
        for j in range(pack):
            sl = slice(j * d, (j + 1) * d)
            dq_ref[0, :, sl] = (dq_acc[j] * sm_scale).astype(dq_ref.dtype)


def _bwd_tri_packed(q, k, v, h, lse, do, delta, sm_scale, bq, nq,
                    interpret):
    """Head-packed triangular backward on [B, T, C]; ``delta`` arrives
    in the packed lse layout [B, H/pack, T, pack]."""
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    n_tri = nq * (nq + 1) // 2

    def ki_map(g, i):
        return (g // g2, _tri_decode_rev(i, nq)[0], g % g2)

    def qi_rev_map(g, i):
        return (g // g2, _tri_decode_rev(i, nq)[1], g % g2)

    def r_rev_map(g, i):
        return (g // g2, g % g2, _tri_decode_rev(i, nq)[1], 0)

    dkdv = functools.partial(_bwd_dkdv_tri_packed_kernel,
                             sm_scale=sm_scale, block=bq, d=d, pack=pack,
                             n=nq)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(b * g2, n_tri),
        in_specs=[
            pl.BlockSpec((1, bq, w), qi_rev_map),               # q
            pl.BlockSpec((1, bq, w), ki_map),                   # k
            pl.BlockSpec((1, bq, w), ki_map),                   # v
            pl.BlockSpec((1, bq, w), qi_rev_map),               # do
            pl.BlockSpec((1, 1, bq, pack), r_rev_map),          # lse
            pl.BlockSpec((1, 1, bq, pack), r_rev_map),          # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bq, w), ki_map),
            pl.BlockSpec((1, bq, w), ki_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), k.dtype),
            jax.ShapeDtypeStruct((b, t, c), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((pack, bq, d), jnp.float32),
            pltpu.VMEM((pack, bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    def q_map(g, i):
        return (g // g2, _tri_decode(i)[0], g % g2)

    def k_map(g, i):
        return (g // g2, _tri_decode(i)[1], g % g2)

    def r_map(g, i):
        return (g // g2, g % g2, _tri_decode(i)[0], 0)

    dqk = functools.partial(_bwd_dq_tri_packed_kernel, sm_scale=sm_scale,
                            block=bq, d=d, pack=pack)
    dq = pl.pallas_call(
        dqk,
        grid=(b * g2, n_tri),
        in_specs=[
            pl.BlockSpec((1, bq, w), q_map),
            pl.BlockSpec((1, bq, w), k_map),
            pl.BlockSpec((1, bq, w), k_map),
            pl.BlockSpec((1, bq, w), q_map),
            pl.BlockSpec((1, 1, bq, pack), r_map),
            pl.BlockSpec((1, 1, bq, pack), r_map),
        ],
        out_specs=pl.BlockSpec((1, bq, w), q_map),
        out_shape=jax.ShapeDtypeStruct((b, t, c), q.dtype),
        scratch_shapes=[pltpu.VMEM((pack, bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- row-resident kernels (multi-block causal fwd + fused backward) ---------
#
# The two-kernel tri decomposition recomputes s and dp in the dQ kernel
# — 7 MXU passes over the triangle where 5 suffice (the same waste the
# single-block fused kernel eliminated at T<=1024).  One kernel cannot
# walk the (qi, kb) triangle AND finalize both dq (row-complete) and
# dk/dv (column-complete) under Pallas's contiguous-revisiting rule for
# output blocks — so this kernel changes the residency instead: the
# grid walks ROWS only; k and v stay resident in VMEM for the whole
# batch·head-group (loaded once instead of once per triangle block),
# an inner ``fori_loop`` with a DYNAMIC trip count (qi+1) walks the
# causal columns (no dead iterations, no per-block prefetch), dq
# finalizes per row step, and dk/dv accumulate in fp32 VMEM scratch
# via dynamic-slice read-modify-write, emitted once at the last row.
# Engagement differs by direction (``RLT_FLASH_ROWRES=0`` opts out of
# both): the FORWARD (online softmax in registers, no big scratch)
# wins up to T=8192 (−15%/−16% at 4096/8192); the BACKWARD, whose
# fp32 [T,128] dk/dv accumulators weigh on the scoped-VMEM budget,
# caps at T=2048 (−28% whole fwd+bwd there with both kernels) — at
# 4096 its 512-tiles overflow scoped VMEM by ~0.5 MB and 256-tiles
# underfeed the MXU (24.3 vs 19.5 ms/iter), so longer sequences pair
# the rowres forward with the grid-tri backward.


def _use_row_resident(t: int, w: int = 128) -> bool:
    """Backward engagement: the fp32 [T, w] dk/dv accumulators plus the
    resident k/v scale with t·w, so the budget is the measured t=2048
    point AT w=128 — wide heads (d ≥ 256 pack to w=d) hit the same
    VMEM ceiling at proportionally shorter t."""
    return t * w <= 2048 * 128 \
        and os.environ.get("RLT_FLASH_ROWRES", "1") != "0"


def _use_row_resident_fwd(t: int, w: int = 128) -> bool:
    """The forward kernel carries no fp32 [T,128] accumulators (online
    softmax lives in registers), so its VMEM budget stretches to
    T=8192 (measured −15%/−16% at 4096/8192 vs the grid-tri forward;
    k/v residency is the win — loaded once per batch·head-group).
    The resident k/v are [T, w] each, so the budget caps t·w at the
    measured w=128 point rather than t alone."""
    return t * w <= 8192 * 128 \
        and os.environ.get("RLT_FLASH_ROWRES", "1") != "0"


def _fwd_rowres_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       *, sm_scale, bq, d, pack, fold):
    """Row-resident forward: k/v resident in VMEM, inner fori over the
    causal columns with the online softmax carried in registers."""
    qi = pl.program_id(1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bq), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bq), 1)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        qj = q_ref[0][:, sl]
        if fold:
            qj = qj * sm_scale

        def col(kb, carry, qj=qj, sl=sl):
            m, l, acc = carry
            kt = k_ref[0, pl.ds(kb * bq, bq), sl]
            vt = v_ref[0, pl.ds(kb * bq, bq), sl]
            s = jax.lax.dot_general(
                qj, kt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if not fold:
                s = s * sm_scale
            s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p.astype(vt.dtype), vt, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l, acc

        m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq, 1), jnp.float32)
        a0 = jnp.zeros((bq, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, qi + 1, col, (m0, l0, a0))
        o_ref[0, :, sl] = (acc / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, j:j + 1] = m + jnp.log(l)


def _fwd_rowres(q, k, v, h, sm_scale, bq, nq, interpret):
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    fold = _staircase_fold(sm_scale)

    def row_map(g, i):
        return (g // g2, i, g % g2)

    def full_map(g, i):
        return (g // g2, 0, g % g2)

    def r_map(g, i):
        return (g // g2, g % g2, i, 0)

    kernel = functools.partial(_fwd_rowres_kernel, sm_scale=sm_scale,
                               bq=bq, d=d, pack=pack, fold=fold)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * g2, nq),
        in_specs=[
            pl.BlockSpec((1, bq, w), row_map),
            pl.BlockSpec((1, t, w), full_map),
            pl.BlockSpec((1, t, w), full_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, w), row_map),
            pl.BlockSpec((1, 1, bq, pack), r_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, g2, t, pack), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_rowres_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                       dq_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                       *, sm_scale, bq, nq, d, pack, fold):
    qi = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bq), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bq), 1)
    for j in range(pack):
        sl = slice(j * d, (j + 1) * d)
        qj = q_ref[0][:, sl]
        if fold:
            qj = qj * sm_scale
        doj = do_ref[0][:, sl]
        lsej = lse_ref[0, 0][:, j:j + 1]
        deltaj = delta_ref[0, 0][:, j:j + 1]

        def col(kb, dq_j, qj=qj, doj=doj, lsej=lsej, deltaj=deltaj,
                sl=sl):
            kt = k_ref[0, pl.ds(kb * bq, bq), sl]
            vt = v_ref[0, pl.ds(kb * bq, bq), sl]
            s = jax.lax.dot_general(
                qj, kt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if not fold:
                s = s * sm_scale
            s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
            p = jnp.exp(s - lsej)
            dv_acc[pl.ds(kb * bq, bq), sl] += jax.lax.dot_general(
                p.astype(doj.dtype), doj, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                doj, vt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - deltaj)
            dsc = ds.astype(qj.dtype)
            dk_acc[pl.ds(kb * bq, bq), sl] += jax.lax.dot_general(
                dsc, qj, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dq_j + jax.lax.dot_general(
                dsc, kt, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        dq_j = jax.lax.fori_loop(
            0, qi + 1, col, jnp.zeros((bq, d), jnp.float32))
        dq_ref[0, :, sl] = (dq_j * sm_scale).astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _emit():
        dk = dk_acc[...] if fold else dk_acc[...] * sm_scale
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_rowres(q, k, v, h, lse, do, delta, sm_scale, bq, nq, interpret):
    """Row-resident fused backward on head-packed [B, T, C] (delta in
    the packed lse layout, as :func:`_bwd_tri_packed`)."""
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    fold = _staircase_fold(sm_scale)

    def row_map(g, i):
        return (g // g2, i, g % g2)

    def full_map(g, i):
        return (g // g2, 0, g % g2)

    def r_map(g, i):
        return (g // g2, g % g2, i, 0)

    kernel = functools.partial(_bwd_rowres_kernel, sm_scale=sm_scale,
                               bq=bq, nq=nq, d=d, pack=pack, fold=fold)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * g2, nq),
        in_specs=[
            pl.BlockSpec((1, bq, w), row_map),                  # q
            pl.BlockSpec((1, bq, w), row_map),                  # do
            pl.BlockSpec((1, 1, bq, pack), r_map),              # lse
            pl.BlockSpec((1, 1, bq, pack), r_map),              # delta
            pl.BlockSpec((1, t, w), full_map),                  # k resident
            pl.BlockSpec((1, t, w), full_map),                  # v resident
        ],
        out_specs=[
            pl.BlockSpec((1, bq, w), row_map),                  # dq per row
            pl.BlockSpec((1, t, w), full_map),                  # dk
            pl.BlockSpec((1, t, w), full_map),                  # dv
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, t, c), k.dtype),
            jax.ShapeDtypeStruct((b, t, c), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((t, w), jnp.float32),                    # dk acc
            pltpu.VMEM((t, w), jnp.float32),                    # dv acc
        ],
        interpret=interpret,
    )(q, do, lse, delta, k, v)
    return dq, dk, dv


def _bwd_packed(q, k, v, h, o, lse, do, causal, sm_scale, interpret):
    b, t, c = q.shape
    d = c // h
    pack = _head_pack(d, h)
    g2 = h // pack
    w = pack * d
    kernel = functools.partial(_bwd_packed_kernel, sm_scale=sm_scale,
                               causal=causal, block=t, d=d, pack=pack)
    x_spec = pl.BlockSpec((1, t, w), lambda g: (g // g2, 0, g % g2))
    r_spec = pl.BlockSpec((1, 1, t, pack), lambda g: (g // g2, g % g2, 0, 0))
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * g2,),
        in_specs=[x_spec, x_spec, x_spec, x_spec, x_spec, r_spec],
        out_specs=[x_spec, x_spec, x_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, c), q.dtype),
            jax.ShapeDtypeStruct((b, t, c), k.dtype),
            jax.ShapeDtypeStruct((b, t, c), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, o, do, lse)
    return dq, dk, dv


def _fold(x, b, t, h, d):
    """[B, T, H·D] → [B·H, T, D] (the multi-block kernels' layout)."""
    return x.reshape(b, t, h, d).transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x, b, t, h, d):
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3).reshape(b, t, h * d)


def _fwd(q, k, v, h, causal, sm_scale, block_q, block_k, interpret):
    """Core forward on head-packed [B, T, C] arrays.

    Single-block shapes take the transpose-free packed path; longer
    sequences fold to [B·H, T, D] for the tiled/triangular kernels.
    Returns ``(o[B,T,C], lse)`` where lse's layout depends on the path
    taken (packed: [B, H/pack, T, pack]; folded: [B·H, T, 1]) — the
    matching ``_bwd`` branch consumes it.
    """
    b, t, c = q.shape
    d = c // h
    bh = b * h
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    nq, nk = t // bq, t // bk

    pack = _head_pack(d, h)
    if nq == 1 and nk == 1 and pack:
        return _fwd_packed(q, k, v, h, causal, sm_scale, interpret)

    if _use_tri(causal, bq, bk, nq) and pack:
        if _use_row_resident_fwd(t, pack * d):
            return _fwd_rowres(q, k, v, h, sm_scale, bq, nq, interpret)
        return _fwd_tri_packed(q, k, v, h, sm_scale, bq, nq, interpret)

    q, k, v = (_fold(x, b, t, h, d) for x in (q, k, v))

    if _use_tri(causal, bq, bk, nq):
        n_tri = nq * (nq + 1) // 2
        kernel = functools.partial(_fwd_tri_kernel, sm_scale=sm_scale,
                                   block=bq)

        def q_map(g, i):
            return (g, _tri_decode(i)[0], 0)

        def k_map(g, i):
            return (g, _tri_decode(i)[1], 0)

        o, lse = pl.pallas_call(
            kernel,
            grid=(bh, n_tri),
            in_specs=[
                pl.BlockSpec((1, bq, d), q_map),
                pl.BlockSpec((1, bk, d), k_map),
                pl.BlockSpec((1, bk, d), k_map),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, d), q_map),
                pl.BlockSpec((1, bq, 1), q_map),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
        return _unfold(o, b, t, h, d), lse

    grid = (bh, nq, nk)

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=bq, block_k=bk, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),      # output accumulator
            pltpu.VMEM((bq, 128), jnp.float32),    # running max
            pltpu.VMEM((bq, 128), jnp.float32),    # running sum
        ],
        interpret=interpret,
    )(q, k, v)
    return _unfold(o, b, t, h, d), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc,
                     *, sm_scale, causal, block_q, block_k, nq):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        # bf16 matmul operands + fp32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                         # [bq, 1]
        delta = delta_ref[0]                                     # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale      # [bq, bk]
        if causal:
            rows = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0)
                    + qi * block_q)
            cols = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1)
                    + ki * block_k)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                     # [bq, bk] f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [bq, bk]
        ds = p * (dp - delta)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [bk, d]

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0] = (dk_acc[:] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, sm_scale, causal, block_q, block_k, nk):
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        # bf16 matmul operands + fp32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0)
                    + qi * block_q)
            cols = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1)
                    + kb * block_k)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [bq, d]

    @pl.when(kb == nk - 1)
    def _final():
        dq_ref[0] = (dq_acc[:] * sm_scale).astype(dq_ref.dtype)


def _single_block_bwd_math(q, k, v, do, lse, delta, *, sm_scale, causal,
                           block):
    """Shared 5-matmul single-block backward: the one place the dq/dk/dv
    math lives, used by both the folded fused kernel and the head-packed
    kernel (one call per packed head) so the two paths cannot diverge.
    Returns fp32 (dq, dk, dv) tiles; callers cast to storage dtype.

    The two-kernel decomposition exists because dK/dV and dQ accumulate
    over different grid axes — but with nq == nk == 1 there is nothing
    to accumulate, and splitting costs two extra [T,T] matmuls per head
    (s and dp recomputed in the dQ kernel): 7 MXU passes where 5
    suffice.  At the T=1024 headline that is ~29% of the backward FLOPs
    for free.  Same math, same dtypes, same order as the split kernels.

    Causal blocks additionally take the staircase path (:func:`_sub_block`):
    row-blocks of q contract only against k[:row_end], skipping the MXU
    work the mask would zero — 37.5% of the [T,T]-matmul FLOPs at
    sub = T/4, statically unrolled so there is no grid overhead.
    """
    sub = _sub_block(block, causal)
    if sub:
        return _staircase_bwd_math(q, k, v, do, lse, delta,
                                   sm_scale=sm_scale, block=block, sub=sub)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale          # [T, T]
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jnp.exp(s - lse)                                         # [T, T] f32
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dsc = ds.astype(q.dtype)
    dk = jax.lax.dot_general(
        dsc, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    dq = jax.lax.dot_general(
        dsc, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    return dq, dk, dv


def _staircase_bwd_math(q, k, v, do, lse, delta, *, sm_scale, block, sub):
    """Causal single-block backward over staircase row-blocks.

    Row-block qi computes its [sub, u] score slab (u = row_end) and the
    five matmuls of :func:`_single_block_bwd_math` restricted to it;
    dq rows finalize per row-block, dk/dv accumulate into fp32 [T, D]
    buffers via static-slice adds.  ``sm_scale`` folds into q when it
    is an exact power of two (s and dk then come pre-scaled: dk =
    dSᵀ·(α·q)); dq post-scales its [sub, D] output either way — cheaper
    than scaling [sub, u] score slabs.
    """
    fold = _staircase_fold(sm_scale)
    qs = q * sm_scale if fold else q
    n = block // sub
    dq_rows = []
    # per-column-block accumulators (static slices only: Pallas kernels
    # cannot scatter into traced arrays)
    dk_blocks: list = [None] * n
    dv_blocks: list = [None] * n
    for qi in range(n):
        r0, u = qi * sub, (qi + 1) * sub
        qr = qs[r0:u]
        dor = do[r0:u]
        s = _staircase_slab(qs, k, r0, u, sm_scale=sm_scale, fold=fold)
        p = jnp.exp(s - lse[r0:u])
        dv_c = jax.lax.dot_general(
            p.astype(dor.dtype), dor, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [u, d]
        dp = jax.lax.dot_general(
            dor, v[:u], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[r0:u])
        dsc = ds.astype(q.dtype)
        dk_c = jax.lax.dot_general(
            dsc, qr, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [u, d]
        dq_rows.append(jax.lax.dot_general(
            dsc, k[:u], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale)
        for kb in range(qi + 1):
            c = slice(kb * sub, (kb + 1) * sub)
            dk_blocks[kb] = dk_c[c] if dk_blocks[kb] is None \
                else dk_blocks[kb] + dk_c[c]
            dv_blocks[kb] = dv_c[c] if dv_blocks[kb] is None \
                else dv_blocks[kb] + dv_c[c]
    dk = jnp.concatenate(dk_blocks)
    if not fold:
        dk = dk * sm_scale
    return jnp.concatenate(dq_rows), dk, jnp.concatenate(dv_blocks)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, sm_scale, causal, block):
    """One-pass single-block backward on folded [B·H, T, D] tiles
    (see :func:`_single_block_bwd_math`)."""
    dq, dk, dv = _single_block_bwd_math(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0], delta_ref[0],
        sm_scale=sm_scale, causal=causal, block=block)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_fused(q, k, v, lse, do, delta, causal, sm_scale, interpret):
    """Single-block backward on folded [B·H, T, D] (when the packed
    layout does not apply): grid over batch·heads only."""
    bh, t, d = q.shape
    kernel = functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                               causal=causal, block=t)
    x_spec = pl.BlockSpec((1, t, d), lambda g: (g, 0, 0))
    r_spec = pl.BlockSpec((1, t, 1), lambda g: (g, 0, 0))
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(bh,),
        in_specs=[x_spec, x_spec, x_spec, x_spec, r_spec, r_spec],
        out_specs=[x_spec, x_spec, x_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _bwd_dkdv_tri_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, dk_acc, dv_acc,
                         *, sm_scale, block: int, n: int):
    """Triangular dk/dv: the grid walks k-rows, each visiting only the
    q blocks at-or-below… i.e. qi >= ki (the transposed lower triangle),
    qi descending within a k-row so the row's iterations are contiguous
    (output-block revisiting requirement)."""
    ki, qi = _tri_decode_rev(pl.program_id(1), n)

    @pl.when(qi == n - 1)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    s = jnp.where((qi == ki) & (rows < cols), NEG_INF, s)
    p = jnp.exp(s - lse)
    dv_acc[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk_acc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(qi == ki)
    def _final():
        dk_ref[0] = (dk_acc[:] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_tri_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dq_acc, *, sm_scale, block: int):
    qi, kb = _tri_decode(pl.program_id(1))

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    s = jnp.where((kb == qi) & (rows < cols), NEG_INF, s)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kb == qi)
    def _final():
        dq_ref[0] = (dq_acc[:] * sm_scale).astype(dq_ref.dtype)


def _bwd_tri(q, k, v, o, lse, do, sm_scale, bq, nq, delta, interpret):
    bh, t, d = q.shape
    n_tri = nq * (nq + 1) // 2

    def ki_map(g, i):
        return (g, _tri_decode_rev(i, nq)[0], 0)

    def qi_rev_map(g, i):
        return (g, _tri_decode_rev(i, nq)[1], 0)

    dkdv = functools.partial(_bwd_dkdv_tri_kernel, sm_scale=sm_scale,
                             block=bq, n=nq)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, n_tri),
        in_specs=[
            pl.BlockSpec((1, bq, d), qi_rev_map),               # q
            pl.BlockSpec((1, bq, d), ki_map),                   # k
            pl.BlockSpec((1, bq, d), ki_map),                   # v
            pl.BlockSpec((1, bq, d), qi_rev_map),               # do
            pl.BlockSpec((1, bq, 1), qi_rev_map),               # lse
            pl.BlockSpec((1, bq, 1), qi_rev_map),               # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), ki_map),
            pl.BlockSpec((1, bq, d), ki_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    def q_map(g, i):
        return (g, _tri_decode(i)[0], 0)

    def k_map(g, i):
        return (g, _tri_decode(i)[1], 0)

    dqk = functools.partial(_bwd_dq_tri_kernel, sm_scale=sm_scale, block=bq)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, n_tri),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_map),
            pl.BlockSpec((1, bq, d), k_map),
            pl.BlockSpec((1, bq, d), k_map),
            pl.BlockSpec((1, bq, d), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
        ],
        out_specs=pl.BlockSpec((1, bq, d), q_map),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _bwd(q, k, v, h, o, lse, do, causal, sm_scale, block_q, block_k,
         interpret):
    """Backward on head-packed [B, T, C]; must mirror ``_fwd``'s branch
    (the packed path's residuals carry a [B, H/pack, T, pack] lse)."""
    b, t, c = q.shape
    d = c // h
    bh = b * h
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    nq, nk = t // bq, t // bk

    if nq == 1 and nk == 1 and _head_pack(d, h):
        return _bwd_packed(q, k, v, h, o, lse, do, causal, sm_scale,
                           interpret)

    if _use_tri(causal, bq, bk, nq) and _head_pack(d, h):
        # per-head delta in the packed lse layout [B, H/pack, T, pack]
        pack = _head_pack(d, h)
        delta = jnp.sum((do.astype(jnp.float32)
                         * o.astype(jnp.float32)).reshape(b, t, h, d),
                        axis=-1)
        delta = delta.reshape(b, t, h // pack, pack).transpose(0, 2, 1, 3)
        if _use_row_resident(t, pack * d):
            return _bwd_rowres(q, k, v, h, lse, do, delta, sm_scale,
                               bq, nq, interpret)
        return _bwd_tri_packed(q, k, v, h, lse, do, delta, sm_scale, bq,
                               nq, interpret)

    q, k, v, o, do = (_fold(x, b, t, h, d) for x in (q, k, v, o, do))

    # delta_i = Σ_d dO_id · O_id — tiny elementwise+reduce; XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                      # [bh, t, 1]

    if nq == 1 and nk == 1:
        dq, dk, dv = _bwd_fused(q, k, v, lse, do, delta, causal, sm_scale,
                                interpret)
    elif _use_tri(causal, bq, bk, nq):
        dq, dk, dv = _bwd_tri(q, k, v, o, lse, do, sm_scale, bq, nq, delta,
                              interpret)
    else:
        q_spec = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, j, 0))
        r_spec = pl.BlockSpec((1, bq, 1), lambda g, i, j: (g, j, 0))
        k_by_i = pl.BlockSpec((1, bk, d), lambda g, i, j: (g, i, 0))
        dkdv = functools.partial(_bwd_dkdv_kernel, sm_scale=sm_scale,
                                 causal=causal, block_q=bq, block_k=bk,
                                 nq=nq)
        dk, dv = pl.pallas_call(
            dkdv,
            grid=(bh, nk, nq),
            in_specs=[
                q_spec,                                          # q by qi=j
                k_by_i,                                          # k by ki
                k_by_i,                                          # v by ki
                q_spec,                                          # do
                r_spec,                                          # lse
                r_spec,                                          # delta
            ],
            out_specs=[k_by_i, k_by_i],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                jax.ShapeDtypeStruct((bh, t, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, do, lse, delta)

        dqk = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                causal=causal, block_q=bq, block_k=bk,
                                nk=nk)
        qi_spec = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0))
        ri_spec = pl.BlockSpec((1, bq, 1), lambda g, i, j: (g, i, 0))
        k_by_j = pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0))
        dq = pl.pallas_call(
            dqk,
            grid=(bh, nq, nk),
            in_specs=[
                qi_spec,
                k_by_j,
                k_by_j,
                qi_spec,
                ri_spec,
                ri_spec,
            ],
            out_specs=qi_spec,
            out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
    return tuple(_unfold(x, b, t, h, d) for x in (dq, dk, dv))


# ---------------------------------------------------------------------------
# custom-vjp wrapper on head-packed [B, T, C]
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, h, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, h, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, h, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, h, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(h, causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _bwd(q, k, v, h, o, lse, g, causal, sm_scale, block_q, block_k,
                interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, dtype=jnp.bfloat16,
                    sm_scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None):
    """Flash attention over ``[B, T, H, D]`` tensors (BTHD in, BTHD out).

    Drop-in for :func:`~ray_lightning_tpu.models.gpt.dot_product_attention`
    (same scaling 1/√D, same causal semantics); differentiable via the
    Pallas backward kernels above.

    Default block sizes adapt to T: sequences up to 1024 use one full-T
    block per grid row (no inner-loop grid overhead — measured +7%
    whole-model step rate at T=1024 on v5e vs fixed 512); longer
    sequences keep 512×512 tiles, whose VMEM footprint stays safe as T
    grows.

    Note: under a multi-device ``pjit`` program, call this inside
    ``shard_map`` (the batch/head grid is per-device); single-device jit
    works directly.  ``parallel/ring.py`` composes it with sequence
    parallelism.
    """
    b, t, h, d = q.shape
    # RLT_FLASH_BLOCK_Q/K override the heuristic (the sweep knob used to
    # tune per-shape defaults; also a user escape hatch)
    if block_q is None:
        env_q = os.environ.get("RLT_FLASH_BLOCK_Q")
        block_q = int(env_q) if env_q else (t if t <= 1024 else 512)
    if block_k is None:
        env_k = os.environ.get("RLT_FLASH_BLOCK_K")
        block_k = int(env_k) if env_k else (t if t <= 1024 else 512)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _use_interpret()
    # [B, T, H, D] → head-packed [B, T, C]: a FREE reshape (it is the
    # qkv Dense output layout); the kernels' index maps slice each
    # head's C columns, so no transpose ever hits HBM
    o = _flash(q.reshape(b, t, h * d), k.reshape(b, t, h * d),
               v.reshape(b, t, h * d), h, causal, sm_scale, block_q,
               block_k, interpret)
    return o.reshape(b, t, h, d).astype(dtype)
