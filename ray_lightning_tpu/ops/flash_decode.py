"""Flash-decode: the per-token serve hot path as a TPU Pallas kernel.

``cached_attention`` (ops/attention.py) is a masked dense einsum: every
decoded token reads ALL ``[S, L, H, D]`` cache rows and materializes
``[S, H, 1, L]`` fp32 scores, however short each slot's live context is.
Decode is bandwidth-bound — one query token against L cache rows — so
the win is not FLOPs, it is *bytes not read*.  This kernel:

- splits the KV cache into ``block_k``-row blocks on a ``(slot, kv
  block)`` grid with an **online softmax** (running max ``m``, running
  sum ``l``, rescaled accumulator ``acc`` in VMEM scratch, exactly the
  flash forward decomposition of ops/flash_attention.py) and a final
  combine at the last block;
- is **length-aware**: ``positions`` rides the grid as a scalar-prefetch
  operand (SMEM), so both the compute guard (``@pl.when``) AND the
  BlockSpec index_map see each slot's bound.  The index_map *clamps*
  dead blocks to the last live block — Pallas skips the DMA for a block
  whose mapped index is unchanged from the previous grid step, so a slot
  at position p reads ``ceil((p+1)/block_k)`` KV blocks, not ``L/block_k``;
- has a **paged** variant whose KV index_map walks a page table
  (``serve/fleet/pages.py identity_page_table``): the cache is viewed as
  ``[S*pages_per_slot, page_size, C]`` physical pages and block ``p`` of
  slot ``s`` fetches physical page ``table[s, p]``.  Today's table is the
  identity (the device cache is slot-contiguous); the kernel contract is
  already the indirect one, so physical page sharing only changes the
  table.

Heads are packed on the lane axis (``C = H*D``) and looped in-kernel
with static column slices, mirroring the packed flash kernels.  On
non-TPU backends everything runs under the Pallas interpreter so the
tier-1 suite executes the real kernel on CPU.

Numerics: fp32 softmax statistics, ``NEG_INF = -1e30`` masking (NaN-free
under exp, ops/flash_attention.py idiom), output in the caller's compute
dtype — parity with the dense einsum within the documented bf16 2e-2 bar
(tests/test_ops.py decode-parity tier).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_lightning_tpu.ops.flash_attention import NEG_INF, _use_interpret

VALID_DECODE_IMPLS = ("auto", "dense", "flash_decode", "paged")

#: stable op-name tag: pallas custom-calls carry the kernel function
#: name, and telemetry/anatomy.py buckets "flash"/"pallas"/"custom-call"
#: names into compute (never collectives — comm/audit.py guard)
_KERNEL_NAME = "flash_decode_kernel"


def resolve_decode_impl(value=None) -> str:
    """Decode attention impl: explicit value > ``RLT_DECODE_IMPL`` env >
    ``auto`` (TPU → flash_decode, like ``auto_attention``; elsewhere the
    dense einsum stays the default so CPU serving is untouched unless a
    caller opts in)."""
    v = (value or os.environ.get("RLT_DECODE_IMPL") or "auto").lower()
    if v not in VALID_DECODE_IMPLS:
        raise ValueError(
            f"RLT_DECODE_IMPL must be one of {VALID_DECODE_IMPLS}, "
            f"got {v!r}")
    if v == "auto":
        return ("flash_decode"
                if jax.devices()[0].platform == "tpu" else "dense")
    return v


def kv_block_bound(kb: int, pos, block_k: int):
    """The length-aware index_map clamp: the KV block index block ``kb``
    actually fetches for a slot at position ``pos``.  Blocks past the
    slot's bound re-map to the last live block (``pos // block_k``) —
    an unchanged mapped index between sequential grid steps means Pallas
    skips the block's DMA, which is the measured traffic saving.
    Consistent with the compute guard: ``kb * block_k <= pos`` iff
    ``kb <= pos // block_k`` (integer division)."""
    return jnp.minimum(kb, pos // block_k)


def decode_kernel_supported(L: int, H: int, D: int, *,
                            block_k: int, dtype) -> bool:
    """Whether the kernel path can lower for this cache geometry.  The
    interpreter (non-TPU) takes anything; on TPU the packed lane axis
    ``C = H*D`` must be a 128-lane multiple and blocks must tile L."""
    C = H * D
    if L % block_k:
        return False
    if _use_interpret():
        return True
    sub = 16 if dtype == jnp.bfloat16 else 8
    return C % 128 == 0 and block_k % sub == 0


def _pick_block_k(L: int) -> int:
    b = min(int(os.environ.get("RLT_DECODE_BLOCK_K", "128") or 128), L)
    while L % b:
        b //= 2
    return max(b, 1)


def _decode_body(pos, kb, nk, logical_base,
                 q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                 *, sm_scale, block_k, n_head, head_dim):
    """Online-softmax update for one ``block_k``-row KV block of one
    slot, looped over the packed heads.  ``logical_base`` is the block's
    first LOGICAL cache row (page-table indirection moves only the
    physical fetch; masking is always in logical positions)."""

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(kb * block_k <= pos)
    def _compute():
        rows = (jax.lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
                + logical_base)
        valid = rows <= pos
        for h in range(n_head):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            q = q_ref[0, :, sl]                       # [1, D]
            k = k_ref[0, :, sl]                       # [block_k, D]
            v = v_ref[0, :, sl]                       # [block_k, D]
            s = jax.lax.dot_general(
                k, q, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale  # [bk, 1]
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[h, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s))
            alpha = jnp.exp(m_prev - m_new)           # [1]
            p = jnp.exp(s - m_new[0])                 # [bk, 1]
            l_ref[h, :] = alpha[0] * l_ref[h, :]
            l_ref[h, :1] = l_ref[h, :1] + jnp.sum(p)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)   # [1, D]
            acc_ref[h, :] = alpha[0] * acc_ref[h, :] + pv[0]
            m_ref[h, :] = jnp.full_like(m_ref[h, :], m_new[0])

    @pl.when(kb == nk - 1)
    def _final():
        for h in range(n_head):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            # l > 0 always: logical row 0 satisfies ``0 <= pos`` for any
            # non-negative position, so at least one key is live
            o_ref[0, :, sl] = (acc_ref[h, :] / l_ref[h, 0])[None, :] \
                .astype(o_ref.dtype)


def flash_decode_kernel(positions_ref, q_ref, k_ref, v_ref, o_ref,
                        m_ref, l_ref, acc_ref, **kw):
    s, kb = pl.program_id(0), pl.program_id(1)
    _decode_body(positions_ref[s], kb, pl.num_programs(1),
                 kb * kw["block_k"], q_ref, k_ref, v_ref, o_ref,
                 m_ref, l_ref, acc_ref, **kw)


def flash_decode_paged_kernel(positions_ref, table_ref, q_ref, k_ref,
                              v_ref, o_ref, m_ref, l_ref, acc_ref, **kw):
    s, p = pl.program_id(0), pl.program_id(1)
    _decode_body(positions_ref[s], p, pl.num_programs(1),
                 p * kw["block_k"], q_ref, k_ref, v_ref, o_ref,
                 m_ref, l_ref, acc_ref, **kw)


def flash_decode_attention(q, k_cache, v_cache, positions, *,
                           dtype=jnp.bfloat16, block_k=None,
                           page_table=None, interpret=None):
    """Length-aware flash decode over the slot cache.

    ``q`` [S, 1, H, D]; ``k_cache``/``v_cache`` [S, L, H, D];
    ``positions`` [S] int32; returns [S, 1, H, D] in ``dtype``.  With
    ``page_table`` ([S, pages_per_slot] int32, physical page ids into
    the ``[S*pages_per_slot, page_size, C]`` page view) the KV
    index_map walks the table instead of the slot-contiguous layout;
    ``page_size`` is implied by ``L // page_table.shape[1]``.
    """
    S, _, H, D = q.shape
    L = k_cache.shape[1]
    C = H * D
    paged = page_table is not None
    if paged:
        n_pages = page_table.shape[1]
        if L % n_pages:
            raise ValueError(
                f"page table with {n_pages} pages cannot tile L={L}")
        bk = L // n_pages
    else:
        bk = block_k or _pick_block_k(L)
    nk = L // bk
    if interpret is None:
        interpret = _use_interpret()

    q2 = q.reshape(S, 1, C)
    k2 = k_cache.reshape(S, L, C)
    v2 = v_cache.reshape(S, L, C)

    if paged:
        # physical page view; the table maps (slot, logical page) ->
        # physical page row
        k2 = k2.reshape(S * nk, bk, C)
        v2 = v2.reshape(S * nk, bk, C)

        def kv_map(s, p, pos_ref, tab_ref):
            return (tab_ref[s, kv_block_bound(p, pos_ref[s], bk)], 0, 0)

        def sq_map(s, p, pos_ref, tab_ref):
            return (s, 0, 0)

        kernel = flash_decode_paged_kernel
        scalars = (jnp.asarray(positions, jnp.int32),
                   jnp.asarray(page_table, jnp.int32))
        kv_block = (1, bk, C)
    else:
        def kv_map(s, kb, pos_ref):
            return (s, kv_block_bound(kb, pos_ref[s], bk), 0)

        def sq_map(s, kb, pos_ref):
            return (s, 0, 0)

        kernel = flash_decode_kernel
        scalars = (jnp.asarray(positions, jnp.int32),)
        kv_block = (1, bk, C)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(S, nk),
        in_specs=[
            pl.BlockSpec((1, 1, C), sq_map),
            pl.BlockSpec(kv_block, kv_map),
            pl.BlockSpec(kv_block, kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, C), sq_map),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),   # running max m
            pltpu.VMEM((H, 128), jnp.float32),   # running sum l
            pltpu.VMEM((H, D), jnp.float32),     # rescaled accumulator
        ],
    )
    body = functools.partial(
        kernel, sm_scale=1.0 / float(np.sqrt(D)), block_k=bk,
        n_head=H, head_dim=D)
    # both names keep the "flash" stem: the anatomy category table and
    # the collective classifier key on it (telemetry/anatomy.py
    # bucket_of, comm/audit.py collective_kind)
    body.__name__ = _KERNEL_NAME if not paged \
        else "flash_decode_paged_kernel"
    out = pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, 1, C), dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*scalars, q2, k2, v2)
    return out.reshape(S, 1, H, D)


__all__ = [
    "NEG_INF",
    "VALID_DECODE_IMPLS",
    "decode_kernel_supported",
    "flash_decode_attention",
    "kv_block_bound",
    "resolve_decode_impl",
]
