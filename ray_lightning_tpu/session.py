"""Per-worker session singleton (reference: ray_lightning/session.py:6-63).

Holds (rank, queue-proxy) inside each worker so callbacks deep in the
training loop can relay side-effects to the driver without plumbing
handles through every layer — the load-bearing trick behind Tune
integration ("relay the side-effect, not the call", SURVEY.md §3.3).
Same strict double-init / uninitialized-access contract as the reference
(session.py:30-48).
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class RLTSession:
    def __init__(self, rank: int, queue: Optional[Any]):
        self._rank = rank
        self._queue = queue

    def get_actor_rank(self) -> int:
        return self._rank

    def put_queue(self, item: Any) -> None:
        if self._queue is None:
            raise ValueError(
                "RLTSession has no queue: this run was not launched with a "
                "driver-side queue (Tune callbacks require one).")
        self._queue.put((self._rank, item))


_session: Optional[RLTSession] = None


def init_session(rank: int, queue: Optional[Any]) -> None:
    global _session
    if _session is not None:
        raise ValueError(
            "A ray_lightning_tpu session is already initialized in this "
            "process; init_session may be called only once.")
    _session = RLTSession(rank, queue)


def get_session() -> RLTSession:
    if _session is None:
        raise ValueError(
            "No ray_lightning_tpu session in this process; was this called "
            "outside a launched worker?")
    return _session


def reset_session() -> None:
    global _session
    _session = None


def get_actor_rank() -> int:
    return get_session().get_actor_rank()


def put_queue(item: Callable | Any) -> None:
    """Enqueue an item (usually a zero-arg callable) for execution on the
    driver (session.py:17-24 + util.py:47-52 analog)."""
    get_session().put_queue(item)
