"""Pipeline-parallel GPT: GPipe microbatching over a (data × stage) mesh.

Beyond the reference's capability surface (SURVEY.md §2.3 marks pipeline
parallelism absent): the blocks' parameters are layer-stacked and
sharded over the ``stage`` axis, activations hop between stages with
``lax.ppermute``, and the whole schedule is one compiled SPMD program
(parallel/pipeline.py).  Raise ``--microbatches`` to shrink the pipeline
bubble ((S-1)/(M+S-1)).

Run locally without a TPU via virtual CPU devices:
    python -m ray_lightning_tpu.examples.ray_pipeline_example --smoke-test
"""

from __future__ import annotations

import argparse
import os


def train(stages: int = 4,
          microbatches: int = 4,
          model_size: str = "gpt2-small",
          num_epochs: int = 1,
          batch_size: int = 8,
          dataset_size: int = 64,
          precision: str = "bf16",
          limit_train_batches: int | None = None):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT
    from ray_lightning_tpu.parallel.pipeline import PipelineStrategy

    module = PipelinedGPT(model_size, n_microbatches=microbatches,
                          dataset_size=dataset_size,
                          batch_size=batch_size)
    trainer = Trainer(
        max_epochs=num_epochs,
        strategy=PipelineStrategy(stages=stages),
        precision=precision,
        limit_train_batches=limit_train_batches,
        limit_val_batches=0,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(module)
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--stages", type=int, default=4,
                        help="Pipeline stages (must divide n_layer).")
    parser.add_argument("--microbatches", type=int, default=4)
    parser.add_argument("--model-size", type=str, default="gpt2-small")
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    args = parser.parse_args()

    kwargs: dict = dict(stages=args.stages,
                        microbatches=args.microbatches,
                        model_size=args.model_size,
                        num_epochs=args.num_epochs,
                        batch_size=args.batch_size)
    if args.smoke_test:
        from ray_lightning_tpu.utils.platform import host_device_count_flags
        os.environ["XLA_FLAGS"] = host_device_count_flags(4)
        import jax
        jax.config.update("jax_platforms", "cpu")
        kwargs.update(model_size="tiny", stages=2, microbatches=2,
                      batch_size=4, dataset_size=8, limit_train_batches=2,
                      precision="32")

    trainer = train(**kwargs)
    print("Final metrics:", dict(trainer.callback_metrics))


if __name__ == "__main__":
    main()
