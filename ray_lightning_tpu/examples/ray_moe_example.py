"""Mixture-of-Experts GPT over a (data × expert × tensor) mesh.

Beyond the reference's capability surface (SURVEY.md §2.3 marks expert
parallelism absent): the routed FFN (ops/moe.py) keeps every shape
static (GShard-style fixed expert capacity), expert weights shard their
leading dim on the ``expert`` mesh axis, and GSPMD lowers the
dispatch/combine einsums to the token all-to-all over ICI.  The router's
load-balance loss folds into the training loss automatically
(GPTLightningModule.training_step) and surfaces as the ``moe_aux``
metric.

Run locally without a TPU via virtual CPU devices:
    python -m ray_lightning_tpu.examples.ray_moe_example --smoke-test
"""

from __future__ import annotations

import argparse
import os


def train(expert: int = 2,
          tensor: int = 2,
          model_size: str = "gpt2-moe-8e",
          num_epochs: int = 1,
          batch_size: int = 8,
          dataset_size: int = 64,
          precision: str = "bf16",
          limit_train_batches: int | None = None):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import (
        CONFIGS, GPTLightningModule, gpt_partition_rules)
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    cfg = CONFIGS[model_size]
    module = GPTLightningModule(cfg, dataset_size=dataset_size,
                                batch_size=batch_size)
    strategy = SpmdStrategy(
        rules=gpt_partition_rules(),
        axis_names=("data", "expert", "tensor"),
        axis_sizes={"expert": expert, "tensor": tensor},
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        strategy=strategy,
        precision=precision,
        limit_train_batches=limit_train_batches,
        limit_val_batches=0,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(module)
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--expert", type=int, default=2,
                        help="Expert-parallel axis size.")
    parser.add_argument("--tensor", type=int, default=2,
                        help="Tensor-parallel axis size within experts.")
    parser.add_argument("--model-size", type=str, default="gpt2-moe-8e")
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    args = parser.parse_args()

    kwargs: dict = dict(expert=args.expert, tensor=args.tensor,
                        model_size=args.model_size,
                        num_epochs=args.num_epochs,
                        batch_size=args.batch_size)
    if args.smoke_test:
        from ray_lightning_tpu.utils.platform import host_device_count_flags
        os.environ["XLA_FLAGS"] = host_device_count_flags(
            2 * args.expert * args.tensor)
        import jax
        jax.config.update("jax_platforms", "cpu")
        kwargs.update(model_size="moe-tiny", batch_size=4, dataset_size=8,
                      limit_train_batches=2, precision="32")

    trainer = train(**kwargs)
    metrics = dict(trainer.callback_metrics)
    print("Final metrics:", metrics)
    assert "moe_aux" in metrics, "router aux loss did not surface"


if __name__ == "__main__":
    main()
