"""Throughput tuning on a tunneled / small-model TPU setup.

The reference's examples stop at "attach the plugin"
(examples/ray_ddp_example.py:118-173); on TPU the next question is
always throughput, and for small models the bottleneck is the host —
per-step dispatch latency and host→device batch transfer — not the
MXU.  This example walks the three knobs that fix it, in the order
measured to matter (benchmarks/README.md config #1: 57.8 → ~400
steps/s):

1. ``Trainer(steps_per_execution=k)`` — k optimizer steps ride ONE
   compiled dispatch (``lax.scan`` over stacked batches): k× fewer
   host round-trips.
2. ``Trainer(cache_train_dataset=True)`` — the train set uploads once
   and lives on device; each epoch a device-side repack follows the
   loader's own index order (shuffle-accurate), and steps gather their
   batch by index — the per-step transfer disappears.  Works under
   distributed plugins too (the cache shards across workers' devices).
3. ``Trainer(precision="bf16")`` — float batch leaves cast to bf16 at
   the host boundary, halving whatever transfer remains.

Also on by default (env knobs, models/gpt.py): bf16-resident params
with an fp32 master (``RLT_BF16_PARAMS``), the fused bf16-logits LM
loss (``RLT_FUSED_CE``), double-buffered streamed input
(``RLT_STREAM_PREFETCH``), and conditional state donation
(``RLT_DONATE`` — auto skips ``donate_argnums`` on small states, worth
−3.4% device time on the gpt2-small headline; see
``core/trainer.py _should_donate``).

    python -m ray_lightning_tpu.examples.ray_perf_tuning_example \
        [--smoke-test] [--num-workers N]
"""

from __future__ import annotations

import argparse
import time

from ray_lightning_tpu import RayXlaPlugin, Trainer
from ray_lightning_tpu.models import LightningMNISTClassifier


def run(steps_per_execution: int = 1, cache: bool = False,
        precision: str = "32", num_workers: int = 0,
        max_epochs: int = 2, train_size: int = 2048) -> tuple[float, int]:
    """One fit with the given knobs; returns (seconds, steps)."""
    plugins = []
    if num_workers > 0:
        plugins.append(RayXlaPlugin(num_workers=num_workers,
                                    platform="cpu"))
    model = LightningMNISTClassifier(config={"batch_size": 128},
                                     train_size=train_size)
    trainer = Trainer(
        plugins=plugins or None,
        max_epochs=max_epochs,
        steps_per_execution=steps_per_execution,
        cache_train_dataset=cache,
        precision=precision,
        enable_checkpointing=False,
        num_sanity_val_steps=0,
        limit_val_batches=0,
        log_every_n_steps=10**9,
        seed=0,
    )
    t0 = time.monotonic()
    trainer.fit(model)
    return time.monotonic() - t0, trainer.global_step


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke-test", action="store_true",
                        help="tiny sizes, single fit per config")
    parser.add_argument("--num-workers", type=int, default=0,
                        help=">0: run through RayXlaPlugin CPU actors "
                             "(cache shards across workers)")
    args = parser.parse_args()

    kw = dict(num_workers=args.num_workers)
    if args.smoke_test:
        import jax
        jax.config.update("jax_platforms", "cpu")  # CI boxes have no TPU
        kw.update(max_epochs=1, train_size=512)

    configs = [
        ("streamed (baseline)", dict()),
        ("steps_per_execution=8", dict(steps_per_execution=8)),
        ("+ cache_train_dataset", dict(steps_per_execution=8, cache=True)),
        ("+ precision=bf16", dict(steps_per_execution=8, cache=True,
                                  precision="bf16")),
    ]
    for name, knobs in configs:
        secs, steps = run(**{**kw, **knobs})
        print(f"{name:28s} {steps / secs:8.1f} steps/s "
              f"({steps} steps in {secs:.1f}s)")


if __name__ == "__main__":
    main()
