"""Tune sweep with a LightningDataModule and a worker init hook.

Reference: examples/ray_ddp_tune.py — Tune + pl_bolts MNISTDataModule +
``init_hook`` FileLock data download (:22-25).  The hermetic analog:
a DataModule that materializes its synthetic dataset in ``prepare_data``
via an atomic per-node cache write, and an ``init_hook`` that pre-warms
the same cache on every worker before training starts (RayXlaPlugin ships the
hook to each actor first; ray_ddp.py:185-186 parity).
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

from ray_lightning_tpu import (
    DataLoader,
    LightningDataModule,
    RayXlaPlugin,
    Trainer,
)
from ray_lightning_tpu import tune
from ray_lightning_tpu.core.data import ArrayDataset
from ray_lightning_tpu.models import LightningMNISTClassifier
from ray_lightning_tpu.models.boring import synthetic_mnist
from ray_lightning_tpu.tune import TuneReportCallback, get_tune_resources

CACHE = os.path.join(tempfile.gettempdir(), "rlt_mnist_cache.npz")


def download_data() -> None:
    """Materialize the dataset once per node (the reference guards its
    download with a FileLock, examples/ray_ddp_tune.py:22-25; here an
    atomic rename makes concurrent regeneration merely redundant)."""
    if os.path.exists(CACHE):
        return
    train = synthetic_mnist(512, seed=0)
    val = synthetic_mnist(128, seed=1)
    train_x, train_y = train.take(np.arange(len(train)))
    val_x, val_y = val.take(np.arange(len(val)))
    tmp = CACHE.replace(".npz", f".tmp.{os.getpid()}.npz")
    np.savez(tmp, train_x=train_x, train_y=train_y, val_x=val_x, val_y=val_y)
    os.replace(tmp, CACHE)  # atomic: concurrent workers race safely


class MNISTDataModule(LightningDataModule):
    def __init__(self, batch_size: int = 32):
        super().__init__()
        self.batch_size = batch_size
        self._train = self._val = None

    def prepare_data(self):
        download_data()

    def setup(self, stage):
        data = np.load(CACHE)
        self._train = ArrayDataset(data["train_x"], data["train_y"])
        self._val = ArrayDataset(data["val_x"], data["val_y"])

    def train_dataloader(self):
        return DataLoader(self._train, batch_size=self.batch_size,
                          shuffle=True)

    def val_dataloader(self):
        return DataLoader(self._val, batch_size=self.batch_size)


def train_mnist(config: dict,
                num_epochs: int = 10,
                num_workers: int = 1,
                use_tpu: bool = False,
                platform: str | None = None,
                limit_train_batches: int | None = None,
                limit_val_batches: int | None = None) -> None:
    model = LightningMNISTClassifier(config)
    dm = MNISTDataModule(batch_size=int(config.get("batch_size", 32)))
    plugin = RayXlaPlugin(num_workers=num_workers, use_tpu=use_tpu,
                          platform=platform, init_hook=download_data)
    trainer = Trainer(
        max_epochs=num_epochs,
        plugins=[plugin],
        callbacks=[TuneReportCallback(
            {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
            on="validation_end")],
        limit_train_batches=limit_train_batches,
        limit_val_batches=limit_val_batches,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(model, dm)


def tune_mnist(num_samples: int = 10,
               num_epochs: int = 10,
               num_workers: int = 1,
               use_tpu: bool = False,
               platform: str | None = None,
               limit_train_batches: int | None = None,
               limit_val_batches: int | None = None):
    config = {
        "layer_1": tune.choice([32, 64, 128]),
        "layer_2": tune.choice([64, 128, 256]),
        "lr": tune.loguniform(1e-4, 1e-1),
        "batch_size": tune.choice([32, 64, 128]),
    }

    def trainable(cfg):
        train_mnist(cfg, num_epochs=num_epochs, num_workers=num_workers,
                    use_tpu=use_tpu, platform=platform,
                    limit_train_batches=limit_train_batches,
                    limit_val_batches=limit_val_batches)

    analysis = tune.run(
        trainable,
        config=config,
        num_samples=num_samples,
        metric="loss",
        mode="min",
        resources_per_trial=get_tune_resources(
            num_workers=num_workers, use_tpu=use_tpu),
        name="tune_mnist_datamodule",
    )
    print("Best hyperparameters found were:", analysis.best_config)
    return analysis


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--num-samples", type=int, default=10)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    parser.add_argument("--address", type=str, default=None)
    args = parser.parse_args()

    if args.address:
        import ray
        ray.init(address=args.address)

    kwargs: dict = dict(num_workers=args.num_workers, use_tpu=args.use_tpu)
    if args.smoke_test:
        kwargs.update(platform="cpu", use_tpu=False,
                      limit_train_batches=4, limit_val_batches=2)
        args.num_epochs = 1
        args.num_samples = 1

    tune_mnist(num_samples=args.num_samples, num_epochs=args.num_epochs,
               **kwargs)


if __name__ == "__main__":
    main()
