"""Long-context GPT training with ring attention (sequence parallelism).

Beyond-reference capability (SURVEY.md §5 notes the reference has no
sequence-length machinery at all): the sequence dimension is sharded
across a ``sequence`` mesh axis, K/V blocks rotate around the ring via
``ppermute`` riding ICI, and the full [T, T] score matrix never exists —
so context length scales with the number of devices instead of hitting
one chip's HBM wall.

Run without a TPU via virtual CPU devices:
    python -m ray_lightning_tpu.examples.ray_longcontext_example --smoke-test
"""

from __future__ import annotations

import argparse
import dataclasses
import os


def train(sequence: int = 4,
          model_size: str = "gpt2-small",
          seq_len: int = 8192,
          num_epochs: int = 1,
          batch_size: int = 1,
          dataset_size: int = 8,
          precision: str = "bf16",
          limit_train_batches: int | None = None):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import (
        CONFIGS, GPTLightningModule, gpt_partition_rules)
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    cfg = dataclasses.replace(CONFIGS[model_size], block_size=seq_len,
                              attention_impl="ring")
    module = GPTLightningModule(cfg, dataset_size=dataset_size,
                                batch_size=batch_size)
    strategy = SpmdStrategy(
        rules=gpt_partition_rules(),
        axis_names=("data", "sequence"),
        axis_sizes={"sequence": sequence},
        # shard_sequence_dim (default True) shards the batch's sequence
        # dim over the ring
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        strategy=strategy,
        precision=precision,
        limit_train_batches=limit_train_batches,
        limit_val_batches=0,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(module)
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sequence", type=int, default=4,
                        help="Ring size (sequence-parallel axis).")
    parser.add_argument("--seq-len", type=int, default=8192,
                        help="Total context length across the ring.")
    parser.add_argument("--model-size", type=str, default="gpt2-small")
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=1)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    args = parser.parse_args()

    kwargs: dict = dict(sequence=args.sequence, seq_len=args.seq_len,
                        model_size=args.model_size,
                        num_epochs=args.num_epochs,
                        batch_size=args.batch_size)
    if args.smoke_test:
        from ray_lightning_tpu.utils.platform import host_device_count_flags
        os.environ["XLA_FLAGS"] = host_device_count_flags(args.sequence)
        import jax
        jax.config.update("jax_platforms", "cpu")
        kwargs.update(model_size="tiny", seq_len=256, batch_size=2,
                      dataset_size=4, limit_train_batches=2,
                      precision="32")

    trainer = train(**kwargs)
    print("Final metrics:", dict(trainer.callback_metrics))


if __name__ == "__main__":
    main()
