"""GPT training over a multi-axis SPMD mesh (data × fsdp × tensor).

This example fills the reference's fourth-example slot
(examples/ray_horovod_example.py): on TPU there is one collective fabric,
so the Horovod path is subsumed by the XLA plugin (SURVEY.md §2.3) and
the freed slot demonstrates what the reference could not do at all —
tensor/FSDP-parallel training expressed as sharding annotations, compiled
by XLA to ICI collectives, over the same actor orchestration.

Run locally without a TPU via virtual CPU devices:
    python -m ray_lightning_tpu.examples.ray_spmd_example --smoke-test
"""

from __future__ import annotations

import argparse
import os


def train(data: int = 1,
          fsdp: int = 2,
          tensor: int = 2,
          model_size: str = "gpt2-small",
          num_epochs: int = 1,
          batch_size: int = 8,
          dataset_size: int = 64,
          precision: str = "bf16",
          limit_train_batches: int | None = None):
    # one process, many local devices: the single-host SPMD path (the
    # multi-host path wraps this same strategy in RayXlaSpmdPlugin actors)
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import (
        CONFIGS, GPTLightningModule, gpt_partition_rules)
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    cfg = CONFIGS[model_size]
    module = GPTLightningModule(cfg, dataset_size=dataset_size,
                                batch_size=batch_size)
    strategy = SpmdStrategy(
        rules=gpt_partition_rules(),
        axis_names=("data", "fsdp", "tensor"),
        axis_sizes={"fsdp": fsdp, "tensor": tensor},
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        strategy=strategy,
        precision=precision,
        limit_train_batches=limit_train_batches,
        limit_val_batches=0,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(module)
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--fsdp", type=int, default=2,
                        help="FSDP (ZeRO-3 parameter sharding) axis size.")
    parser.add_argument("--tensor", type=int, default=2,
                        help="Megatron-style tensor-parallel axis size.")
    parser.add_argument("--model-size", type=str, default="gpt2-small")
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    args = parser.parse_args()

    kwargs: dict = dict(fsdp=args.fsdp, tensor=args.tensor,
                        model_size=args.model_size,
                        num_epochs=args.num_epochs,
                        batch_size=args.batch_size)
    if args.smoke_test:
        # enough virtual CPU devices for a 1×fsdp×tensor mesh — the flag
        # must be in place before jax initializes its backend, and the
        # platform is forced via jax.config (the env var alone loses to
        # installed TPU plugins)
        from ray_lightning_tpu.utils.platform import host_device_count_flags
        os.environ["XLA_FLAGS"] = host_device_count_flags(
            args.fsdp * args.tensor)
        import jax
        jax.config.update("jax_platforms", "cpu")
        kwargs.update(model_size="tiny", batch_size=4, dataset_size=8,
                      limit_train_batches=2, precision="32")

    trainer = train(**kwargs)
    print("Final metrics:", dict(trainer.callback_metrics))


if __name__ == "__main__":
    main()
