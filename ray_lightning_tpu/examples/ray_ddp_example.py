"""MNIST data-parallel training, optionally as a Tune sweep.

Reference: examples/ray_ddp_example.py (MNISTClassifier + train_mnist /
tune_mnist + CLI :118-173).  Same shape here with ``RayXlaPlugin``
workers: the driver builds the module and Trainer; actors run the
compiled SPMD step; Tune trials relay metrics through the worker→driver
queue (SURVEY.md §3.3).
"""

from __future__ import annotations

import argparse

from ray_lightning_tpu import Trainer, RayXlaPlugin
from ray_lightning_tpu import tune
from ray_lightning_tpu.models import LightningMNISTClassifier
from ray_lightning_tpu.tune import (
    TuneReportCallback,
    get_tune_resources,
)


def train_mnist(config: dict,
                data_dir: str = "",
                num_epochs: int = 10,
                num_workers: int = 1,
                use_tpu: bool = False,
                platform: str | None = None,
                callbacks: list | None = None,
                limit_train_batches: int | None = None,
                limit_val_batches: int | None = None) -> Trainer:
    """Train the MNIST classifier once (train_mnist analog,
    examples/ray_ddp_example.py:41-58)."""
    model = LightningMNISTClassifier(config, data_dir)
    plugin = RayXlaPlugin(num_workers=num_workers, use_tpu=use_tpu,
                          platform=platform)
    trainer = Trainer(
        max_epochs=num_epochs,
        callbacks=list(callbacks or []),
        plugins=[plugin],
        limit_train_batches=limit_train_batches,
        limit_val_batches=limit_val_batches,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(model)
    return trainer


def tune_mnist(data_dir: str = "",
               num_samples: int = 10,
               num_epochs: int = 10,
               num_workers: int = 1,
               use_tpu: bool = False,
               platform: str | None = None,
               limit_train_batches: int | None = None,
               limit_val_batches: int | None = None):
    """Random-search sweep over lr/width/batch (tune_mnist analog,
    examples/ray_ddp_example.py:81-115)."""
    config = {
        "layer_1": tune.choice([32, 64, 128]),
        "layer_2": tune.choice([64, 128, 256]),
        "lr": tune.loguniform(1e-4, 1e-1),
        "batch_size": tune.choice([32, 64, 128]),
    }

    def trainable(cfg):
        train_mnist(
            cfg, data_dir, num_epochs=num_epochs, num_workers=num_workers,
            use_tpu=use_tpu, platform=platform,
            limit_train_batches=limit_train_batches,
            limit_val_batches=limit_val_batches,
            callbacks=[TuneReportCallback(
                {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
                on="validation_end")],
        )

    analysis = tune.run(
        trainable,
        config=config,
        num_samples=num_samples,
        metric="loss",
        mode="min",
        resources_per_trial=get_tune_resources(
            num_workers=num_workers, use_tpu=use_tpu),
        name="tune_mnist",
    )
    print("Best hyperparameters found were:", analysis.best_config)
    return analysis


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1,
                        help="Number of training workers (TPU hosts).")
    parser.add_argument("--use-tpu", action="store_true", default=False,
                        help="Reserve TPU chips for each worker.")
    parser.add_argument("--tune", action="store_true", default=False,
                        help="Run a Tune hyperparameter sweep.")
    parser.add_argument("--num-samples", type=int, default=10,
                        help="Number of Tune trials.")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--smoke-test", action="store_true", default=False,
                        help="Tiny run on CPU workers for CI.")
    parser.add_argument("--address", type=str, default=None,
                        help="Ray cluster address (e.g. auto / ray://...).")
    args = parser.parse_args()

    if args.address:
        import ray
        ray.init(address=args.address)

    kwargs: dict = dict(num_workers=args.num_workers, use_tpu=args.use_tpu)
    if args.smoke_test:
        kwargs.update(platform="cpu", use_tpu=False,
                      limit_train_batches=4, limit_val_batches=2)
        args.num_epochs = 1
        args.num_samples = 2

    if args.tune:
        tune_mnist(num_samples=args.num_samples,
                   num_epochs=args.num_epochs, **kwargs)
    else:
        trainer = train_mnist({}, num_epochs=args.num_epochs, **kwargs)
        print("Final metrics:", dict(trainer.callback_metrics))


if __name__ == "__main__":
    main()
