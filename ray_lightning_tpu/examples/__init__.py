"""Runnable example scripts (reference: ray_lightning/examples/*.py).

The actor-based scripts expose ``--num-workers``, ``--use-tpu`` and
(where applicable) ``--tune`` / ``--address`` CLI flags, matching the
reference's example CLI surface (examples/ray_ddp_example.py:118-173);
the single-host SPMD script exposes mesh-axis flags instead.  All of
them support ``--smoke-test``.
``--smoke-test`` downsizes to one epoch / few batches on CPU workers so
the scripts double as CI smoke tests (reference test.yaml:95-103).
"""
