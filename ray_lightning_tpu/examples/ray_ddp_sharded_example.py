"""Train a GPT language model with the ZeRO-1 sharded plugin, measuring
per-epoch wall time and peak device memory.

Reference: examples/ray_ddp_sharded_example.py — ImageGPT (pl_bolts) under
``RayShardedPlugin`` with fp16 and ``CUDACallback`` (:16-45), the repo's
only perf-measurement code.  Here the model is the in-tree GPT family
(models/gpt.py), sharding is XLA ZeRO-1 (reduce-scatter grads, sharded
optimizer step, all-gather params) instead of FairScale OSS/SDP, and
``TPUPerfCallback`` reads PJRT ``memory_stats`` where the reference read
``torch.cuda.max_memory_allocated``.
"""

from __future__ import annotations

import argparse
import time

from ray_lightning_tpu import RayXlaShardedPlugin, Trainer
from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule
from ray_lightning_tpu.utils.profiling import (
    ThroughputMonitor, peak_device_memory_bytes)


class TPUPerfCallback(ThroughputMonitor):
    """Epoch wall time + peak device memory (CUDACallback analog,
    examples/ray_ddp_sharded_example.py:16-45).  The measurement itself
    is the package's ThroughputMonitor — values log through the trainer's
    metrics and ride the normal rank-0 relay instead of a manual
    all_reduce; this subclass just adds the example's console line."""

    def on_train_epoch_end(self, trainer, module):
        t0 = self._epoch_t0
        super().on_train_epoch_end(trainer, module)
        if trainer.is_global_zero and t0 is not None:
            peak = peak_device_memory_bytes()
            mem = f", peak memory {peak / 1e6:.0f}MB" if peak else ""
            print(f"Epoch {trainer.current_epoch}: "
                  f"{time.monotonic() - t0:.2f}s{mem}", flush=True)


def train(num_workers: int = 1,
          use_tpu: bool = False,
          platform: str | None = None,
          model_size: str = "gpt2-small",
          num_epochs: int = 1,
          batch_size: int = 8,
          dataset_size: int = 256,
          precision: str = "bf16",
          limit_train_batches: int | None = None) -> Trainer:
    cfg = CONFIGS[model_size]
    module = GPTLightningModule(cfg, dataset_size=dataset_size,
                                batch_size=batch_size)
    plugin = RayXlaShardedPlugin(num_workers=num_workers, use_tpu=use_tpu,
                                 platform=platform)
    trainer = Trainer(
        max_epochs=num_epochs,
        plugins=[plugin],
        callbacks=[TPUPerfCallback()],
        precision=precision,
        limit_train_batches=limit_train_batches,
        limit_val_batches=0,
        num_sanity_val_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(module)
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--model-size", type=str, default="gpt2-small",
                        choices=sorted(CONFIGS))
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--smoke-test", action="store_true", default=False)
    parser.add_argument("--address", type=str, default=None)
    args = parser.parse_args()

    if args.address:
        import ray
        ray.init(address=args.address)

    kwargs: dict = dict(num_workers=args.num_workers, use_tpu=args.use_tpu,
                        model_size=args.model_size,
                        num_epochs=args.num_epochs,
                        batch_size=args.batch_size)
    if args.smoke_test:
        kwargs.update(platform="cpu", use_tpu=False, model_size="tiny",
                      num_epochs=1, batch_size=2, dataset_size=8,
                      limit_train_batches=2, precision="32")

    trainer = train(**kwargs)
    print("Final metrics:", dict(trainer.callback_metrics))


if __name__ == "__main__":
    main()
