"""MPMD pipeline configuration (``Trainer(strategy="mpmd")`` knobs).

``MpmdConfig`` is the frozen, picklable settings object of the MPMD
plane, following the ``CommPolicy`` / ``PlanConfig`` construction
pattern (first match wins):

- ``Trainer(strategy=MpmdPipelineStrategy(MpmdConfig(...)))`` — full
  control;
- ``Trainer(strategy="mpmd")`` — env knobs, read at resolution time:
  ``RLT_MPMD_STAGES``, ``RLT_MPMD_CUTS`` (comma-separated ascending
  layer boundaries; empty = planner-scored even split),
  ``RLT_MPMD_SCHEDULE`` (``gpipe``/``1f1b``), ``RLT_MPMD_MICRO``,
  ``RLT_MPMD_VIRTUAL`` (0 = auto interleave when layers allow),
  ``RLT_MPMD_CODEC`` (``none``/``bf16``/``int8``/``fp8``/``int4`` —
  the comm plane's codec menu applied to the activation payloads),
  ``RLT_MPMD_BLOCK``, ``RLT_MPMD_EF``, ``RLT_MPMD_ACTORS``,
  ``RLT_MPMD_TIMEOUT_S``.

The resolved config pickles driver→worker with the strategy and
round-trips through ``worker_env()`` like the comm/compile/elastic/plan
knobs do (plugins/xla.py), so worker-side tooling consulting
``RLT_MPMD*`` stays consistent with the driver's resolution.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

#: codec menu for the activation channel — ``none`` plus everything the
#: comm plane's ``compress_cast`` dispatch accepts (comm/quant.py)
VALID_CODECS = ("none", "bf16", "int8", "fp8", "int4")
VALID_SCHEDULES = ("gpipe", "1f1b")

ENV_STAGES = "RLT_MPMD_STAGES"
ENV_CUTS = "RLT_MPMD_CUTS"
ENV_SCHEDULE = "RLT_MPMD_SCHEDULE"
ENV_MICRO = "RLT_MPMD_MICRO"
ENV_VIRTUAL = "RLT_MPMD_VIRTUAL"
ENV_CODEC = "RLT_MPMD_CODEC"
ENV_BLOCK = "RLT_MPMD_BLOCK"
ENV_EF = "RLT_MPMD_EF"
ENV_ACTORS = "RLT_MPMD_ACTORS"
ENV_TIMEOUT = "RLT_MPMD_TIMEOUT_S"
ENV_KNOBS = (ENV_STAGES, ENV_CUTS, ENV_SCHEDULE, ENV_MICRO, ENV_VIRTUAL,
             ENV_CODEC, ENV_BLOCK, ENV_EF, ENV_ACTORS, ENV_TIMEOUT)


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name, "").strip()
    if raw in ("0", "false", "False"):
        return False
    if raw in ("1", "true", "True"):
        return True
    return default


@dataclasses.dataclass(frozen=True)
class MpmdConfig:
    """How the MPMD pipeline runs.

    stages: number of cooperating per-stage programs (>= 2).
    cuts: ascending layer boundaries between stages (``(2,)`` on 4
        layers = slices [0:2) and [2:4)).  ``None`` = let the stage
        partitioner pick, scoring every contiguous cut with the
        planner's per-link ``_dcn`` byte attribution
        (mpmd/partition.py choose_cuts).
    schedule: driver-side microbatch schedule — ``"gpipe"`` (all
        forwards, then all backwards) or ``"1f1b"`` (one-forward-
        one-backward steady state; interleaves over virtual stage
        chunks when the layer count allows — see mpmd/schedule.py for
        why PLAIN 1F1B analytically ties GPipe's bubble and
        interleaving is what buys it down).
    microbatches: microbatches per optimizer step (batch must divide).
    virtual: virtual chunks per stage for the interleaved 1F1B
        schedule.  ``0`` = auto (2 when every stage slice splits
        evenly and the schedule is 1f1b, else 1); GPipe always runs
        un-interleaved.
    codec: wire format of the stage-boundary activation / activation-
        grad payloads (comm/quant.py codecs).  ``"none"`` ships the
        residency dtype untouched.
    block_size: codec scale-block length (must divide the trailing
        activation dim; even for int4).
    error_feedback: carry the per-link quantization residual across
        steps and re-inject it before encoding (the comm plane's EF
        machinery applied to the activation path); the residual rides
        the stage's optimizer state and checkpoints with it.
    actors: run each stage as a cluster-backend actor exchanging
        activations over the worker↔worker peer channel (the true
        MPMD-over-DCN shape).  ``False`` (default) runs the stages
        in-process — same programs, same schedule, same channel codec,
        one process (the CPU-proxy mode benches and tests use).
    timeout_s: dead-peer bound — a channel receive that waits longer
        raises naming the stage/rank/microbatch instead of hanging.
    """

    stages: int = 2
    cuts: Optional[tuple] = None
    schedule: str = "1f1b"
    microbatches: int = 4
    virtual: int = 0
    codec: str = "none"
    block_size: int = 64
    error_feedback: bool = True
    actors: bool = False
    timeout_s: float = 120.0

    def __post_init__(self):
        if self.stages < 2:
            raise ValueError(
                f"mpmd stages must be >= 2 (got {self.stages}); a "
                f"single stage is just the sequential model")
        if self.schedule not in VALID_SCHEDULES:
            raise ValueError(f"mpmd schedule {self.schedule!r}; "
                             f"options: {VALID_SCHEDULES}")
        if self.codec not in VALID_CODECS:
            raise ValueError(f"mpmd codec {self.codec!r}; "
                             f"options: {VALID_CODECS}")
        if self.microbatches < 1:
            raise ValueError("mpmd microbatches must be >= 1")
        if self.virtual < 0:
            raise ValueError("mpmd virtual must be >= 0 (0 = auto)")
        if self.block_size <= 0:
            raise ValueError("mpmd block_size must be positive")
        if self.codec == "int4" and self.block_size % 2:
            raise ValueError("mpmd int4 needs an even block_size")
        if self.timeout_s <= 0:
            raise ValueError("mpmd timeout_s must be positive")
        if self.cuts is not None:
            cuts = tuple(int(c) for c in self.cuts)
            if list(cuts) != sorted(set(cuts)) or any(c <= 0 for c in cuts):
                raise ValueError(
                    f"mpmd cuts must be strictly ascending positive "
                    f"layer boundaries, got {cuts}")
            if len(cuts) != self.stages - 1:
                raise ValueError(
                    f"mpmd cuts {cuts} define {len(cuts) + 1} stages, "
                    f"config says {self.stages}")
            object.__setattr__(self, "cuts", cuts)

    # -- construction ----------------------------------------------------

    @classmethod
    def resolve(cls, value=None) -> "MpmdConfig":
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        if value is not None:
            raise TypeError(f"bad mpmd config: {value!r}")
        cuts_raw = os.environ.get(ENV_CUTS, "").strip()
        cuts = tuple(int(c) for c in cuts_raw.split(",") if c) or None
        return cls(
            stages=int(os.environ.get(ENV_STAGES, "2")),
            cuts=cuts,
            schedule=os.environ.get(ENV_SCHEDULE, "1f1b").strip() or "1f1b",
            microbatches=int(os.environ.get(ENV_MICRO, "4")),
            virtual=int(os.environ.get(ENV_VIRTUAL, "0")),
            codec=os.environ.get(ENV_CODEC, "none").strip() or "none",
            block_size=int(os.environ.get(ENV_BLOCK, "64")),
            error_feedback=_env_flag(ENV_EF, True),
            actors=_env_flag(ENV_ACTORS, False),
            timeout_s=float(os.environ.get(ENV_TIMEOUT, "120")),
        )

    # -- env round-trip --------------------------------------------------

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process (plugins/xla.py ships it like RLT_COMM*)."""
        env = {
            ENV_STAGES: str(self.stages),
            ENV_SCHEDULE: self.schedule,
            ENV_MICRO: str(self.microbatches),
            ENV_VIRTUAL: str(self.virtual),
            ENV_CODEC: self.codec,
            ENV_BLOCK: str(self.block_size),
            ENV_EF: "1" if self.error_feedback else "0",
            ENV_ACTORS: "1" if self.actors else "0",
            ENV_TIMEOUT: repr(self.timeout_s),
        }
        if self.cuts is not None:
            env[ENV_CUTS] = ",".join(str(c) for c in self.cuts)
        return env
