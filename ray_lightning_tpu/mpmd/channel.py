"""Point-to-point activation channel between pipeline stages.

Stage(i) ↔ stage(i+1) exchange of activations and activation-grads —
the one data path of the MPMD runtime that crosses hosts (everything
else is control RPC).  Three layers:

- **Mailbox**: a thread-safe tag-addressed store.  Tags are
  ``(kind, chunk, mb, step)`` tuples, so *out-of-order delivery is
  harmless by construction* — a receive blocks on ITS tag and takes
  whatever order the payloads arrived in.  A receive that outlives
  ``timeout_s`` raises :class:`PeerTimeout` naming the waiting stage/
  rank and the missing payload instead of hanging the fleet (the
  dead-peer contract tests/test_mpmd.py pins).
- **ChannelCodec**: the comm plane's fp8/int4/int8/bf16 codecs
  (comm/quant.py ``compress_cast``) applied per payload, with the
  EQuARX error-feedback residual carried PER (kind, mb) SLOT across
  optimizer steps — encode adds the slot's residual before
  quantizing and stores the new quantization error; the residual tree
  rides the owning stage's optimizer state (engine) so it checkpoints
  and restores with it.  ``codec="none"`` is a passthrough.
- **Transports**: :class:`InProcessChannel` (shared mailboxes — the
  single-process proxy mode) and :class:`PeerChannel` (the cluster
  backends' worker↔worker peer frames next to the worker→driver
  queue: builtin backend routes ``peer`` frames through the driver's
  socket fan-in, Ray delivers via a concurrent actor method —
  cluster/backend.py ``peer_send`` / worker_state mailbox).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_lightning_tpu.cluster.peer import (  # noqa: F401 - re-export
    Mailbox,
    PeerTimeout,
)


def payload_tag(kind: str, chunk: int, mb: int, step: int) -> tuple:
    return (kind, int(chunk), int(mb), int(step))


# -- codec ------------------------------------------------------------------


class ChannelCodec:
    """Per-link payload codec with per-slot error feedback.

    One instance per SENDING side of a link.  ``encode(slot, x)``
    returns the wire dict; ``decode(wire)`` reverses it on the
    receiver.  With ``error_feedback`` the slot's residual (same shape
    as the payload, fp32) persists across steps: the signal actually
    quantized is ``x + residual`` and the new residual is the
    quantization error — the comm plane's ``CommState`` contract on
    the activation path.  ``residuals`` is a plain dict pytree the
    engine stores inside the stage's optimizer state, so it
    checkpoints/restores with the stage and ``state_dict`` round-trips
    it (tests/test_mpmd.py).
    """

    def __init__(self, mode: str = "none", block_size: int = 64,
                 error_feedback: bool = True):
        self.mode = mode
        self.block_size = block_size
        self.error_feedback = error_feedback and mode not in ("none",
                                                              "bf16")
        self.residuals: dict = {}

    def encode(self, slot: tuple, x) -> dict:
        import jax.numpy as jnp

        arr = np.asarray(x)
        if self.mode == "none":
            return {"mode": "none", "q": arr}
        from ray_lightning_tpu.comm.quant import compress_cast
        val = jnp.asarray(arr, jnp.float32)
        if val.shape[-1] % self.block_size:
            raise ValueError(
                f"activation trailing dim {val.shape[-1]} not a "
                f"multiple of the codec block size {self.block_size}")
        if self.error_feedback:
            r = self.residuals.get(slot)
            if r is not None:
                val = val + jnp.asarray(r)
        q, scale = compress_cast(val, self.mode, self.block_size)
        wire = {"mode": self.mode, "q": np.asarray(q),
                "block_size": self.block_size,
                "shape": arr.shape, "dtype": str(arr.dtype)}
        if scale is not None:
            wire["scale"] = np.asarray(scale)
        if self.error_feedback:
            from ray_lightning_tpu.comm.quant import decompress_cast
            self.residuals[slot] = np.asarray(
                val - decompress_cast(q, scale, self.mode,
                                      self.block_size))
        return wire

    @staticmethod
    def decode(wire: dict):
        import jax.numpy as jnp

        if wire["mode"] == "none":
            return jnp.asarray(wire["q"])
        from ray_lightning_tpu.comm.quant import decompress_cast
        out = decompress_cast(jnp.asarray(wire["q"]),
                              (jnp.asarray(wire["scale"])
                               if "scale" in wire else None),
                              wire["mode"], wire.get("block_size", 64))
        return out.astype(wire["dtype"]).reshape(wire["shape"])

    # -- persistence (residual rides the stage opt state) ----------------

    def state_dict(self) -> dict:
        return {"/".join(map(str, k)): v
                for k, v in self.residuals.items()}

    def load_state_dict(self, state: dict) -> None:
        self.residuals = {}
        for key, v in (state or {}).items():
            kind, chunk, mb, step = key.split("/")
            self.residuals[(kind, int(chunk), int(mb), int(step))] = v


def make_codec(config) -> ChannelCodec:
    """Codec for one link under an :class:`MpmdConfig`."""
    return ChannelCodec(mode=config.codec, block_size=config.block_size,
                        error_feedback=config.error_feedback)


def ef_slot(kind: str, mb: int) -> tuple:
    """Error-feedback residual slot: per (direction, microbatch) — the
    payload at a fixed slot is the quantity whose step-over-step error
    the residual accumulates (chunk/step stay out of the key so the
    residual persists across steps)."""
    return (kind, 0, mb, 0)


# -- transports -------------------------------------------------------------


class InProcessChannel:
    """All chunks in one process: one shared mailbox per chunk."""

    def __init__(self, n_chunks: int, timeout_s: float = 120.0):
        self.timeout_s = timeout_s
        self._boxes = [Mailbox() for _ in range(n_chunks)]

    def send(self, dst_chunk: int, tag: tuple, wire: Any) -> None:
        self._boxes[dst_chunk].put(tag, wire)

    def recv(self, chunk: int, tag: tuple, *, who: str = "",
             src: str = "peer") -> Any:
        return self._boxes[chunk].take(tag, self.timeout_s,
                                       who=who or f"chunk {chunk}",
                                       src=src)


class PeerChannel:
    """Worker-side transport over the cluster backends' peer frames.

    Each stage actor owns one :class:`Mailbox`; incoming peer items
    (``{"tag": ..., "wire": ...}``) land there via
    ``worker_state.peer_push`` — routed by the builtin backend's
    driver socket fan-in, or delivered by Ray through the actor's
    concurrent ``__rlt_peer_deliver__`` method.  ``peers`` maps chunk
    index → actor name; sends go through ``worker_state.peer_send``.
    """

    def __init__(self, my_chunks, peers: dict, timeout_s: float = 120.0,
                 rank: Optional[int] = None):
        self.my_chunks = tuple(my_chunks)
        self.peers = dict(peers)
        self.timeout_s = timeout_s
        self.rank = rank
        from ray_lightning_tpu.cluster import worker_state
        self.mailbox = worker_state.peer_mailbox()

    def send(self, dst_chunk: int, tag: tuple, wire: Any) -> None:
        if dst_chunk in self.my_chunks:
            self.mailbox.put(tag, wire)
            return
        from ray_lightning_tpu.cluster import worker_state
        worker_state.peer_send(self.peers[dst_chunk],
                               {"tag": tag, "wire": wire})

    def recv(self, chunk: int, tag: tuple, *, who: str = "",
             src: str = "peer") -> Any:
        who = who or (f"stage rank {self.rank} (chunk {chunk})"
                      if self.rank is not None else f"chunk {chunk}")
        return self.mailbox.take(tag, self.timeout_s, who=who, src=src)
