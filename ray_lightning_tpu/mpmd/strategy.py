"""``Trainer(strategy="mpmd")`` — the MPMD pipeline's strategy object.

Unlike the SPMD strategies, this one never shards a single program: it
is a ROUTING object the trainer recognizes in ``_run_stage`` and hands
to the MPMD engine (mpmd/engine.py), carrying the resolved
:class:`MpmdConfig`.  It still speaks the strategy introspection
surface the planner/metrics planes consume — most usefully
``step_collective_bytes``, which declares the stage-boundary
activation exchange as a ``_dcn``-suffixed op so plan/cost.py scores
it at the DCN bandwidth and the metrics plane charges
``rlt_comm_dcn_bytes_total`` for it, exactly like the comm plane's
hierarchical declarations.
"""

from __future__ import annotations

from ray_lightning_tpu.parallel.strategy import ShardingStrategy


class MpmdPipelineStrategy(ShardingStrategy):
    """Pipeline parallelism as N per-stage programs over DCN.

    ``config`` is an :class:`~ray_lightning_tpu.mpmd.config.MpmdConfig`
    (or dict / None — ``None`` resolves the ``RLT_MPMD*`` env knobs,
    which is what the string form ``Trainer(strategy="mpmd")`` does).
    The comm plane's gradient compression never applies (there is no
    cross-replica gradient sync to compress — the codec rides the
    ACTIVATION channel instead, ``MpmdConfig.codec``).
    """

    name = "mpmd"
    comm_compressible = False

    def __init__(self, config=None):
        from ray_lightning_tpu.mpmd.config import MpmdConfig
        self.config = MpmdConfig.resolve(config)

    def step_collective_bytes(self, mesh, abstract_state,
                              comm=None) -> dict:
        """Declared per-step fabric traffic: the activation/activation-
        grad exchange over the stage-boundary (DCN) links at the
        configured codec's wire size.  ``abstract_state`` gives no
        activation shape, so this declaration is filled in by the
        engine (``trainer._mpmd_report['activation_bytes_per_step']``
        is the authoritative number); here the op is declared with the
        boundary COUNT so the planner's per-link scoring sees a DCN op
        exists even aval-free."""
        del mesh, abstract_state, comm
        return {"activation_exchange_dcn": 0}

    def __repr__(self):
        c = self.config
        return (f"MpmdPipelineStrategy(stages={c.stages}, "
                f"schedule={c.schedule!r}, micro={c.microbatches}, "
                f"codec={c.codec!r})")
