"""MPMD pipeline runtime: N cooperating per-stage programs.

The inversion vs parallel/pipeline.py: there the whole pipeline is ONE
SPMD program (every host traces and compiles the full model, the GPipe
schedule is frozen at trace time); here each chunk compiles ONLY its
own layer slice (through the active persistent compile cache — much
smaller programs, so the cache win multiplies) and a DRIVER-side
schedule (mpmd/schedule.py) decides the microbatch order — orders SPMD
tracing cannot express.  Activations/activation-grads cross chunk
boundaries over the activation channel (mpmd/channel.py) with the comm
plane's codecs optionally on the wire.

Two execution shapes, same programs, same schedule, same channel:

- **in-process** (default): every chunk lives in this process and ops
  execute serially in the schedule's dependency order — the CPU-proxy
  mode (bubble fractions are therefore SIMULATED by replaying the
  schedule under measured per-op seconds, the same traced-model
  discipline the SPMD pipeline's byte accounting uses; real-fabric
  wall numbers are the ROADMAP follow-on).
- **actors** (``MpmdConfig(actors=True)``): one cluster-backend actor
  per stage rank, each compiling only its chunks and blocking on peer
  channel receives — the true MPMD-over-DCN shape; one RPC per stage
  per step.

Tied weights (GPT's ``wte``): the last chunk holds a mirror for the
head; its gradient ships to the owning chunk 0 over the channel before
the optimizer step and the updated value ships back after — the
Megatron tied-embedding exchange, here as ordinary channel traffic.

Per-op spans (``mpmd_fwd``/``mpmd_bwd`` with stage/mb attrs) ride the
trace plane; the bubble/compile/byte summary lands on
``trainer._mpmd_report`` for the bench and tests.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.mpmd import channel as chan
from ray_lightning_tpu.mpmd import partition as part_mod
from ray_lightning_tpu.mpmd import schedule as sched_mod
from ray_lightning_tpu.telemetry import counter as _tcounter, span
from ray_lightning_tpu.telemetry import metrics as _metrics

_log = logging.getLogger(__name__)


def _micro_split(batch, n_micro: int):
    """Split every array leaf's leading dim into ``n_micro`` slices."""
    leaves = jax.tree_util.tree_leaves(batch)
    b = leaves[0].shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch size {b} does not divide into {n_micro} "
            f"microbatches (RLT_MPMD_MICRO)")
    mb = b // n_micro
    return [jax.tree_util.tree_map(lambda x: x[m * mb:(m + 1) * mb],
                                   batch)
            for m in range(n_micro)]


class ChunkRunner:
    """One chunk's live state + program dispatch (both exec shapes)."""

    def __init__(self, chunk: int, n_chunks: int, partition, programs,
                 params, tx, config, channel, rank: Optional[int] = None):
        self.chunk = chunk
        self.n_chunks = n_chunks
        self.partition = partition
        self.programs = programs
        self.params = params
        self.tx = tx
        self.tx_state = tx.init(params)
        self.config = config
        self.channel = channel
        self.rank = rank if rank is not None else chunk
        self.codec = chan.make_codec(config)
        self.stash: dict = {}      # mb -> (input activation, batch|None)
        self.acc = None            # accumulated dparams
        self.losses: list = []
        self.sent_bytes = 0
        self._apply = jax.jit(self._apply_fn)

    @property
    def opt_state(self) -> dict:
        """Optimizer state as stored/checkpointed: the channel codec's
        error-feedback residuals ride NEXT TO the tx state — the comm
        plane's CommState pattern applied to the activation path."""
        return {"tx": self.tx_state,
                "channel_ef": self.codec.state_dict()}

    def load_opt_state(self, state: dict) -> None:
        self.tx_state = state["tx"]
        self.codec.load_state_dict(state.get("channel_ef", {}))

    @property
    def is_first(self) -> bool:
        return self.chunk == 0

    @property
    def is_last(self) -> bool:
        return self.chunk == self.n_chunks - 1

    def _who(self) -> str:
        return f"stage rank {self.rank} (chunk {self.chunk})"

    def _send(self, dst: int, kind: str, mb: int, step: int, x) -> None:
        wire = self.codec.encode(chan.ef_slot(kind, mb), x)
        self.sent_bytes += sum(
            np.asarray(v).nbytes for v in wire.values()
            if isinstance(v, np.ndarray))
        self.channel.send(dst, chan.payload_tag(kind, dst, mb, step),
                          wire)

    def _recv(self, kind: str, mb: int, step: int, src: int):
        wire = self.channel.recv(
            self.chunk, chan.payload_tag(kind, self.chunk, mb, step),
            who=self._who(), src=f"chunk {src}")
        return chan.ChannelCodec.decode(wire)

    def _send_raw(self, dst: int, kind: str, step: int, items) -> None:
        """Codec-free control payloads (the tied-weight exchange ships
        exact — quantizing a weight update would desynchronize the
        mirror; the codec is an ACTIVATION-path tool)."""
        items = [np.asarray(v) for v in items]
        self.sent_bytes += sum(v.nbytes for v in items)
        self.channel.send(dst, chan.payload_tag(kind, dst, 0, step),
                          items)

    def _recv_raw(self, kind: str, step: int, src: int):
        return self.channel.recv(
            self.chunk, chan.payload_tag(kind, self.chunk, 0, step),
            who=self._who(), src=f"chunk {src}")

    # -- schedule ops ----------------------------------------------------

    def forward(self, mb: int, step: int, micro_batch=None) -> None:
        with span("mpmd_fwd", stage=self.rank, chunk=self.chunk, mb=mb):
            if self.is_first:
                x = micro_batch[0] if isinstance(
                    micro_batch, (tuple, list)) else micro_batch
            else:
                x = self._recv("fwd", mb, step, self.chunk - 1)
            self.stash[mb] = (x, micro_batch if self.is_last else None)
            if self.is_last:
                loss = self.programs["fwd"](self.params, x, micro_batch)
                self.losses.append(loss)
            else:
                h = self.programs["fwd"](self.params, x)
                self._send(self.chunk + 1, "fwd", mb, step, h)

    def backward(self, mb: int, step: int) -> None:
        with span("mpmd_bwd", stage=self.rank, chunk=self.chunk, mb=mb):
            x, batch = self.stash.pop(mb)
            if self.is_last:
                _, dp, dh = self.programs["bwd"](self.params, x, batch)
                self._send(self.chunk - 1, "bwd", mb, step, dh)
            elif self.is_first:
                g = self._recv("bwd", mb, step, self.chunk + 1)
                dp = self.programs["bwd"](self.params, x, g)
            else:
                g = self._recv("bwd", mb, step, self.chunk + 1)
                dp, dx = self.programs["bwd"](self.params, x, g)
                self._send(self.chunk - 1, "bwd", mb, step, dx)
            self.acc = dp if self.acc is None else \
                jax.tree_util.tree_map(jnp.add, self.acc, dp)

    # -- step boundary ---------------------------------------------------

    def _apply_fn(self, params, tx_state, acc):
        import optax
        grads = jax.tree_util.tree_map(
            lambda g: g / self.config.microbatches, acc)
        updates, new_tx = self.tx.update(grads, tx_state, params)
        return optax.apply_updates(params, updates), new_tx

    def exchange_tied_grads(self, step: int) -> None:
        """Pre-apply: the head mirror's grads ship to the owner (chunk
        0), which folds them into its accumulator — the full-model
        tied gradient is the sum of both ends' contributions."""
        tied = self.partition.spec.tied_keys
        if not tied or self.n_chunks < 2:
            return
        if self.is_last:
            self._send_raw(0, "tied_grad", step,
                           [self.acc[k] for k in tied])
        if self.is_first:
            vals = self._recv_raw("tied_grad", step, self.n_chunks - 1)
            for k, g in zip(tied, vals):
                self.acc[k] = self.acc[k] + jnp.asarray(
                    g, self.acc[k].dtype)

    def apply(self) -> float:
        self.params, self.tx_state = self._apply(
            self.params, self.tx_state, self.acc)
        self.acc = None
        loss = (float(np.mean([np.asarray(v) for v in self.losses]))
                if self.losses else 0.0)
        self.losses = []
        return loss

    def broadcast_tied_values(self, step: int) -> None:
        """Post-apply: the owner's freshly updated tied leaves
        overwrite the head mirror, keeping the tie exact (the mirror's
        own optimizer update is dead weight by construction)."""
        tied = self.partition.spec.tied_keys
        if not tied or self.n_chunks < 2:
            return
        if self.is_first:
            self._send_raw(self.n_chunks - 1, "tied_val", step,
                           [self.params[k] for k in tied])
        if self.is_last:
            vals = self._recv_raw("tied_val", step, 0)
            self.params = dict(self.params)
            for k, v in zip(tied, vals):
                self.params[k] = jnp.asarray(v, self.params[k].dtype)


# -- program compilation ----------------------------------------------------


def compile_chunk(partition, chunk: int, h_aval, micro_aval,
                  x_aval) -> "tuple[dict, dict]":
    """Build + AOT-compile one chunk's fwd/bwd through the active
    persistent cache (``lower().compile()`` writes the entry; the
    first dispatch is a disk retrieval — the compile/aot.py contract).
    Returns ``(programs, info)`` with per-program compile seconds and
    HLO text sizes for the report and the per-stage-program tests."""
    programs = part_mod.build_chunk_programs(partition, chunk)
    pa = partition.chunk_param_avals[chunk]
    first, last = chunk == 0, chunk == partition.n_chunks - 1
    if last:
        sigs = {"fwd": (pa, h_aval, micro_aval),
                "bwd": (pa, h_aval, micro_aval)}
    elif first:
        sigs = {"fwd": (pa, x_aval), "bwd": (pa, x_aval, h_aval)}
    else:
        sigs = {"fwd": (pa, h_aval), "bwd": (pa, h_aval, h_aval)}
    info: dict = {"compile_seconds": {}, "hlo_bytes": {}}
    for name, args in sigs.items():
        t0 = time.monotonic()
        compiled = programs[name].lower(*args).compile()
        dt = time.monotonic() - t0
        info["compile_seconds"][name] = dt
        try:
            info["hlo_bytes"][name] = len(compiled.as_text())
        except Exception:   # noqa: BLE001 - text dump optional
            info["hlo_bytes"][name] = 0
        _tcounter("mpmd_compile_seconds", dt, chunk=chunk, program=name)
    return programs, info


def _prepare(trainer, module, example_batch, config):
    """Everything both exec shapes share: spec, planner-scored cuts,
    partition, schedule, full init params (same rng derivation as the
    SPMD trainer — parity by construction), per-chunk avals."""
    if getattr(trainer, "gradient_clip_val", None):
        raise ValueError(
            "strategy='mpmd' does not support gradient_clip_val: "
            "per-stage programs cannot take a global grad norm without "
            "an extra cross-stage reduction (unimplemented)")
    if getattr(trainer, "accumulate_grad_batches", 1) > 1:
        raise ValueError(
            "strategy='mpmd' expresses accumulation as its microbatch "
            "schedule; set MpmdConfig.microbatches instead of "
            "accumulate_grad_batches")
    spec = part_mod.spec_of(module)
    tx = trainer._configure_tx(module)

    from ray_lightning_tpu.core.steps import build_init_fn
    init_fn = build_init_fn(module, tx)
    rng = jax.random.PRNGKey(
        int(os.environ.get("RLT_GLOBAL_SEED", "0"))
        if trainer.seed is None else trainer.seed)
    state0 = jax.jit(init_fn)(rng, example_batch)
    full_params = state0.params

    micro = _micro_split(example_batch, config.microbatches)[0]
    x0 = micro[0] if isinstance(micro, (tuple, list)) else micro
    x_aval = jax.ShapeDtypeStruct(np.asarray(x0).shape,
                                  np.asarray(x0).dtype)
    micro_aval = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                       np.asarray(v).dtype), micro)
    embed_params = {k: full_params[k] for k in spec.embed_keys}
    h_shape = jax.eval_shape(spec.embed_fn, embed_params, x_aval)
    h_aval = jax.ShapeDtypeStruct(h_shape.shape, h_shape.dtype)

    # planner-scored cuts: boundary activation bytes at the DCN link,
    # stage balance as tie-breaker (mpmd/partition.py score_cuts)
    layer_bytes = sum(
        int(np.prod(v.shape[1:], dtype=np.int64)) * v.dtype.itemsize
        for v in jax.tree_util.tree_leaves(full_params[spec.stacked_key]))
    boundary_bytes = int(np.prod(h_aval.shape, dtype=np.int64)
                         ) * h_aval.dtype.itemsize
    cuts = part_mod.resolve_cuts(
        spec.n_layers, config.stages, config.cuts,
        layer_bytes=layer_bytes, boundary_bytes=boundary_bytes,
        n_micro=config.microbatches, codec=config.codec,
        block_size=config.block_size,
        plan_config=getattr(trainer, "plan", None))

    even = (tuple(spec.n_layers // config.stages * s
                  for s in range(1, config.stages))
            if spec.n_layers % config.stages == 0 else None)
    lps = (spec.n_layers // config.stages
           if even is not None and cuts == even else 1)
    virtual = sched_mod.resolve_virtual(config.schedule, config.virtual,
                                        lps, config.microbatches)
    partition = part_mod.build_partition(spec, cuts, virtual)
    partition.chunk_param_avals = [
        jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype),
            partition.chunk_params(full_params, c))
        for c in range(partition.n_chunks)]
    schedule = sched_mod.build_schedule(config.schedule, config.stages,
                                        config.microbatches, virtual)
    return (spec, tx, full_params, partition, schedule, cuts,
            h_aval, micro_aval, x_aval, boundary_bytes)


# -- in-process fit ---------------------------------------------------------


def run_mpmd_fit(trainer, module, loaders, example_batch):
    """The fit loop behind ``Trainer(strategy='mpmd')``.  Honors
    max_steps / max_epochs / limit_train_batches and the train-loop
    callback surface the harness and tests use.  Validation inside an
    MPMD fit is not run — evaluate with a non-mpmd strategy (same
    math: without a stage axis the model is a plain sequential
    scan)."""
    strategy = trainer.plugin.strategy
    config = strategy.config
    if config.actors:
        return _run_actor_fit(trainer, module, loaders, example_batch,
                              config)

    (spec, tx, full_params, partition, schedule, cuts, h_aval,
     micro_aval, x_aval, boundary_bytes) = _prepare(
        trainer, module, example_batch, config)

    channel = chan.InProcessChannel(partition.n_chunks,
                                    timeout_s=config.timeout_s)
    runners: list = []
    compile_info: list = []
    with span("compile"):
        for c in range(partition.n_chunks):
            programs, info = compile_chunk(partition, c, h_aval,
                                           micro_aval, x_aval)
            runners.append(ChunkRunner(
                c, partition.n_chunks, partition, programs,
                partition.chunk_params(full_params, c), tx, config,
                channel, rank=schedule.rank_of(c)))
            compile_info.append(info)

    # metrics plane: the activation exchange is this strategy's per-step
    # fabric traffic — charged per executed step like a strategy's
    # declared collectives, all of it DCN (the links the cuts minimize)
    act_bytes = part_mod.activation_wire_bytes(
        boundary_bytes, partition.n_chunks - 1, config.microbatches,
        codec=config.codec, block_size=config.block_size)
    if _metrics.metrics_enabled():
        _metrics.note_step_collectives(
            {"activation_exchange_dcn": act_bytes}, dcn_bytes=act_bytes)

    # dependency-feasible global order for serial in-process execution
    exec_order = sorted(
        schedule.ends, key=lambda op: (schedule.starts[op],
                                       schedule.rank_of(op.chunk)))
    op_times: dict = {}

    def run_step(batch, step_idx: int) -> float:
        micros = _micro_split(batch, config.microbatches)
        for op in exec_order:
            t0 = time.perf_counter()
            if op.kind == "F":
                runners[op.chunk].forward(op.mb, step_idx,
                                          micros[op.mb])
            else:
                runners[op.chunk].backward(op.mb, step_idx)
            dt = time.perf_counter() - t0
            key = (op.chunk, op.kind)
            op_times[key] = (dt if key not in op_times
                             else 0.5 * op_times[key] + 0.5 * dt)
        runners[-1].exchange_tied_grads(step_idx)
        runners[0].exchange_tied_grads(step_idx)
        losses = [r.apply() for r in runners]
        runners[0].broadcast_tied_values(step_idx)
        runners[-1].broadcast_tied_values(step_idx)
        return losses[-1]

    result = _drive_loop(trainer, module, loaders, run_step, config)

    # bubble attribution: replay BOTH schedules under the measured
    # per-op seconds (module docstring: simulated — the serial
    # in-process proxy cannot exhibit real overlap).  GPipe is always
    # the un-interleaved classic, so when this run executed v>1 chunks
    # its replay needs STAGE-level times: a stage's op is the sum of
    # its chunks' measured ops (chunks c, c+S, ... share rank c%S).
    def _stage_times() -> dict:
        agg: dict = {}
        for (c, k), dt in op_times.items():
            key = (c % config.stages, k)
            agg[key] = agg.get(key, 0.0) + dt
        return agg

    bubbles = {}
    for kind in ("gpipe", "1f1b"):
        v = schedule.virtual if kind == "1f1b" else 1
        s = sched_mod.build_schedule(kind, config.stages,
                                     config.microbatches, v)
        if op_times:
            s = sched_mod.simulate(
                s, op_times if v == schedule.virtual
                else _stage_times())
        bubbles[kind] = s.to_dict()
        _tcounter("mpmd_bubble_fraction",
                  bubbles[kind]["bubble_fraction"], schedule=kind)
        reg = _metrics.get_registry()
        if reg is not None:
            # per-schedule simulated bubble seconds/step, attributable
            # next to the step-time series
            reg.gauge("rlt_mpmd_bubble_seconds").set(
                bubbles[kind]["bubble_fraction"]
                * bubbles[kind]["makespan"], schedule=kind)

    merged = partition.merge_params([r.params for r in runners])
    from ray_lightning_tpu.core.state import TrainState
    trainer.state = TrainState.create(
        merged, {}, {f"chunk{r.chunk}": r.opt_state for r in runners},
        jax.random.PRNGKey(0))
    trainer._mpmd_report = {
        "mode": "in-process",
        "stages": config.stages,
        "virtual": schedule.virtual,
        "cuts": list(cuts),
        "schedule": config.schedule,
        "microbatches": config.microbatches,
        "codec": config.codec,
        "per_stage_compile_seconds": [
            round(sum(i["compile_seconds"].values()), 4)
            for i in compile_info],
        "per_stage_hlo_bytes": [dict(i["hlo_bytes"])
                                for i in compile_info],
        "per_stage_param_elements": [
            partition.params_elements(r.params) for r in runners],
        "bubble": bubbles,
        "activation_bytes_per_step": part_mod.activation_wire_bytes(
            boundary_bytes, partition.n_chunks - 1, config.microbatches,
            codec=config.codec, block_size=config.block_size),
        "sent_bytes_per_stage": [r.sent_bytes for r in runners],
    }
    return result


def _drive_loop(trainer, module, loaders, run_step, config):
    """Shared epoch/step loop + the callback surface for both exec
    shapes (setup, on_train_epoch_start/end, on_train_batch_end,
    on_train_end, teardown — what the bench harness and the tests'
    tracking callbacks consume)."""
    for cb in trainer.callbacks:
        cb.setup(trainer, module, "fit")
    try:
        step_idx = 0
        for epoch in range(trainer.max_epochs or 10**9):
            trainer.current_epoch = epoch
            if trainer.max_steps >= 0 and step_idx >= trainer.max_steps:
                break
            for cb in trainer.callbacks:
                cb.on_train_epoch_start(trainer, module)
            for i, batch in enumerate(loaders["train"]):
                if trainer.limit_train_batches is not None \
                        and i >= trainer.limit_train_batches:
                    break
                if trainer.max_steps >= 0 \
                        and step_idx >= trainer.max_steps:
                    break
                t0 = time.monotonic()
                with span("step", step=step_idx):
                    loss = run_step(jax.tree_util.tree_map(
                        np.asarray, batch), step_idx)
                if trainer.time_to_first_step is None \
                        and trainer._stage_t0 is not None:
                    trainer.time_to_first_step = (time.monotonic()
                                                  - trainer._stage_t0)
                _metrics.on_step(time.monotonic() - t0, step=step_idx)
                step_idx += 1
                trainer.global_step = step_idx
                trainer.callback_metrics["loss"] = loss
                metrics = {"loss": np.float32(loss)}
                for cb in trainer.callbacks:
                    cb.on_train_batch_end(trainer, module, metrics,
                                          batch, i)
            for cb in trainer.callbacks:
                cb.on_train_epoch_end(trainer, module)
        for cb in trainer.callbacks:
            cb.on_train_end(trainer, module)
    finally:
        for cb in trainer.callbacks:
            cb.teardown(trainer, module, "fit")
    return trainer


# -- actor fit --------------------------------------------------------------


class _ActorTrainerShim:
    """The slice of Trainer that ``_prepare`` reads, worker-side."""

    gradient_clip_val = None
    accumulate_grad_batches = 1
    plan = None

    def __init__(self, seed: int = 0):
        self.seed = seed

    def _configure_tx(self, module, grad_sync=None):
        tx = module.configure_optimizers()
        return tx["optimizer"] if isinstance(tx, dict) else tx


class MpmdStageActor:
    """One stage rank as a cluster-backend actor: builds ONLY its
    chunks' programs (the per-stage compile the whole plane exists
    for), executes its per-rank op queue per step, exchanging payloads
    over the peer channel (cluster/backend.py).  One ``run_step`` RPC
    per stage per step; first/last ranks receive the host batch,
    middles run from the channel alone."""

    def __init__(self, rank: int, module, config, peer_names,
                 seed: int = 0):
        self.rank = rank
        self.config = config
        self._module = module
        self._peer_names = list(peer_names)
        self._seed = seed
        module.setup_model()

    def setup(self, example_batch):
        """Deferred heavy init (jax init + per-chunk compiles) so actor
        construction stays cheap and failures carry call context."""
        (spec, tx, full_params, partition, schedule, cuts, h_aval,
         micro_aval, x_aval, _bb) = _prepare(
            _ActorTrainerShim(self._seed), self._module,
            example_batch, self.config)
        self.partition, self.schedule = partition, schedule
        my_chunks = [c for c in range(partition.n_chunks)
                     if schedule.rank_of(c) == self.rank]
        channel = chan.PeerChannel(
            my_chunks,
            {c: self._peer_names[schedule.rank_of(c)]
             for c in range(partition.n_chunks)},
            timeout_s=self.config.timeout_s, rank=self.rank)
        self.runners = {}
        info = {}
        for c in my_chunks:
            programs, ci = compile_chunk(partition, c, h_aval,
                                         micro_aval, x_aval)
            self.runners[c] = ChunkRunner(
                c, partition.n_chunks, partition, programs,
                partition.chunk_params(full_params, c), tx,
                self.config, channel, rank=self.rank)
            info[c] = ci
        self.ops = self.schedule.ranks[self.rank]
        return {"rank": self.rank, "chunks": my_chunks,
                "cuts": list(cuts), "virtual": schedule.virtual,
                "compile_seconds": {
                    c: i["compile_seconds"] for c, i in info.items()},
                "param_elements": {
                    c: partition.params_elements(self.runners[c].params)
                    for c in my_chunks}}

    def run_step(self, step_idx: int, batch=None):
        micros = (_micro_split(jax.tree_util.tree_map(np.asarray, batch),
                               self.config.microbatches)
                  if batch is not None else None)
        for op in self.ops:
            r = self.runners[op.chunk]
            if op.kind == "F":
                mbatch = (micros[op.mb] if micros is not None
                          and (r.is_first or r.is_last) else None)
                r.forward(op.mb, step_idx, mbatch)
            else:
                r.backward(op.mb, step_idx)
        for r in self.runners.values():
            r.exchange_tied_grads(step_idx)
        losses = {c: r.apply() for c, r in self.runners.items()}
        for r in self.runners.values():
            r.broadcast_tied_values(step_idx)
        last = self.partition.n_chunks - 1
        return {"rank": self.rank, "loss": losses.get(last)}

    def chunk_params(self):
        """chunk -> host param tree (driver merges the full model)."""
        return {c: jax.tree_util.tree_map(np.asarray, r.params)
                for c, r in self.runners.items()}

    def ping(self):
        return self.rank

    def __rlt_peer_deliver__(self, item):
        """Ray-backend peer delivery (runs on a concurrent actor
        thread — the driver creates stage actors with
        max_concurrency >= 2; the builtin backend delivers via its
        peer frames instead and never calls this)."""
        from ray_lightning_tpu.cluster import worker_state
        worker_state.peer_push(item)
        return True


def _run_actor_fit(trainer, module, loaders, example_batch, config):
    """Driver side of the actor shape: one stage actor per rank over
    the cluster backend, setup (each compiles only its own chunks),
    then one ``run_step`` fan-out per optimizer step."""
    import uuid

    from ray_lightning_tpu.cluster.backend import get_backend

    backend = get_backend()
    run_tag = uuid.uuid4().hex[:8]
    names = [f"rlt-mpmd-{os.getpid()}-{run_tag}-s{r}"
             for r in range(config.stages)]
    env = {"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
           # rng lowering must match the driver's: stage actors re-run
           # the same PRNGKey(seed) init, and a flag mismatch here
           # would draw DIFFERENT (equally random) initial kernels
           "JAX_THREEFRY_PARTITIONABLE":
               str(bool(jax.config.jax_threefry_partitionable)).lower(),
           **config.worker_env()}
    seed = 0 if trainer.seed is None else trainer.seed
    actors = []
    try:
        for r in range(config.stages):
            actors.append(backend.create_actor(
                MpmdStageActor, r, module, config, names, seed,
                env=env, name=names[r], max_concurrency=2))
        eb = jax.tree_util.tree_map(np.asarray, example_batch)
        setup_info = [f.result(timeout=600)
                      for f in [a.call("setup", eb) for a in actors]]
        cuts = tuple(setup_info[0]["cuts"])
        virtual = int(setup_info[0]["virtual"])

        def run_step(batch, step_idx):
            futs = [a.call("run_step", step_idx,
                           batch if r in (0, config.stages - 1)
                           else None)
                    for r, a in enumerate(actors)]
            out = [f.result(timeout=config.timeout_s * 4)
                   for f in futs]
            losses = [o["loss"] for o in out if o["loss"] is not None]
            return float(losses[-1]) if losses else 0.0

        result = _drive_loop(trainer, module, loaders, run_step, config)

        chunk_params: dict = {}
        for a in actors:
            chunk_params.update(
                a.call("chunk_params").result(timeout=600))
        partition = part_mod.build_partition(part_mod.spec_of(module),
                                             cuts, virtual)
        merged = partition.merge_params(
            [chunk_params[c] for c in sorted(chunk_params)])
        from ray_lightning_tpu.core.state import TrainState
        trainer.state = TrainState.create(merged, {}, {},
                                          jax.random.PRNGKey(0))
        trainer._mpmd_report = {
            "mode": "actors",
            "stages": config.stages,
            "virtual": virtual,
            "cuts": list(cuts),
            "schedule": config.schedule,
            "microbatches": config.microbatches,
            "codec": config.codec,
            "setup": setup_info,
        }
        return result
    finally:
        for a in actors:
            try:
                a.kill()
            except Exception:   # noqa: BLE001 - teardown best-effort
                pass
