"""MPMD pipeline plane: per-stage programs over DCN.

Pipeline-parallel training as N cooperating per-stage programs instead
of one SPMD program (ROADMAP item 1; "Scaling Deep Learning Training
with MPMD Pipeline Parallelism", PAPERS.md 2412.14374):

- ``partition.py`` — contiguous layer slices from an explicit cut list
  or the planner's scored choice; per-chunk params + fwd/bwd programs
  whose arguments are ONLY that chunk's layers (each host compiles a
  fraction of the model, through the persistent compile cache);
- ``channel.py`` — stage(i)↔stage(i+1) activation/activation-grad
  exchange with the comm plane's fp8/int4/int8/bf16 codecs + error
  feedback on the payloads, out-of-order-safe mailboxes, dead-peer
  timeouts that name the stage;
- ``schedule.py`` — GPipe and 1F1B (auto-interleaved over virtual
  chunks) as driver-side microbatch schedules with a bubble
  simulator;
- ``engine.py`` — the runtime: in-process proxy mode and per-stage
  cluster actors over the worker↔worker peer channel;
- ``strategy.py`` — ``Trainer(strategy="mpmd")`` + ``RLT_MPMD*`` env
  knobs (config.py).
"""

from ray_lightning_tpu.mpmd.config import MpmdConfig  # noqa: F401
from ray_lightning_tpu.mpmd.strategy import (  # noqa: F401
    MpmdPipelineStrategy,
)

__all__ = ["MpmdConfig", "MpmdPipelineStrategy"]
