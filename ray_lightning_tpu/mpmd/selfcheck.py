"""MPMD-plane selfcheck (wired into ``format.sh --check``).

Asserts the invariants that don't need a training run:

- schedule invariants on a grid of (stages, microbatches, virtual):
  every microbatch's F before its B per chunk, dependency order holds
  globally, 1F1B in-flight depth <= stages x virtual; plain 1F1B's
  bubble TIES GPipe's (the analytic fact the schedule docstring pins)
  while interleaved 1F1B beats it on >= 4 microbatches;
- RLT_MPMD* env knobs round-trip through ``worker_env()`` →
  ``resolve()`` unchanged, and invalid configs raise;
- channel codec round-trip: exact for representable payloads, bounded
  error + error-feedback residual update for fp8/int4, out-of-order
  mailbox delivery, and the dead-peer timeout raising with the
  stage/rank in the message;
- stage-cut enumeration/resolution sanity (even split wins on uniform
  layers; explicit bad cuts raise);
- the MpmdPipelineStrategy resolves via ``Trainer(strategy="mpmd")``'s
  registry path and declines the comm plane's gradient compression;
- the mpmd metric name is on the telemetry lint surface.
"""

from __future__ import annotations

import os


def _main(argv) -> int:   # noqa: ARG001 - argv kept for parity
    import numpy as np

    from ray_lightning_tpu.cluster.peer import Mailbox, PeerTimeout
    from ray_lightning_tpu.mpmd import channel as chan
    from ray_lightning_tpu.mpmd import partition as part
    from ray_lightning_tpu.mpmd import schedule as sched
    from ray_lightning_tpu.mpmd.config import MpmdConfig

    problems: list[str] = []

    # 1. schedule invariants + the bubble facts
    for stages, micro, virtual in ((2, 4, 1), (2, 8, 2), (4, 8, 1),
                                   (3, 6, 1), (2, 4, 2)):
        for kind in ("gpipe", "1f1b"):
            try:
                s = sched.build_schedule(kind, stages, micro, virtual)
                sched.validate(s)
            except Exception as e:   # noqa: BLE001 - report, don't crash
                problems.append(
                    f"schedule {kind} S={stages} M={micro} v={virtual} "
                    f"invalid: {e!r}")
    try:
        tie_g = sched.build_schedule("gpipe", 2, 4, 1).bubble_fraction
        tie_f = sched.build_schedule("1f1b", 2, 4, 1).bubble_fraction
        if abs(tie_g - tie_f) > 1e-9:
            problems.append(
                f"plain 1f1b bubble {tie_f} != gpipe {tie_g} (the "
                f"documented analytic tie broke)")
        inter = sched.build_schedule("1f1b", 2, 4, 2).bubble_fraction
        if not inter < tie_g:
            problems.append(
                f"interleaved 1f1b bubble {inter} not below gpipe "
                f"{tie_g} on 4 microbatches")
    except Exception as e:   # noqa: BLE001
        problems.append(f"bubble comparison failed: {e!r}")

    # 2. env round-trip + validation
    src = MpmdConfig(stages=3, cuts=(2, 5), schedule="gpipe",
                     microbatches=6, virtual=1, codec="fp8",
                     block_size=32, error_feedback=False, actors=True,
                     timeout_s=7.5)
    saved = {k: os.environ.get(k) for k in src.worker_env()}
    os.environ.update(src.worker_env())
    try:
        if MpmdConfig.resolve(None) != src:
            problems.append("RLT_MPMD* env round-trip changed the config")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    for bad in (dict(stages=1), dict(schedule="zb"), dict(codec="int2"),
                dict(codec="int4", block_size=33),
                dict(stages=2, cuts=(1, 2))):
        try:
            MpmdConfig(**bad)
            problems.append(f"MpmdConfig({bad}) should have raised")
        except ValueError:
            pass

    # 3. channel: codec round-trip, EF residual, out-of-order, timeout
    x = np.linspace(-1, 1, 256, dtype=np.float32).reshape(2, 128)
    for mode in ("none", "bf16", "fp8", "int8", "int4"):
        codec = chan.ChannelCodec(mode, block_size=64)
        wire = codec.encode(("fwd", 0, 0, 0), x)
        out = np.asarray(chan.ChannelCodec.decode(wire), np.float32)
        tol = {"none": 0.0, "bf16": 0.01, "fp8": 0.08, "int8": 0.02,
               "int4": 0.16}[mode]
        if np.max(np.abs(out - x)) > tol:
            problems.append(
                f"codec {mode} round-trip error "
                f"{np.max(np.abs(out - x)):.4f} > {tol}")
        if codec.error_feedback:
            if not codec.state_dict():
                problems.append(f"codec {mode}: EF residual not carried")
    box = Mailbox()
    box.put(("fwd", 0, 1, 0), "late-first")
    box.put(("fwd", 0, 0, 0), "early-second")
    if box.take(("fwd", 0, 0, 0), 1.0) != "early-second":
        problems.append("mailbox out-of-order take failed")
    try:
        box.take(("bwd", 0, 0, 0), 0.05, who="stage rank 1 (chunk 1)",
                 src="chunk 0")
        problems.append("dead-peer timeout did not raise")
    except PeerTimeout as e:
        if "stage rank 1" not in str(e):
            problems.append(f"timeout error does not name the stage: {e}")

    # 4. cuts
    if part.resolve_cuts(8, 4, None) != (2, 4, 6):
        problems.append("even split is not the default planner choice")
    try:
        part.resolve_cuts(4, 2, (5,))
        problems.append("out-of-range cut should have raised")
    except ValueError:
        pass
    if len(part.enumerate_stage_cuts(6, 3)) != 10:
        problems.append("stage-cut enumeration count off (C(5,2)=10)")

    # 5. strategy resolution + comm plane declines
    from ray_lightning_tpu.parallel.strategy import (resolve_strategy,
                                                     strategy_names)
    strat = resolve_strategy("mpmd")
    if getattr(strat, "name", "") != "mpmd":
        problems.append("resolve_strategy('mpmd') did not resolve")
    if "mpmd" not in strategy_names():
        problems.append("'mpmd' missing from strategy_names()")
    if strat.comm_compressible:
        problems.append("mpmd must decline gradient compression")

    # 6. metric name on the lint surface
    from ray_lightning_tpu.telemetry.metrics import CORE_METRICS
    if "rlt_mpmd_bubble_seconds" not in CORE_METRICS:
        problems.append("rlt_mpmd_bubble_seconds missing from "
                        "telemetry CORE_METRICS")

    for p in problems:
        print(f"mpmd selfcheck: {p}")
    if not problems:
        print("mpmd selfcheck: schedule invariants + bubble facts, env "
              "round-trip, channel codec/EF/out-of-order/timeout, "
              "stage cuts, strategy resolution, and metric names OK")
    return 1 if problems else 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
