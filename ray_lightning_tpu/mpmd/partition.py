"""Stage partitioner: contiguous layer slices → per-chunk params and
per-chunk compiled programs.

The module under MPMD describes itself once through
``LightningModule.configure_mpmd()`` (core/module.py), returning an
:class:`MpmdSpec` — three pure functions (embed / one-layer stage /
head+loss) plus which top-level param keys belong to the embedding and
head and which are *tied* across both ends (GPT's ``wte``).  From that
and a cut list the partitioner builds, per chunk:

- a **param slice**: the stacked-layer leaves' ``[cut_lo:cut_hi]``
  rows, plus the embed keys on chunk 0, the head keys on the last
  chunk, and a *mirror* of each tied key on the last chunk (forward
  needs it there; its gradient is shipped back to the owner over the
  channel and the updated value re-broadcast after the step — the
  Megatron tied-embedding exchange, done here as channel traffic);
- **fwd/bwd jitted programs** over exactly that slice.  Backward
  recomputes the chunk forward under ``jax.vjp`` from the stashed
  input activation, so no residuals cross program boundaries and each
  program's arguments are only its own layers — the per-stage-programs
  property the compile-cache/HLO assertions in tests/test_mpmd.py pin
  (a chunk's program CANNOT compute layers whose params it never
  receives).

Cut selection: an explicit list wins; otherwise :func:`choose_cuts`
enumerates every contiguous composition and scores each with the
planner's cost primitives — boundary activation bytes (codec-aware,
``comm.quant.payload_bytes``) at the ``_dcn`` link bandwidth
(plan/cost.py ``link_gbps``) plus the compute imbalance of the largest
stage — the stage-cut analog of the PR-8 candidate scoring.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MpmdSpec:
    """What a module tells the partitioner (``configure_mpmd``).

    ``embed_fn(embed_params, x) -> h`` lifts the raw batch input into
    the first activation; ``stage_fn(layer_params, h) -> h`` applies
    ONE layer (the partitioner scans it over each chunk's stacked
    slice); ``head_loss_fn(head_params, h, batch) -> loss`` finishes
    the model and reduces to this microbatch's mean loss.  Param keys
    are top-level names in the module's ``init_params`` tree:
    ``stacked_key`` is the layer-stacked subtree (leading dim =
    n_layers on every leaf), ``embed_keys``/``head_keys`` the ends'
    extras, ``tied_keys`` ⊆ embed_keys the leaves the head ALSO reads.
    """

    n_layers: int
    embed_fn: Callable[[Any, Any], Any]
    stage_fn: Callable[[Any, Any], Any]
    head_loss_fn: Callable[[Any, Any, Any], Any]
    stacked_key: str = "blocks"
    embed_keys: tuple = ("wte", "wpe")
    head_keys: tuple = ("ln_f",)
    tied_keys: tuple = ()

    def __post_init__(self):
        bad = [k for k in self.tied_keys if k not in self.embed_keys]
        if bad:
            raise ValueError(
                f"tied_keys {bad} must be embed-owned (embed_keys is "
                f"the ownership side of the tie)")


def spec_of(module) -> MpmdSpec:
    spec = module.configure_mpmd()
    if not isinstance(spec, MpmdSpec):
        raise TypeError(
            f"{type(module).__name__}.configure_mpmd() must return an "
            f"MpmdSpec, got {type(spec).__name__}")
    return spec


# -- cuts -------------------------------------------------------------------


def enumerate_stage_cuts(n_layers: int, n_stages: int) -> "list[tuple]":
    """Every contiguous split of ``n_layers`` into ``n_stages``
    non-empty slices, as ascending boundary tuples (the planner's
    stage-cut candidate space)."""
    if n_stages > n_layers:
        raise ValueError(
            f"{n_layers} layers cannot split into {n_stages} non-empty "
            f"stages")
    return [tuple(c) for c in
            itertools.combinations(range(1, n_layers), n_stages - 1)]


def stage_slices(cuts: Sequence[int], n_layers: int) -> "list[tuple]":
    bounds = [0, *cuts, n_layers]
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]


def score_cuts(cuts: Sequence[int], n_layers: int, *,
               layer_bytes: int, boundary_bytes: int, n_micro: int,
               codec: str = "none", block_size: int = 64,
               plan_config=None, process_count: int = 2) -> tuple:
    """Rank key for one cut list, smaller is better: (modeled step
    comm seconds over the stage-boundary DCN links, largest stage's
    layer count, label).  Uses the planner's own per-link attribution
    — the ``activation_exchange_dcn`` op is scored at the DCN
    bandwidth exactly like a strategy's ``_dcn``-suffixed declaration
    in plan/cost.py — and the comm plane's codec byte model."""
    from ray_lightning_tpu.comm.audit import bytes_to_seconds
    from ray_lightning_tpu.plan.config import PlanConfig
    from ray_lightning_tpu.plan.cost import link_gbps

    config = plan_config or PlanConfig()
    wire = activation_wire_bytes(boundary_bytes, len(cuts), n_micro,
                                 codec=codec, block_size=block_size)
    gbps = link_gbps("activation_exchange_dcn", config, process_count)
    comm_s = bytes_to_seconds(wire, gbps)
    sizes = [hi - lo for lo, hi in stage_slices(cuts, n_layers)]
    return (comm_s, max(sizes) * layer_bytes, tuple(cuts))


def activation_wire_bytes(boundary_bytes: int, n_boundaries: int,
                          n_micro: int, *, codec: str = "none",
                          block_size: int = 64) -> int:
    """Bytes ONE optimizer step pushes across the stage-boundary links:
    every boundary carries each microbatch's activation forward AND its
    activation-grad backward, each at the codec's wire size
    (``payload_bytes`` — the same model the comm plane's declarations
    charge)."""
    if codec == "none":
        per = boundary_bytes
    else:
        from ray_lightning_tpu.comm.quant import payload_bytes
        # boundary payloads travel as fp32-equivalent element counts
        per = payload_bytes(max(1, boundary_bytes // 4), codec, block_size)
    return 2 * n_boundaries * n_micro * per


def resolve_cuts(n_layers: int, n_stages: int,
                 cuts: Optional[Sequence[int]] = None, *,
                 layer_bytes: int = 1, boundary_bytes: int = 1,
                 n_micro: int = 1, codec: str = "none",
                 block_size: int = 64, plan_config=None) -> tuple:
    """Explicit ``cuts`` validated, or the planner's choice: the
    best-scoring contiguous composition (uniform-layer models resolve
    to the even split — the balance term — with the DCN term breaking
    ties toward fewer boundary bytes)."""
    if cuts is not None:
        cuts = tuple(int(c) for c in cuts)
        if len(cuts) != n_stages - 1 or list(cuts) != sorted(set(cuts)) \
                or any(not 0 < c < n_layers for c in cuts):
            raise ValueError(
                f"cuts {cuts} do not split {n_layers} layers into "
                f"{n_stages} non-empty contiguous stages")
        return cuts
    return min(
        enumerate_stage_cuts(n_layers, n_stages),
        key=lambda c: score_cuts(
            c, n_layers, layer_bytes=layer_bytes,
            boundary_bytes=boundary_bytes, n_micro=n_micro, codec=codec,
            block_size=block_size, plan_config=plan_config))


# -- per-chunk params -------------------------------------------------------


@dataclasses.dataclass
class StagePartition:
    """Resolved chunk layout: slices + param selection/merge."""

    spec: MpmdSpec
    slices: list                   # chunk -> (lo, hi) layer bounds

    @property
    def n_chunks(self) -> int:
        return len(self.slices)

    def chunk_params(self, full_params: Any, chunk: int) -> dict:
        """This chunk's param tree: its stacked-layer rows, plus the
        ends' extras (tied keys mirrored onto the last chunk)."""
        lo, hi = self.slices[chunk]
        spec = self.spec
        out: dict = {spec.stacked_key: jax.tree_util.tree_map(
            lambda x: x[lo:hi], full_params[spec.stacked_key])}
        if chunk == 0:
            for k in spec.embed_keys:
                out[k] = full_params[k]
        if chunk == self.n_chunks - 1:
            for k in spec.head_keys:
                out[k] = full_params[k]
            for k in spec.tied_keys:
                out.setdefault(k, full_params[k])
        return out

    def merge_params(self, chunk_trees: Sequence[dict]) -> dict:
        """Inverse of :meth:`chunk_params`: re-stack the layer rows in
        cut order and take each extra key from its OWNER (embed keys —
        tied mirrors on the last chunk are discarded; the engine keeps
        them equal to the owner's value by re-broadcasting after every
        step)."""
        spec = self.spec
        full: dict = {spec.stacked_key: jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0),
            *[t[spec.stacked_key] for t in chunk_trees])}
        for k in spec.embed_keys:
            full[k] = chunk_trees[0][k]
        for k in spec.head_keys:
            if k not in full:
                full[k] = chunk_trees[-1][k]
        return full

    def tied_mirror_grads(self, last_chunk_grads: dict) -> dict:
        return {k: last_chunk_grads[k] for k in self.spec.tied_keys}

    def params_elements(self, chunk_tree: dict) -> int:
        return sum(int(np.prod(x.shape, dtype=np.int64))
                   for x in jax.tree_util.tree_leaves(chunk_tree))


def build_partition(spec: MpmdSpec, cuts: Sequence[int],
                    virtual: int = 1) -> StagePartition:
    """Chunk layout.  ``virtual == 1``: one chunk per stage, sliced at
    ``cuts``.  ``virtual > 1`` (interleaved 1F1B): the layer chain
    splits into ``n_stages × virtual`` EQUAL contiguous chunks in
    layer order, chunk c living on rank ``c % n_stages`` — the
    Megatron interleaved placement, where each round of the forward
    chain crosses every rank once.  Interleaving therefore requires
    the even layout (custom cuts express per-STAGE imbalance, which
    round-robin chunk placement cannot honor — rejected loudly)."""
    n_stages = len(cuts) + 1
    if virtual == 1:
        return StagePartition(spec=spec,
                              slices=list(stage_slices(cuts, spec.n_layers)))
    n_chunks = n_stages * virtual
    if spec.n_layers % n_chunks:
        raise ValueError(
            f"{spec.n_layers} layers do not split into {n_chunks} "
            f"interleaved chunks ({n_stages} stages x {virtual} virtual)")
    even = tuple(spec.n_layers // n_stages * s
                 for s in range(1, n_stages))
    if tuple(cuts) != even:
        raise ValueError(
            f"interleaved schedules need the even stage layout {even}, "
            f"got cuts {tuple(cuts)} (drop virtual or the custom cuts)")
    w = spec.n_layers // n_chunks
    return StagePartition(
        spec=spec, slices=[(c * w, (c + 1) * w) for c in range(n_chunks)])


# -- per-chunk programs -----------------------------------------------------


def _scan_layers(stage_fn, stacked, h):
    def body(carry, p):
        return stage_fn(p, carry), None
    out, _ = jax.lax.scan(body, h, stacked)
    return out


def chunk_forward_fn(part: StagePartition, chunk: int) -> Callable:
    """The pure forward math of one chunk (what both the fwd program
    and the bwd recompute trace): chunk 0 takes the raw batch input,
    the last chunk returns the microbatch loss, middles map h -> h."""
    spec = part.spec
    first = chunk == 0
    last = chunk == part.n_chunks - 1

    def fwd(params, x, batch=None):
        h = spec.embed_fn(params, x) if first else x
        h = _scan_layers(spec.stage_fn, params[spec.stacked_key], h)
        if last:
            return spec.head_loss_fn(params, h, batch)
        return h

    return fwd


def build_chunk_programs(part: StagePartition, chunk: int) -> dict:
    """Jitted fwd/bwd for one chunk (engine compiles them through the
    active persistent cache via ``lower().compile()``).

    Signatures (first/mid/last resolved by position in the chain):

    - fwd: ``(params, x[, batch]) -> h | loss``
    - bwd: ``(params, x, g) -> (dparams[, dx])`` for first/mid —
      recompute-vjp from the stashed input; last:
      ``(params, h, batch) -> (loss, dparams, dh)`` via value_and_grad
      (cotangent 1.0 — the engine divides the accumulator by M at
      apply time).
    """
    fwd = chunk_forward_fn(part, chunk)
    first = chunk == 0
    last = chunk == part.n_chunks - 1

    if last:
        def bwd(params, h, batch):
            loss, (dp, dh) = jax.value_and_grad(
                lambda p, hh: fwd(p, hh, batch), argnums=(0, 1))(params, h)
            return loss, dp, dh

        return {"fwd": jax.jit(fwd), "bwd": jax.jit(bwd)}

    if first:
        def bwd(params, x, g):
            _, vjp = jax.vjp(lambda p: fwd(p, x), params)
            (dp,) = vjp(g)
            return dp
    else:
        def bwd(params, x, g):
            _, vjp = jax.vjp(fwd, params, x)
            dp, dx = vjp(g)
            return dp, dx

    return {"fwd": jax.jit(fwd), "bwd": jax.jit(bwd)}
