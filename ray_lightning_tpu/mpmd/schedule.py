"""Driver-side microbatch schedules for the MPMD pipeline.

A *schedule* is, per rank, an ordered list of ``(chunk, kind, mb)``
ops (``kind`` ∈ {"F", "B"}) that the stage executors run in order,
blocking on their channel receives.  Because the schedule is driver
data — not an SPMD trace — it can express orders the compiled-in GPipe
of parallel/pipeline.py cannot: the 1F1B steady state, interleaved
virtual chunks, and (future) zero-bubble splits.

Both built-in schedules come out of ONE greedy list-scheduler over the
microbatch dependency DAG (``F(c, m)`` after ``F(c-1, m)``;
``B(c, m)`` after ``F(c, m)`` and ``B(c+1, m)``), differing only in
the op-priority rule:

- ``gpipe``: forwards first — every rank runs all M forwards in
  microbatch order, then all M backwards (the classic two-phase
  schedule; what the SPMD pipeline compiles in).
- ``1f1b``: backwards first — a ready backward always preempts a
  forward, which reproduces the Megatron 1F1B warmup/steady-state
  shape and bounds the in-flight (forwarded-but-not-backwarded)
  activation stash at ``n_stages`` instead of GPipe's M.

On the bubble: with one chunk per rank, PLAIN 1F1B's fill/drain
bubble fraction analytically TIES GPipe's — (S-1)(tf+tb) of idle over
a (M+S-1)(tf+tb) makespan for both; what 1F1B buys at v=1 is the
bounded activation stash (``validate`` pins the depth).  The bubble
win comes from *interleaving*: with ``virtual > 1`` chunks per rank
the fill latency per chunk shrinks by ~v while the per-rank work is
unchanged, so the 1f1b priority rule fills former bubble slots with
other chunks' ops.  ``simulate`` makes both claims measurable (and
tests/test_mpmd.py pins the tie AND the interleaved win).

``simulate`` replays a schedule against per-op durations (defaults or
measured, e.g. the engine's compiled-program timings) and returns
per-rank busy/idle plus the op start/end times the engine re-emits as
trace-plane bubble spans.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

#: default duration model: backward ≈ 2× forward (recompute + backprop)
DEFAULT_TF = 1.0
DEFAULT_TB = 2.0


@dataclasses.dataclass(frozen=True)
class Op:
    chunk: int
    kind: str          # "F" | "B"
    mb: int

    def __repr__(self):
        return f"{self.kind}{self.mb}c{self.chunk}"


@dataclasses.dataclass
class Schedule:
    """One resolved schedule: rank-ordered op lists + its simulation."""

    kind: str                      # "gpipe" | "1f1b"
    n_stages: int
    n_micro: int
    virtual: int
    ranks: list                    # rank -> [Op, ...] in execution order
    starts: dict                   # Op -> start time (duration model)
    ends: dict                     # Op -> end time
    makespan: float
    busy: list                     # rank -> busy time

    @property
    def n_chunks(self) -> int:
        return self.n_stages * self.virtual

    def rank_of(self, chunk: int) -> int:
        return chunk % self.n_stages

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of the fleet over the makespan: 1 - busy/(S·T).
        The number the bench compares across schedules."""
        total = self.n_stages * self.makespan
        return 1.0 - sum(self.busy) / total if total > 0 else 0.0

    def rank_bubble_fraction(self, rank: int) -> float:
        return (1.0 - self.busy[rank] / self.makespan
                if self.makespan > 0 else 0.0)

    def to_dict(self) -> dict:
        return {
            "schedule": self.kind,
            "stages": self.n_stages,
            "microbatches": self.n_micro,
            "virtual": self.virtual,
            "makespan": round(self.makespan, 6),
            "bubble_fraction": round(self.bubble_fraction, 4),
            "rank_bubble_fractions": [
                round(self.rank_bubble_fraction(r), 4)
                for r in range(self.n_stages)],
        }


def _deps(op: Op, n_chunks: int):
    if op.kind == "F":
        if op.chunk > 0:
            yield Op(op.chunk - 1, "F", op.mb)
    else:
        yield Op(op.chunk, "F", op.mb)
        if op.chunk < n_chunks - 1:
            yield Op(op.chunk + 1, "B", op.mb)


def build_schedule(kind: str, n_stages: int, n_micro: int,
                   virtual: int = 1,
                   times: Optional[dict] = None) -> Schedule:
    """Greedy list-schedule of the pipeline DAG under ``kind``'s
    priority rule (module docstring).  ``times`` maps ``(chunk, "F"|
    "B") -> seconds`` (defaults: tf=1, tb=2 split evenly over a rank's
    chunks); pass the engine's measured per-program durations to get
    the bubble numbers the bench reports."""
    if kind not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown mpmd schedule {kind!r}")
    if n_stages < 1 or n_micro < 1 or virtual < 1:
        raise ValueError(
            f"bad schedule shape: stages={n_stages} micro={n_micro} "
            f"virtual={virtual}")
    n_chunks = n_stages * virtual

    def dur(chunk: int, k: str) -> float:
        if times and (chunk, k) in times:
            return float(times[(chunk, k)])
        base = DEFAULT_TF if k == "F" else DEFAULT_TB
        return base / virtual

    pending = {Op(c, k, m) for c in range(n_chunks)
               for k in ("F", "B") for m in range(n_micro)}
    ends: dict = {}
    starts: dict = {}
    rank_free = [0.0] * n_stages
    ranks: list = [[] for _ in range(n_stages)]
    busy = [0.0] * n_stages

    def ready(op: Op) -> bool:
        return all(d in ends for d in _deps(op, n_chunks))

    def ready_at(op: Op) -> float:
        return max([ends[d] for d in _deps(op, n_chunks)], default=0.0)

    # priority among a rank's ready ops: gpipe runs forwards first
    # (all F before any B — the two-phase shape); 1f1b ALTERNATES —
    # after a forward prefer a backward and vice versa (the literal
    # one-F-one-B steady state; warmup falls out because no backward
    # is ready yet, cooldown because no forward remains)
    # 1f1b's defining constraint: a rank holds at most S·v in-flight
    # (forwarded, not yet backwarded) microbatch-chunks — it IDLES
    # rather than over-fill (a work-conserving greedy would drift to
    # GPipe's M-deep stash during warmup).  GPipe is uncapped.
    cap = n_stages * virtual if kind == "1f1b" else None
    depth = [0] * n_stages
    last_kind = ["B"] * n_stages   # so warmup prefers F

    def prio(op: Op, rank: int) -> int:
        if kind == "gpipe":
            return 0 if op.kind == "F" else 1
        return 0 if op.kind != last_kind[rank] else 1

    while pending:
        # earliest feasible (rank-free, deps-done, under-cap) op
        # fleet-wide; ties broken by the schedule's priority rule then
        # (mb, chunk) for determinism
        best, best_key = None, None
        for op in pending:
            if not ready(op):
                continue
            rank = op.chunk % n_stages
            if cap is not None and op.kind == "F" and depth[rank] >= cap:
                continue
            t = max(rank_free[rank], ready_at(op))
            key = (t, prio(op, rank), op.mb, op.chunk)
            if best_key is None or key < best_key:
                best, best_key = op, key
        if best is None:   # pragma: no cover - DAG is acyclic
            raise RuntimeError("mpmd schedule deadlocked")
        rank = best.chunk % n_stages
        depth[rank] += 1 if best.kind == "F" else -1
        last_kind[rank] = best.kind
        t0 = best_key[0]
        t1 = t0 + dur(best.chunk, best.kind)
        starts[best] = t0
        ends[best] = t1
        rank_free[rank] = t1
        busy[rank] += t1 - t0
        ranks[rank].append(best)
        pending.discard(best)

    sched = Schedule(kind=kind, n_stages=n_stages, n_micro=n_micro,
                     virtual=virtual, ranks=ranks, starts=starts,
                     ends=ends, makespan=max(rank_free), busy=busy)
    validate(sched)
    return sched


def resolve_virtual(schedule: str, virtual: int, layers_per_stage: int,
                    n_micro: int) -> int:
    """The interleave depth a config's ``virtual=0`` (auto) resolves
    to: 2 when the schedule is 1f1b, every stage's layer slice splits
    evenly and there are enough microbatches for the interleave to pay
    (>= 2); GPipe and explicit values pass through (GPipe never
    auto-interleaves — the classic schedule is the baseline the bench
    diffs against)."""
    if virtual > 0:
        return virtual
    if schedule == "1f1b" and layers_per_stage % 2 == 0 \
            and layers_per_stage >= 2 and n_micro >= 2:
        return 2
    return 1


def validate(sched: Schedule) -> None:
    """Schedule invariants (also run by mpmd/selfcheck.py):

    - every (chunk, mb) runs F exactly once and B exactly once, F
      before B, in a valid dependency order rank-locally and globally;
    - 1f1b only: the per-rank in-flight stash (microbatch-chunks
      forwarded but not yet backwarded) never exceeds ``n_stages`` —
      the bounded-memory property plain 1F1B exists for (GPipe's
      stash legitimately reaches M).
    """
    n_chunks = sched.n_chunks
    seen: dict = {}
    order: dict = {}
    i = 0
    # global replay in simulated start order must respect every dep
    for op in sorted(sched.ends, key=lambda o: (sched.starts[o],
                                                o.chunk % sched.n_stages)):
        order[op] = i
        i += 1
        seen[op] = seen.get(op, 0) + 1
    for op in order:
        for d in _deps(op, n_chunks):
            if d not in order or order[d] >= order[op]:
                raise AssertionError(f"schedule violates dep {d} -> {op}")
    for c in range(n_chunks):
        for m in range(sched.n_micro):
            f, b = Op(c, "F", m), Op(c, "B", m)
            if seen.get(f) != 1 or seen.get(b) != 1:
                raise AssertionError(
                    f"chunk {c} mb {m}: F×{seen.get(f)} B×{seen.get(b)}")
            if sched.starts[b] < sched.ends[f]:
                raise AssertionError(f"B before F for chunk {c} mb {m}")
    if sched.kind == "1f1b":
        for rank, ops in enumerate(sched.ranks):
            depth = 0
            for op in ops:
                depth += 1 if op.kind == "F" else -1
                if depth > sched.n_stages * sched.virtual:
                    raise AssertionError(
                        f"1f1b rank {rank} stash depth {depth} exceeds "
                        f"{sched.n_stages * sched.virtual}")


def simulate(sched: Schedule, times: dict) -> Schedule:
    """Re-simulate an existing schedule's op ORDER under measured
    per-op ``times`` ((chunk, kind) -> seconds): per-rank queues replay
    in order, each op starting when its rank is free AND its deps'
    re-timed ends have passed.  Returns a new Schedule with the same
    order and updated starts/ends/busy/makespan — this is how the
    engine turns measured program timings into the bubble fractions
    the bench emits."""
    n_chunks = sched.n_chunks
    ends: dict = {}
    starts: dict = {}
    rank_free = [0.0] * sched.n_stages
    busy = [0.0] * sched.n_stages
    cursor = [0] * sched.n_stages
    total = sum(len(ops) for ops in sched.ranks)
    done = 0
    while done < total:
        progressed = False
        for rank, ops in enumerate(sched.ranks):
            while cursor[rank] < len(ops):
                op = ops[cursor[rank]]
                deps = list(_deps(op, n_chunks))
                if any(d not in ends for d in deps):
                    break
                t0 = max([rank_free[rank]]
                         + [ends[d] for d in deps])
                t1 = t0 + float(times.get((op.chunk, op.kind), 1.0))
                starts[op], ends[op] = t0, t1
                rank_free[rank] = t1
                busy[rank] += t1 - t0
                cursor[rank] += 1
                done += 1
                progressed = True
        if not progressed:   # pragma: no cover - validated schedules
            raise RuntimeError("mpmd schedule replay deadlocked")
    return dataclasses.replace(
        sched, starts=starts, ends=ends,
        makespan=max(rank_free), busy=busy)
