"""Speculative-decoding knobs (the serve plane's draft→verify loop).

Resolved like every other plane config (PageConfig, FleetConfig):
``Server(spec=...)`` accepts a :class:`SpecConfig`, a bool/int/dict
sugar, or ``None`` to defer to the ``RLT_SPEC_*`` env knobs — and
``worker_env()`` reproduces the config in a worker process so replica
actors inherit it under both cluster backends.

The loop itself: per decode round the DRAFT model (a smaller sibling
sharing the target's weights, ``LightningModule.configure_draft``)
greedily drafts ``k`` tokens per slot over its own KV cache
(core/steps.py ``build_draft_step``), then ONE batched target forward
scores all k+1 positions (``build_verify_step``); the scheduler accepts
the longest agreeing prefix plus one corrected token — token-level
IDENTICAL to target-only greedy decode, so speculation is purely a
latency lever.  ``min_accept`` arms the per-request fallback: a request
whose rolling acceptance collapses below the floor is marked ``spec
off`` and thereafter takes only the verify's first (= plain decode)
token; when EVERY live slot has fallen back the scheduler plans plain
decode steps again and the draft cost disappears entirely.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decode configuration.

    enabled: master switch — off keeps the serve plane byte-identical
        to the plain-decode build (no draft model, no extra programs).
    k: speculation depth — tokens drafted per round; each verify can
        emit 1..k+1 tokens.  Deeper k amortizes more target forwards
        but wastes more draft work at low acceptance.
    min_accept: per-request acceptance floor in [0, 1] — a request
        whose rolling window acceptance (accepted/drafted) drops below
        it falls back to plain decode for its remaining life.  0
        disables the fallback.
    window: spec rounds in the rolling acceptance window (per request);
        the fallback only arms once the window has ``window // 2``
        entries, so a cold start can't trip it.
    draft_layers: draft depth override for
        ``configure_draft(layers=...)``; 0 = the module's default
        (GPT: ``n_layer // 2``).
    draft_quant: ``"int8"`` holds the draft weights as a blockwise
        int8-resident copy (comm/quant.py), dequantized inline in the
        draft programs — trades exact weight sharing for ~2x smaller
        draft residency (the HBM delta is reported in
        ``server.stats()``).  Parity note: the EMITTED stream stays
        exactly greedy-parity regardless (only the target's verify
        decides tokens); quantization can only move the acceptance
        rate.
    """

    enabled: bool = False
    k: int = 4
    min_accept: float = 0.0
    window: int = 32
    draft_layers: int = 0
    draft_quant: Optional[str] = None

    def __post_init__(self):
        if self.k < 1:
            raise ValueError("spec k must be >= 1")
        if not 0.0 <= self.min_accept <= 1.0:
            raise ValueError("min_accept must be in [0, 1]")
        if self.window < 2:
            raise ValueError("window must be >= 2")
        if self.draft_layers < 0:
            raise ValueError("draft_layers must be >= 0")
        if self.draft_quant not in (None, "int8"):
            raise ValueError(
                f"draft_quant {self.draft_quant!r}; only 'int8' is "
                f"supported (comm/quant.py blockwise residency)")

    @classmethod
    def resolve(cls, value) -> "SpecConfig":
        """``Server(spec=...)`` → a config.  ``None`` defers to the
        ``RLT_SPEC_*`` env knobs (the worker_env round-trip)."""
        if isinstance(value, cls):
            return value
        if isinstance(value, bool):
            return cls(enabled=value)
        if isinstance(value, int):
            return cls(enabled=True, k=value)
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        if value is not None:
            raise TypeError(f"bad spec config: {value!r}")
        env = os.environ.get
        return cls(
            enabled=env("RLT_SPEC_DECODE", "").strip()
            in ("1", "true", "True"),
            k=int(env("RLT_SPEC_K", "4") or 4),
            min_accept=float(env("RLT_SPEC_MIN_ACCEPT", "0") or 0),
            window=int(env("RLT_SPEC_WINDOW", "32") or 32),
            draft_layers=int(env("RLT_SPEC_DRAFT_LAYERS", "0") or 0),
            draft_quant=env("RLT_DRAFT_QUANT", "").strip() or None,
        )

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process."""
        if not self.enabled:
            return {}
        out = {"RLT_SPEC_DECODE": "1",
               "RLT_SPEC_K": str(self.k),
               "RLT_SPEC_MIN_ACCEPT": repr(self.min_accept),
               "RLT_SPEC_WINDOW": str(self.window),
               "RLT_SPEC_DRAFT_LAYERS": str(self.draft_layers)}
        if self.draft_quant:
            out["RLT_DRAFT_QUANT"] = self.draft_quant
        return out


__all__ = ["SpecConfig"]
