"""Bucketed sequence lengths: the static-shape contract of the serve
plane.

XLA programs have static shapes, so a prefill over an arbitrary prompt
length would retrace per length — fatal for a multi-tenant endpoint.
Prompts are instead padded up to one of a small set of length buckets;
each bucket gets ONE prefill program, compiled once per (bucket,
topology) ever, through the persistent compilation cache
(compile/cache.py namespacing).  Decode is bucket-free: one token per
step against the slot-indexed KV cache, one program total.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

#: default bucket ladder (powers of two): doubles cap the padding waste
#: at <2x tokens while keeping the compiled-program count logarithmic
DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048)


def resolve_buckets(buckets: "Sequence[int] | None",
                    max_seq_len: int) -> tuple[int, ...]:
    """Validated ascending bucket ladder clipped to ``max_seq_len``.

    ``None`` takes :data:`DEFAULT_BUCKETS` up to the model context (a
    terminal ``max_seq_len`` bucket is always present so every
    admissible prompt has a home).
    """
    if max_seq_len < 1:
        raise ValueError(f"max_seq_len must be >= 1, got {max_seq_len}")
    if buckets is None:
        out = [b for b in DEFAULT_BUCKETS if b < max_seq_len]
        out.append(max_seq_len)
        return tuple(out)
    out = sorted({int(b) for b in buckets})
    if not out:
        raise ValueError("buckets must be non-empty")
    if out[0] < 1:
        raise ValueError(f"buckets must be positive, got {out[0]}")
    if out[-1] > max_seq_len:
        raise ValueError(
            f"bucket {out[-1]} exceeds the model context {max_seq_len}")
    return tuple(out)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``length`` (raises when the prompt exceeds the
    terminal bucket — the admission-time length check)."""
    if length < 1:
        raise ValueError(f"prompt length must be >= 1, got {length}")
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"prompt length {length} exceeds the largest bucket {buckets[-1]}")


def pad_to_bucket(tokens: np.ndarray, bucket: int,
                  pad_id: int = 0) -> np.ndarray:
    """Right-pad a 1-D token array to ``[1, bucket]`` int32 (the prefill
    program's input shape).  Pad content is irrelevant by construction:
    the causal mask plus the decode position bound keep padded positions
    out of every attended window (core/steps.py build_prefill_step)."""
    tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
    if len(tokens) > bucket:
        raise ValueError(
            f"prompt length {len(tokens)} exceeds bucket {bucket}")
    out = np.full((1, bucket), pad_id, dtype=np.int32)
    out[0, :len(tokens)] = tokens
    return out


__all__ = ["DEFAULT_BUCKETS", "resolve_buckets", "bucket_for",
           "pad_to_bucket"]
