"""Worker-side serve engine: AOT-compiled prefill/decode over a
device-resident KV cache.

One engine lives inside each serve worker for the fleet's whole life.
At setup it:

1. builds the mesh through the TRAINING strategy
   (``strategy.build_mesh(batch_hint=slots)``) and shards params with
   the strategy's own ``param_spec`` walk — the serving layout is the
   training layout;
2. materializes params (restored weights or a seeded init) and the
   zeroed slot-indexed KV cache (``kv_cache_spec`` sharding);
3. jits one prefill program per sequence-length bucket
   (core/steps.py build_prefill_step) plus ONE decode program
   (build_decode_step), submits them to the AOT precompiler so XLA
   compiles in the background through the persistent compilation cache
   (compile/) — every (bucket, topology) program is compiled once per
   FLEET, ever: worker 2 and every restart read worker 1's disk
   entries — then dispatch-warms each program once on scratch state;
4. counts Python re-traces per program (the traced body bumps a host
   counter, so a retrace is observable as a counter increment) — the
   zero-retrace-after-warmup acceptance evidence, alongside the
   compile-cache hit counters.

After setup the engine is a pure executor: ``prefill``/``decode`` calls
carry no Python branching on request state, so the decode loop shape
never changes (scheduler.py keeps insertion/eviction host-side).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional, Sequence

import numpy as np

from ray_lightning_tpu.compile import AotPrecompiler
from ray_lightning_tpu.core.steps import (
    build_decode_step,
    build_draft_step,
    build_kv_copy,
    build_prefill_step,
    build_suffix_step,
    build_verify_step,
    kv_layer_pairs,
)
from ray_lightning_tpu.serve.kvcache import KVCacheSpec
from ray_lightning_tpu.telemetry import metrics as _metrics

_log = logging.getLogger(__name__)


class ServeEngine:
    """Compiled generation executor bound to one process's devices."""

    def __init__(self, module, strategy, buckets: Sequence[int],
                 slots: int, max_seq_len: int, seed: int = 0,
                 weights: Optional[dict] = None, paged: Any = None,
                 spec: Any = None, kvship: bool = False):
        self.module = module
        self.strategy = strategy
        self.buckets = tuple(buckets)
        self.slots = int(slots)
        self.max_seq_len = int(max_seq_len)
        self.seed = int(seed)
        self._weights = weights
        #: PageConfig (serve/fleet/pages.py) — when enabled the engine
        #: additionally builds the page-copy + single-slot suffix
        #: programs that make prefix-cache hits executable
        self.paged = paged if paged is not None and paged.enabled \
            else None
        #: SpecConfig (serve/spec.py) — when enabled the engine builds
        #: the draft plane: a draft param subtree + its own KV cache,
        #: one draft prefill per bucket, the k-step draft program and
        #: the batched verify program
        self.spec = spec if spec is not None and spec.enabled else None
        #: build per-bucket kv_import programs so cross-replica KV-page
        #: shipping (serve/fleet/router.py) can install donor rows; a
        #: flag (not default-on) so non-fleet engines keep their exact
        #: pre-existing program count
        self.kvship = bool(kvship)
        #: which decode attention kernel the compiled program uses —
        #: dense | flash_decode | paged (resolved at setup from
        #: RLT_DECODE_IMPL, ops/flash_decode.py); benches emit it so a
        #: kernel regression is visible in the JSON ledger
        self.decode_kernel = "dense"
        self.trace_counts: dict[str, int] = {}
        self.kv_spec: Optional[KVCacheSpec] = None
        self.params = None
        self._mesh = None
        self._prefills: dict[int, Any] = {}
        self._decode = None
        self._kv_copy = None
        self._suffix = None
        self._kv_init = None
        self._k = None
        self._v = None
        # draft plane (spec decode)
        self.draft_kv_spec: Optional[KVCacheSpec] = None
        self.draft_layers = 0
        self._draft_model = None
        self._draft_params = None
        self._draft_prefills: dict[int, Any] = {}
        self._draft = None
        self._verify = None
        self._dkv_init = None
        self._dk = None
        self._dv = None
        #: extra HBM the draft residency holds (0 = pure weight-sharing
        #: views of the target tree; int8 quant holds payload+scales)
        self.draft_resident_bytes = 0
        #: what a standalone bf16 copy of the draft tree would cost —
        #: the baseline the HBM delta in stats() is measured against
        self.draft_fp_bytes = 0
        # kv-ship plane
        self._kv_imports: dict[int, Any] = {}

    # -- setup -------------------------------------------------------------

    def setup(self) -> "ServeEngine":
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_lightning_tpu.parallel.mesh import set_current_mesh

        t0 = time.monotonic()
        module = self.module
        module.setup_model()
        model = module.configure_decode_model()
        mesh = self.strategy.build_mesh(batch_hint=self.slots)
        self._mesh = mesh
        set_current_mesh(mesh)

        # abstract params + cache geometry, no device work: params from
        # the model's own init avals, K/V head shapes from an abstract
        # prefill capture on the smallest bucket
        dummy = jax.ShapeDtypeStruct((1, self.buckets[0]), np.int32)
        abstract_vars = jax.eval_shape(
            model.init, jax.random.PRNGKey(0), dummy)
        abstract_params = abstract_vars["params"]
        _, cap = jax.eval_shape(
            lambda p, t: model.apply({"params": p}, t, True,
                                     mutable=["kv_cache"]),
            abstract_params, dummy)
        k_avals = [k for k, _ in kv_layer_pairs(cap["kv_cache"])]
        self.kv_spec = KVCacheSpec.from_capture(
            k_avals, self.slots, self.max_seq_len)
        kv_dtype = k_avals[0].dtype

        param_sh = self.strategy._shardings_with(
            mesh, abstract_params, self.strategy.param_spec)
        kv_sh = NamedSharding(mesh, self.strategy.kv_cache_spec(mesh))
        rep = NamedSharding(mesh, P())
        multi = mesh.devices.size > 1

        # -- params: restored weights or a seeded fresh init --------------
        if self._weights is not None:
            from flax import serialization
            params = self._weights["params"] \
                if isinstance(self._weights, dict) \
                and "params" in self._weights else self._weights
            # normalize checkpoint/state-dict nesting onto the model's
            # own param tree structure before sharding
            params = serialization.from_state_dict(abstract_params,
                                                   params)
            self.params = jax.device_put(params, param_sh) \
                if multi else jax.device_put(params)
        else:
            def init_fn(rng):
                import jax.numpy as jnp
                variables = module.init_params(
                    rng, np.zeros((1, self.buckets[0]), np.int32))
                p = dict(variables)["params"]
                pd = getattr(module, "param_dtype", None)
                if pd is not None:
                    p = jax.tree_util.tree_map(
                        lambda a: a.astype(pd)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a,
                        p)
                return p

            ikw = {"out_shardings": param_sh} if multi else {}
            self.params = jax.jit(init_fn, **ikw)(
                jax.random.PRNGKey(self.seed))
        self._weights = None

        # -- programs ------------------------------------------------------
        import jax.numpy as jnp
        shape = self.kv_spec.shape

        def kv_init():
            z = jnp.zeros(shape, kv_dtype)
            return z, z

        kkw = {"out_shardings": (kv_sh, kv_sh)} if multi else {}
        self._kv_init = jax.jit(self._counted("kv_init", kv_init), **kkw)

        def jit_step(name, fn, n_scalars):
            kw: dict = {"donate_argnums": (1, 2)}
            if multi:
                kw["in_shardings"] = (
                    (param_sh, kv_sh, kv_sh) + (rep,) * n_scalars)
                kw["out_shardings"] = (kv_sh, kv_sh, rep)
            return jax.jit(self._counted(name, fn), **kw)

        for b in self.buckets:
            self._prefills[b] = jit_step(
                f"prefill_{b}", build_prefill_step(module, b), 3)

        # decode kernel selection (ops/flash_decode.py): the paged
        # kernel needs a page table whose pages tile the cache; when
        # paging is off or ragged, "paged" degrades to the
        # slot-contiguous flash kernel rather than failing setup
        from ray_lightning_tpu.ops.flash_decode import resolve_decode_impl
        impl = resolve_decode_impl(None)
        page_table = suffix_table = None
        if impl == "paged":
            if self.paged is not None \
                    and self.max_seq_len % self.paged.page_size == 0:
                from ray_lightning_tpu.serve.fleet.pages import (
                    identity_page_table)
                page_table = identity_page_table(
                    self.slots, self.max_seq_len, self.paged.page_size)
                suffix_table = identity_page_table(
                    1, self.max_seq_len, self.paged.page_size)
            else:
                impl = "flash_decode"
        self.decode_kernel = impl
        self._decode = jit_step(
            "decode", build_decode_step(module, page_table=page_table), 2)
        if self.paged is not None:
            # paged-KV programs (serve/fleet/pages.py): a masked page
            # copy for prefix-cache hits + the single-slot suffix step
            # that computes only the unmatched tail of a prompt
            self._suffix = jit_step(
                "suffix",
                build_suffix_step(module, page_table=suffix_table), 3)
            ckw: dict = {"donate_argnums": (0, 1)}
            if multi:
                ckw["in_shardings"] = (kv_sh, kv_sh, rep, rep, rep)
                ckw["out_shardings"] = (kv_sh, kv_sh)
            self._kv_copy = jax.jit(
                self._counted("kv_copy", build_kv_copy()), **ckw)

        if self.spec is not None:
            # -- draft plane (speculative decoding, serve/spec.py) ---------
            draft_model = module.configure_draft(
                self.spec.draft_layers or None)
            if draft_model is None:
                raise ValueError(
                    f"spec= requires {type(module).__name__}."
                    f"configure_draft() to return a draft module "
                    f"(core/module.py hook); it returned None")
            self._draft_model = draft_model
            self.draft_layers = getattr(
                getattr(draft_model, "config", None), "n_layer", 0)
            d_abstract = jax.eval_shape(
                draft_model.init, jax.random.PRNGKey(0), dummy)["params"]

            def _subtree(target, aval, path=""):
                """Draft params BY PATH out of the target tree — the
                weight-sharing contract: every draft param is the
                target's same-named array (zero extra HBM)."""
                if isinstance(aval, dict):
                    out = {}
                    for name, sub in aval.items():
                        if name not in target:
                            raise ValueError(
                                f"draft param {path + name!r} missing "
                                f"from the target tree: "
                                f"configure_draft() must share the "
                                f"target's param naming")
                        out[name] = _subtree(target[name], sub,
                                             path + name + "/")
                    return out
                if tuple(target.shape) != tuple(aval.shape):
                    raise ValueError(
                        f"draft param {path!r}: shape {aval.shape} != "
                        f"target {target.shape}")
                return target

            draft_params = _subtree(self.params, d_abstract)
            self.draft_fp_bytes = int(sum(
                int(np.prod(a.shape)) * 2
                for a in jax.tree_util.tree_leaves(d_abstract)))
            dequant = None
            if self.spec.draft_quant == "int8":
                # int8 residency (RLT_DRAFT_QUANT): hold the draft tree
                # as blockwise (payload, scale) pairs, dequantized
                # INSIDE the draft programs (comm/quant.py).  Trades
                # the zero-cost views for a ~2x-smaller standalone copy
                # whose bytes stay resident even if the target tree is
                # later offloaded; the measured delta rides stats().
                from ray_lightning_tpu.comm.quant import (
                    dequantize_blob, quantize_blob)
                flat, treedef = jax.tree_util.tree_flatten(draft_params)
                shapes = [tuple(a.shape) for a in flat]
                dtypes = [a.dtype for a in flat]
                qflat = [tuple(quantize_blob(a, "int8")) for a in flat]
                self._draft_params = qflat
                self.draft_resident_bytes = int(sum(
                    p.nbytes + s.nbytes for p, s in qflat))

                def dequant(qleaves):
                    leaves = [
                        dequantize_blob(p, s, "int8", shape, dtype=dt)
                        for (p, s), shape, dt in zip(qleaves, shapes,
                                                     dtypes)]
                    return jax.tree_util.tree_unflatten(treedef, leaves)
            else:
                self._draft_params = draft_params

            # draft KV geometry from an abstract draft prefill capture
            _, dcap = jax.eval_shape(
                lambda p, t: draft_model.apply(
                    {"params": p}, t, True, mutable=["kv_cache"]),
                d_abstract, dummy)
            dk_avals = [a for a, _ in kv_layer_pairs(dcap["kv_cache"])]
            self.draft_kv_spec = KVCacheSpec.from_capture(
                dk_avals, self.slots, self.max_seq_len)
            d_shape = self.draft_kv_spec.shape

            def dkv_init():
                z = jnp.zeros(d_shape, kv_dtype)
                return z, z

            self._dkv_init = jax.jit(
                self._counted("draft_kv_init", dkv_init), **kkw)

            def jit_draft(name, fn):
                # no in_shardings pin: the draft param tree is NOT the
                # target tree (subtree, possibly quantized pairs) — jax
                # reads the resident shardings of the shared views
                kw: dict = {"donate_argnums": (1, 2)}
                if multi:
                    kw["out_shardings"] = (kv_sh, kv_sh, rep)
                return jax.jit(self._counted(name, fn), **kw)

            for b in self.buckets:
                self._draft_prefills[b] = jit_draft(
                    f"draft_prefill_{b}",
                    build_prefill_step(module, b, model=draft_model,
                                       dequant=dequant))
            self._draft = jit_draft(
                "draft",
                build_draft_step(module, self.spec.k,
                                 page_table=page_table,
                                 model=draft_model, dequant=dequant))
            self._verify = jit_step(
                "verify",
                build_verify_step(module, self.spec.k,
                                  page_table=page_table), 2)

        if self.kvship:
            # -- KV-page import programs (fleet disaggregation) ------------
            # one per bucket: install shipped donor rows [0, b) at a
            # slot with a single dynamic_update_slice per cache — the
            # device half of cross-replica prefix donation
            # (serve/fleet/router.py ships, PrefixIndex addresses)
            def import_fn(k_caches, v_caches, ks, vs, slot):
                zero = (0,) * (k_caches.ndim - 2)
                k_caches = jax.lax.dynamic_update_slice(
                    k_caches, ks, (0, slot) + zero)
                v_caches = jax.lax.dynamic_update_slice(
                    v_caches, vs, (0, slot) + zero)
                return k_caches, v_caches

            for b in self.buckets:
                ikw2: dict = {"donate_argnums": (0, 1)}
                if multi:
                    ikw2["in_shardings"] = (kv_sh, kv_sh, rep, rep, rep)
                    ikw2["out_shardings"] = (kv_sh, kv_sh)
                self._kv_imports[b] = jax.jit(
                    self._counted(f"kv_import_{b}", import_fn), **ikw2)

        # AOT avals must describe the params AS SERVED (post
        # param_dtype cast / restore), not the fp32 init avals — a
        # dtype drift here would background-compile a program the
        # dispatch never runs (cache miss instead of the hit the
        # compiled-once story is built on)
        param_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self.params)
        self._precompile_and_warm(jax, param_avals, shape, kv_dtype)
        _log.info(
            "serve engine ready in %.2fs: mesh=%s buckets=%s slots=%d "
            "kv=%s (%.1f MB)", time.monotonic() - t0, dict(mesh.shape),
            self.buckets, self.slots, shape,
            self.kv_spec.nbytes(np.dtype(kv_dtype).itemsize) / 2**20)
        return self

    def _precompile_and_warm(self, jax, abstract_params, kv_shape,
                             kv_dtype) -> None:
        """Background-compile every program through the persistent cache
        (no-op when the cache is inactive, compile/aot.py), then warm
        each with ONE dispatch on scratch state — after this, a serving
        trace-count increment means a real retrace (the acceptance
        counter)."""
        pre = AotPrecompiler.resolve()
        kv_aval = jax.ShapeDtypeStruct(kv_shape, kv_dtype)
        i32 = lambda *s: jax.ShapeDtypeStruct(s, np.int32)  # noqa: E731
        for b, jitted in self._prefills.items():
            pre.submit(f"prefill_{b}", jitted,
                       (abstract_params, kv_aval, kv_aval,
                        i32(1, b), i32(), i32()))
        pre.submit("decode", self._decode,
                   (abstract_params, kv_aval, kv_aval,
                    i32(self.slots), i32(self.slots)))
        if self.paged is not None:
            pre.submit("suffix", self._suffix,
                       (abstract_params, kv_aval, kv_aval,
                        i32(), i32(), i32()))
            pre.submit("kv_copy", self._kv_copy,
                       (kv_aval, kv_aval, i32(), i32(), i32()))
        if self.spec is not None:
            dp_avals = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._draft_params)
            dkv_aval = jax.ShapeDtypeStruct(self.draft_kv_spec.shape,
                                            kv_dtype)
            for b, jitted in self._draft_prefills.items():
                pre.submit(f"draft_prefill_{b}", jitted,
                           (dp_avals, dkv_aval, dkv_aval,
                            i32(1, b), i32(), i32()))
            pre.submit("draft", self._draft,
                       (dp_avals, dkv_aval, dkv_aval,
                        i32(self.slots), i32(self.slots)))
            pre.submit("verify", self._verify,
                       (abstract_params, kv_aval, kv_aval,
                        i32(self.slots, self.spec.k + 1),
                        i32(self.slots, self.spec.k + 1)))
        if self.kvship:
            nl, _, _, nh, hd = self.kv_spec.shape
            for b, jitted in self._kv_imports.items():
                rows = jax.ShapeDtypeStruct((nl, 1, b, nh, hd), kv_dtype)
                pre.submit(f"kv_import_{b}", jitted,
                           (kv_aval, kv_aval, rows, rows, i32()))
        pre.barrier()

        # scratch warmup: the warmed cache state is garbage, so re-init
        # the real cache afterwards (slots are overwritten by their
        # admitting prefill anyway; this keeps even slot 0 pristine)
        k, v = self._kv_init()
        for b, jitted in self._prefills.items():
            k, v, tok = jitted(self.params, k, v,
                               np.zeros((1, b), np.int32),
                               np.int32(0), np.int32(1))
        zeros = np.zeros((self.slots,), np.int32)
        k, v, toks = self._decode(self.params, k, v, zeros, zeros)
        if self.paged is not None:
            k, v = self._kv_copy(k, v, np.int32(0),
                                 np.int32(self.slots - 1), np.int32(1))
            k, v, toks = self._suffix(self.params, k, v, np.int32(0),
                                      np.int32(0), np.int32(0))
        if self.spec is not None:
            dk, dv = self._dkv_init()
            for b, jitted in self._draft_prefills.items():
                dk, dv, _ = jitted(self._draft_params, dk, dv,
                                   np.zeros((1, b), np.int32),
                                   np.int32(0), np.int32(1))
            dk, dv, _ = self._draft(self._draft_params, dk, dv, zeros,
                                    zeros)
            z2 = np.zeros((self.slots, self.spec.k + 1), np.int32)
            k, v, toks = self._verify(self.params, k, v, z2, z2)
            del dk, dv
        if self.kvship:
            nl, _, _, nh, hd = self.kv_spec.shape
            for b, jitted in self._kv_imports.items():
                rows = np.zeros((nl, 1, b, nh, hd), kv_dtype)
                k, v = jitted(k, v, rows, rows, np.int32(0))
        jax.block_until_ready(toks)
        del k, v
        self._k, self._v = self._kv_init()
        if self.spec is not None:
            # draft-cache warmup state is garbage too: re-init
            self._dk, self._dv = self._dkv_init()
        #: trace counts at the end of warmup — any later growth is a
        #: REAL decode-loop retrace (the acceptance counter)
        self.trace_counts_at_warmup = dict(self.trace_counts)

    def _counted(self, name: str, fn):
        """Wrap a step body so every TRACE bumps a host counter (the
        wrapper body only runs while jax traces; cached dispatches never
        re-enter Python)."""
        def wrapped(*args):
            self.trace_counts[name] = self.trace_counts.get(name, 0) + 1
            reg = _metrics.get_registry()
            if reg is not None:
                reg.counter("rlt_serve_traces_total").inc(1, program=name)
            return fn(*args)
        return wrapped

    # -- serving -----------------------------------------------------------

    def prefill(self, slot: int, tokens: np.ndarray, length: int,
                bucket: int) -> int:
        """Insert a request at ``slot``: write its K/V block, return its
        first generated token."""
        t0 = time.monotonic()
        self._k, self._v, tok = self._prefills[bucket](
            self.params, self._k, self._v,
            np.asarray(tokens, np.int32), np.int32(slot),
            np.int32(length))
        import jax
        out = int(np.asarray(jax.device_get(tok)))
        self._charge("rlt_serve_prefill_seconds_total",
                     time.monotonic() - t0)
        return out

    def prefill_reused(self, slot: int, src_slot: int,
                       tokens: np.ndarray, length: int,
                       matched: int) -> int:
        """Prefix-cache-hit insertion (serve/fleet/pages.py): copy the
        ``matched`` donor rows device-side, then teacher-force ONLY the
        unmatched suffix through the single-slot suffix program.  The
        last suffix step's argmax is the request's first generated
        token — the same greedy contract as :meth:`prefill`, at
        ``length - matched`` computed tokens instead of ``length``."""
        if self._kv_copy is None:
            raise RuntimeError("engine built without paged=; no reuse "
                               "programs")
        t0 = time.monotonic()
        toks = np.asarray(tokens, np.int32).reshape(-1)
        self._k, self._v = self._kv_copy(
            self._k, self._v, np.int32(src_slot), np.int32(slot),
            np.int32(matched))
        # a full-prompt match still replays the final prompt token (a
        # same-value rewrite) to read its logits for the first token
        out = None
        for pos in range(min(int(matched), int(length) - 1), int(length)):
            self._k, self._v, out = self._suffix(
                self.params, self._k, self._v, np.int32(toks[pos]),
                np.int32(pos), np.int32(slot))
        import jax
        first = int(np.asarray(jax.device_get(out)))
        self._charge("rlt_serve_prefill_seconds_total",
                     time.monotonic() - t0)
        return first

    def decode(self, tokens: np.ndarray,
               positions: np.ndarray) -> np.ndarray:
        """One continuous-batching step: every slot advances a token."""
        t0 = time.monotonic()
        self._k, self._v, out = self._decode(
            self.params, self._k, self._v,
            np.asarray(tokens, np.int32), np.asarray(positions, np.int32))
        import jax
        toks = np.asarray(jax.device_get(out))
        self._charge("rlt_serve_decode_seconds_total",
                     time.monotonic() - t0)
        return toks

    # -- speculative decoding ----------------------------------------------

    def draft_prefill(self, slot: int, tokens: np.ndarray, length: int,
                      bucket: int) -> None:
        """Write the DRAFT model's K/V rows for an admitted prompt.

        Runs at every admission (fresh AND prefix-reused) so the draft
        cache carries the request's history before its first spec
        round; the emitted-token contract is the target's alone, so
        the draft prefill's argmax is discarded."""
        t0 = time.monotonic()
        self._dk, self._dv, _ = self._draft_prefills[bucket](
            self._draft_params, self._dk, self._dv,
            np.asarray(tokens, np.int32), np.int32(slot),
            np.int32(length))
        self._charge("rlt_serve_draft_seconds_total",
                     time.monotonic() - t0)

    def draft(self, tokens: np.ndarray,
              positions: np.ndarray) -> np.ndarray:
        """One k-step draft round over every slot: ``[S, k]`` drafted
        tokens (core/steps.py ``build_draft_step``)."""
        t0 = time.monotonic()
        self._dk, self._dv, out = self._draft(
            self._draft_params, self._dk, self._dv,
            np.asarray(tokens, np.int32), np.asarray(positions, np.int32))
        import jax
        drafts = np.asarray(jax.device_get(out))
        self._charge("rlt_serve_draft_seconds_total",
                     time.monotonic() - t0)
        return drafts

    def verify(self, tokens: np.ndarray, positions: np.ndarray,
               drafts: np.ndarray) -> np.ndarray:
        """ONE batched target forward over the k drafted positions:
        ``[S, k+1]`` target argmaxes — column j is the token plain
        decode would emit after accepting drafts ``1..j`` (the
        scheduler folds the longest agreeing prefix + one corrected
        token).  Counts as a single target forward however many tokens
        it ends up emitting — the tokens-per-target-forward win."""
        t0 = time.monotonic()
        toks2 = np.concatenate(
            [np.asarray(tokens, np.int32)[:, None],
             np.asarray(drafts, np.int32)], axis=1)
        pos2 = (np.asarray(positions, np.int32)[:, None]
                + np.arange(self.spec.k + 1, dtype=np.int32)[None, :])
        self._k, self._v, out = self._verify(
            self.params, self._k, self._v, toks2, pos2)
        import jax
        ver = np.asarray(jax.device_get(out))
        self._charge("rlt_serve_verify_seconds_total",
                     time.monotonic() - t0)
        return ver

    # -- KV-page shipping (fleet disaggregation) ---------------------------

    def export_kv(self, slot: int, bucket: int
                  ) -> "tuple[np.ndarray, np.ndarray]":
        """Device→host copy of ``slot``'s cache rows ``[0, bucket)``
        across every layer: ``([n_layer, 1, bucket, H, D], same)`` —
        the payload a prefill replica ships to a decode replica.  Rows
        past the prompt are pad garbage; the importer only registers
        (and the reuse path only copies) the prompt's whole pages, so
        they never influence decode."""
        k_rows = np.asarray(self._k[:, slot:slot + 1, :bucket])
        v_rows = np.asarray(self._v[:, slot:slot + 1, :bucket])
        return k_rows, v_rows

    def import_kv(self, slot: int, k_rows: np.ndarray,
                  v_rows: np.ndarray) -> None:
        """Install shipped donor rows at ``slot`` via the per-bucket
        AOT ``kv_import_{b}`` program.  Sound for the same reason
        kv_copy is: a cache row is a pure per-(token, position) value,
        identical wherever it was computed — including on another
        replica."""
        if not self._kv_imports:
            raise RuntimeError("engine built without kvship=; no "
                               "import programs")
        bucket = int(k_rows.shape[2])
        dt = self._k.dtype  # codec decode yields fp32; the program's
        # aval is the cache dtype — cast host-side, never retrace
        self._k, self._v = self._kv_imports[bucket](
            self._k, self._v, np.asarray(k_rows).astype(dt),
            np.asarray(v_rows).astype(dt), np.int32(slot))

    @staticmethod
    def _charge(name: str, seconds: float) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter(name).inc(seconds)

    # -- evidence ----------------------------------------------------------

    def stats(self) -> dict:
        """Trace counters + compile-cache counters: the zero-retrace /
        compiled-once evidence surfaced to the driver."""
        from ray_lightning_tpu.compile import cache as compile_cache
        s = compile_cache.stats()
        warm = getattr(self, "trace_counts_at_warmup", {})
        out = {
            "decode_kernel": self.decode_kernel,
            "traces": dict(self.trace_counts),
            # traces since the warmup snapshot: 0 everywhere = the
            # decode loop never re-traced while serving
            "retraces": {name: n - warm.get(name, 0)
                         for name, n in self.trace_counts.items()},
            # kv_init + decode + prefills (+ paged copy/suffix pair)
            # (+ spec: draft_kv_init + draft prefills + draft + verify)
            # (+ kvship: one import per bucket) — the program-count
            # invariant serve/selfcheck.py pins
            "programs": 1 + 1 + len(self._prefills)
            + (2 if self.paged is not None else 0)
            + (3 + len(self._draft_prefills) if self.spec is not None
               else 0)
            + len(self._kv_imports),
            "compile_cache": {
                "active": compile_cache.active_dir() is not None,
                "hits": s.hits,
                "misses": s.misses,
                "backend_compile_secs": round(s.backend_compile_secs, 3),
            },
        }
        if self.spec is not None:
            out["spec"] = {
                "k": self.spec.k,
                "draft_layers": self.draft_layers,
                "draft_quant": self.spec.draft_quant,
                # what a standalone bf16 draft copy would cost vs the
                # HBM the residency actually adds (0 = weight-sharing
                # views; int8 = payload + scales) — the satellite's
                # reported HBM delta
                "draft_fp_bytes": self.draft_fp_bytes,
                "draft_resident_bytes": self.draft_resident_bytes,
                "draft_hbm_delta_bytes": self.draft_resident_bytes
                - self.draft_fp_bytes,
            }
        return out


__all__ = ["ServeEngine"]
