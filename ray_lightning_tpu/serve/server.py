"""The public serving endpoint: ``Server`` — trained checkpoint in,
multi-tenant generation out.

Driver-side composition of the serve plane (module docstrings of the
parts hold the details): a :class:`~ray_lightning_tpu.serve.scheduler.
Scheduler` forms continuous batches over bucketed sequence lengths, a
fleet of persistent :class:`~ray_lightning_tpu.serve.worker.ServeWorker`
actors (one per TPU host, same cluster backends and rendezvous plumbing
as the fit path) executes them against AOT-compiled prefill/decode
programs and a strategy-sharded KV cache, and the PR 2 metrics plane
serves TTFT / TPOT / queue depth / tokens-per-second live on the
driver's ``/metrics`` endpoint.

::

    server = Server(GPTLightningModule("tiny"), checkpoint=ckpt_path,
                    num_workers=2, platform="cpu",
                    buckets=(16, 32), max_batch_slots=8,
                    telemetry={"metrics_port": 0}).start()
    req = server.submit(prompt_tokens, tenant="alice")
    tokens = req.result(timeout=60)          # np.int32 generated ids
    tokens = server.generate(prompt_tokens)  # submit + wait
    server.shutdown()                        # graceful drain first

Prompts and completions are token-id arrays — tokenization lives with
the caller, like every dataset concern in this framework.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ray_lightning_tpu.cluster.backend import get_backend
from ray_lightning_tpu.cluster.queue import WorkerQueueProxy
from ray_lightning_tpu.compile import CompileCacheConfig
from ray_lightning_tpu.parallel.strategy import resolve_strategy
from ray_lightning_tpu.serve.buckets import resolve_buckets
from ray_lightning_tpu.serve.scheduler import Scheduler, ServeRequest
from ray_lightning_tpu.serve.worker import ServeWorker
from ray_lightning_tpu.telemetry import TelemetryConfig
from ray_lightning_tpu.util import _handle_queue_item
from ray_lightning_tpu.utils.platform import host_device_count_flags

_log = logging.getLogger(__name__)


@dataclass
class ServeSpec:
    """Picklable engine configuration shipped to every serve worker."""

    module: Any
    strategy: Any
    buckets: tuple
    slots: int
    max_seq_len: int
    seed: int
    telemetry: TelemetryConfig
    compile_cache: CompileCacheConfig
    #: paged-KV prefix reuse (serve/fleet/pages.py PageConfig); None or
    #: disabled keeps the engine's pre-fleet program set
    paged: Any = None
    #: speculative decoding (serve/spec.py SpecConfig); None/disabled
    #: keeps the plain-decode program set
    spec: Any = None
    #: build the per-bucket kv_import programs (fleet KV shipping)
    kvship: Any = None


class Server:
    """Multi-tenant generation endpoint over a trained module."""

    def __init__(
        self,
        module,
        checkpoint: Optional[str] = None,
        *,
        strategy: Any = None,
        buckets: Optional[Sequence[int]] = None,
        max_batch_slots: int = 8,
        num_workers: int = 1,
        platform: Optional[str] = None,
        use_tpu: bool = False,
        devices_per_worker: Optional[int] = None,
        max_seq_len: Optional[int] = None,
        max_new_tokens: int = 32,
        eos_token: Optional[int] = None,
        tenant_quotas: "dict[str, int] | int | None" = None,
        max_prefills_per_step: int = 1,
        seed: int = 0,
        default_root_dir: Optional[str] = None,
        telemetry: Any = None,
        compile_cache: Any = None,
        paged: Any = None,
        spec: Any = None,
        kvship: bool = False,
        worker_env: Optional[dict] = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.module = module
        self.strategy = resolve_strategy(strategy)
        if max_seq_len is None:
            cfg = getattr(module, "config", None)
            max_seq_len = getattr(cfg, "block_size", None)
            if max_seq_len is None:
                raise ValueError(
                    "pass max_seq_len= (module.config has no block_size)")
        self.max_seq_len = int(max_seq_len)
        self.buckets = resolve_buckets(buckets, self.max_seq_len)
        self.max_batch_slots = int(max_batch_slots)
        self.num_workers = int(num_workers)
        self.platform = platform or ("tpu" if use_tpu else None)
        self.use_tpu = use_tpu
        self.devices_per_worker = devices_per_worker
        self.seed = int(seed)
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "rlt_serve")
        self.telemetry = TelemetryConfig.resolve(telemetry)
        self.compile_cache = CompileCacheConfig.resolve(compile_cache)
        from ray_lightning_tpu.serve.fleet.pages import PageConfig
        from ray_lightning_tpu.serve.spec import SpecConfig
        self.paged = PageConfig.resolve(paged)
        self.spec = SpecConfig.resolve(spec)
        self.kvship = bool(kvship)
        self.worker_env = dict(worker_env or {})
        self.scheduler = Scheduler(
            self.buckets, self.max_batch_slots, self.max_seq_len,
            quotas=tenant_quotas,
            max_prefills_per_step=max_prefills_per_step,
            default_max_new_tokens=max_new_tokens, eos_token=eos_token,
            paged=self.paged, spec=self.spec)
        self._weights = self._resolve_weights(module, checkpoint)
        self._backend = None
        self._workers: list = []
        self._queue = None
        self._agg = None
        self._metrics_server = None
        self._profile_ctl = None
        self._pump: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._draining = False
        self._started = False
        self._error: Optional[BaseException] = None
        #: postmortem of a mid-serve fleet failure: classified cause +
        #: the flight-recorder dump paths (telemetry/flight.py), linked
        #: from the fleet router's failover report
        self.failure_report: Optional[dict] = None
        self._setup_info: list = []
        self.telemetry_paths: Optional[dict] = None
        #: goodput plane (telemetry/goodput.py): the pump's wall-clock
        #: ledger (decode / prefill / queue_idle split) and its
        #: finalized doc — the serve half of the goodput surface
        self._goodput_ledger = None
        self.goodput_doc: Optional[dict] = None

    @staticmethod
    def _resolve_weights(module, checkpoint: Optional[str]):
        """Weights for the fleet: an msgpack checkpoint path, a module
        carrying ``_trained_variables`` from a previous ``fit``, or
        ``None`` (seeded fresh init — benches and smoke tests)."""
        if checkpoint is not None:
            from ray_lightning_tpu.core.trainer import Trainer
            ckpt = Trainer.load_checkpoint_dict(checkpoint)
            return {"params": ckpt["state"]["params"]}
        trained = getattr(module, "_trained_variables", None)
        if trained is not None:
            return {"params": trained["params"]}
        return None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Server":
        """Spawn the fleet, rendezvous, build+warm every engine, start
        the scheduler pump.  Blocking; returns self."""
        if self._started:
            return self
        backend = get_backend()
        self._backend = backend
        base_env = self._worker_env_base()
        run_tag = uuid.uuid4().hex[:8]
        self._workers = [
            backend.create_actor(
                ServeWorker,
                env={**base_env, "RLT_PROCESS_ID": str(i)},
                resources=self._worker_resources(),
                name=f"rlt-serve-{os.getpid()}-{run_tag}-{i}",
            )
            for i in range(self.num_workers)
        ]
        try:
            self._rendezvous()
            self._start_telemetry()
            self._queue = (backend.worker_queue_proxy()
                           if hasattr(backend, "worker_queue_proxy")
                           else WorkerQueueProxy())
            spec = ServeSpec(
                module=self.module, strategy=self.strategy,
                buckets=self.buckets, slots=self.max_batch_slots,
                max_seq_len=self.max_seq_len, seed=self.seed,
                telemetry=self.telemetry,
                compile_cache=self.compile_cache,
                paged=self.paged, spec=self.spec,
                kvship=self.kvship)
            payload = (spec, self._weights)
            ref = None
            if backend.supports_object_store:
                payload = ref = backend.put(payload)
            try:
                futures = [
                    w.call("setup_serve", payload, i, self._queue)
                    for i, w in enumerate(self._workers)]
                self._setup_info = self._wait_all(futures, timeout=600)
            finally:
                if ref is not None:
                    backend.free(ref)
        except BaseException:
            self._kill_workers()
            raise
        info = self._setup_info[0]
        _log.info("serve fleet ready: %d worker(s), mesh=%s, buckets=%s, "
                  "slots=%d", self.num_workers, info["mesh"],
                  info["buckets"], info["slots"])
        self._started = True
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="rlt-serve-pump")
        self._pump.start()
        return self

    def _worker_env_base(self) -> dict:
        """Mirror of the fit path's worker env plumbing
        (plugins/xla.py RayXlaPlugin._worker_env_base)."""
        env = {"RLT_NUM_PROCESSES": str(self.num_workers)}
        if self.platform:
            env["RLT_PLATFORM"] = self.platform
            env["JAX_PLATFORMS"] = self.platform
        if self.platform == "cpu":
            n = self.devices_per_worker or 1
            env["XLA_FLAGS"] = host_device_count_flags(n)
            env["RLT_NUM_LOCAL_DEVICES"] = str(n)
            env["PALLAS_AXON_POOL_IPS"] = ""
        if self.telemetry.enabled:
            env["RLT_TELEMETRY"] = "1"
            env["RLT_HEARTBEAT_INTERVAL"] = str(
                self.telemetry.heartbeat_interval)
        env.update(self.compile_cache.worker_env())
        env.update(self.paged.worker_env())
        env.update(self.spec.worker_env())
        if self.kvship:
            env["RLT_SERVE_KVSHIP"] = "1"
        env.update(self.worker_env)
        return env

    def _worker_resources(self) -> dict:
        res: dict = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = self.devices_per_worker or 1
        return res

    def _rendezvous(self) -> None:
        """PJRT coordinator election + rank env, exactly like a fit
        (plugins/xla.py)."""
        workers = self._workers
        coord_env = {}
        if self.num_workers > 1:
            ip = workers[0].call("get_node_ip").result(timeout=120)
            port = workers[0].call("get_free_port").result(timeout=120)
            coord_env = {"RLT_COORDINATOR": f"{ip}:{port}"}
        futs = [w.call("set_env_vars", {**coord_env,
                                        "RLT_PROCESS_ID": str(i)})
                for i, w in enumerate(workers)]
        self._wait_all(futs, timeout=120)

    def _start_telemetry(self) -> None:
        cfg = self.telemetry
        if not cfg.enabled:
            return
        from ray_lightning_tpu import telemetry
        from ray_lightning_tpu.telemetry import exporter as _exporter
        agg = telemetry.TelemetryAggregator(
            cfg.resolve_dir(self.default_root_dir),
            heartbeat_timeout=cfg.heartbeat_timeout,
            hard_timeout=cfg.hard_timeout,
            flight_capacity=cfg.flight_capacity,
            incident_cfg=cfg.resolved_incident(),
            run_kind="serve")
        for i, w in enumerate(self._workers):
            agg.register_worker(i, w)
        telemetry.set_active(agg)
        self._agg = agg
        if cfg.metrics:
            # driver-side registry (rank -1): the scheduler's
            # TTFT/TPOT/queue-depth/tokens instruments flush straight
            # into the aggregator and ride the same /metrics exposition
            # as the workers' windows
            telemetry.enable_metrics(rank=-1, sink=agg.ingest_metrics,
                                     interval=cfg.metrics_interval)
            # POST /debug/profile?steps=N: the pump attaches the armed
            # window to the next plan broadcast (tracing.py)
            from ray_lightning_tpu.telemetry.tracing import (
                ServeProfileController)
            self._profile_ctl = ServeProfileController(agg.out_dir)
            self._metrics_server = _exporter.start_metrics_server(
                agg, cfg, profile_controller=self._profile_ctl)

    @property
    def metrics_url(self) -> Optional[str]:
        return self._metrics_server.url \
            if self._metrics_server is not None else None

    def profile_status(self) -> Optional[dict]:
        """State of the on-demand jax.profiler window (same document
        ``/status`` serves under ``profile``); None when telemetry
        metrics are off."""
        return self._profile_ctl.status() \
            if self._profile_ctl is not None else None

    # -- request surface ---------------------------------------------------

    def submit(self, prompt, tenant: str = "default",
               max_new_tokens: Optional[int] = None,
               ship_kv: bool = False) -> ServeRequest:
        """Enqueue a prompt (token ids); returns a handle whose
        ``result()`` blocks for the generated tokens.  ``ship_kv``
        marks a disaggregation prefill leg: its prefill step exports
        the whole-page KV rows into the kv outbox alongside the step
        result (``export_kv(..., req_id=...)`` claims them)."""
        if not self._started:
            raise RuntimeError("Server.start() first")
        if self._draining:
            raise RuntimeError("server is draining; no new requests")
        if self._error is not None:
            raise RuntimeError("serve fleet failed") from self._error
        req = self.scheduler.submit(prompt, tenant=tenant,
                                    max_new_tokens=max_new_tokens,
                                    ship_kv=ship_kv)
        self._work.set()
        return req

    def generate(self, prompt, tenant: str = "default",
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 300.0) -> np.ndarray:
        """Blocking submit-and-wait."""
        return self.submit(prompt, tenant=tenant,
                           max_new_tokens=max_new_tokens).result(timeout)

    # -- KV-page shipping (fleet disaggregation) ---------------------------

    def can_ship_kv(self) -> bool:
        """Both ends of the KV-ship channel need paging (the prefix
        index addresses donor pages) and the kv_import programs."""
        return self._started and self.kvship and self.paged.enabled

    def export_kv(self, prompt_tokens, req_id: "int | None" = None):
        """Donor rows for the fleet's KV-ship leg — both the push
        path (disaggregation ships a just-prefilled request's pages
        to its decode replica) and the pull path (prefix federation
        fetches a RETAINED donor another replica advertised): the
        longest registered prefix of ``prompt_tokens`` on this
        replica as ``(k_rows, v_rows, matched_tokens)``, or ``None``
        (no donor — the federation caller treats that as a stale
        directory entry and invalidates it).
        Rows are exported at bucket granularity — the import side's
        AOT programs are per-bucket — and the importer registers only
        the matched whole pages, so the bucket tail never decodes.

        ``req_id`` (a ``submit(ship_kv=True)`` request) claims the
        rows the prefill step piggybacked into the kv outbox — the
        fast path with no worker round-trip; the donor match below is
        the fallback when the outbox entry was capped out."""
        sched = self.scheduler
        if sched.pages is None or not self._started:
            return None
        if req_id is not None:
            boxed = sched.pop_kv_export(int(req_id))
            if boxed is not None:
                return boxed
        prompt_tokens = np.asarray(prompt_tokens,
                                   dtype=np.int32).reshape(-1)
        # match and pin under ONE lock hold: an admission evicting (and
        # re-admitting) the donor between the match and the worker row
        # fetch would ship a DIFFERENT prompt's rows under this
        # prompt's registration
        with sched._lock:
            hit = sched.pages.match(prompt_tokens)
            if hit is None:
                return None
            src, matched = hit
            sched.pages.pin(src)
        try:
            from ray_lightning_tpu.serve.buckets import bucket_for
            bucket = bucket_for(matched, self.buckets)
            results = self._wait_all(
                [w.call("serve_export_kv", int(src), int(bucket))
                 for w in self._workers], timeout=120)
            rows = next(r for r in results if r is not None)
            return rows[0], rows[1], int(matched)
        finally:
            with sched._lock:
                sched.pages.unpin(src)

    def can_adopt_kv(self) -> bool:
        """Cheap capacity probe for the router's ship policy: is there
        a slot this replica could host shipped rows in RIGHT NOW (free,
        or reclaimable from an LRU donor)?  Racy by design — a ship
        admitted on a stale yes still fails safe in ``import_kv`` — but
        it lets the router skip the quantize/mailbox/install cost of a
        ship that is doomed before it starts (a saturated decode
        replica under burst)."""
        sched = self.scheduler
        if sched.pages is None or not self._started:
            return False
        with sched._lock:
            return (sched.allocator.free_count > 0
                    or sched.pages.donor_count > 0)

    def import_kv(self, prompt_tokens, k_rows, v_rows) -> bool:
        """Adopt shipped donor rows: acquire a donor slot, install the
        rows on every worker, then register the prefix (the order is
        the soundness story — scheduler.adopt_commit docstring).
        False = no adoptable slot (router falls back to pooled
        prefill)."""
        prompt_tokens = np.asarray(prompt_tokens,
                                   dtype=np.int32).reshape(-1)
        slot = self.scheduler.adopt_imported(prompt_tokens)
        if slot is None:
            return False
        try:
            self._wait_all(
                [w.call("serve_import_kv", int(slot), k_rows, v_rows)
                 for w in self._workers], timeout=120)
        except BaseException:
            self.scheduler.adopt_abort(slot)
            raise
        self.scheduler.adopt_commit(slot, prompt_tokens)
        return True

    # -- the pump ----------------------------------------------------------

    def _pump_loop(self) -> None:
        sched = self.scheduler
        if self._agg is not None:
            # the active aggregator is THREAD-local (aggregator.py: the
            # tune runner's per-trial threads need their own); the pump
            # is the thread draining the worker queue, so it must bind
            # the fleet's aggregator itself or every relayed telemetry
            # item would be dropped silently
            from ray_lightning_tpu import telemetry
            telemetry.set_active(self._agg)
        ledger = self._goodput_ledger = self._make_goodput_ledger()
        try:
            self._pump_iterations(sched, ledger)
        finally:
            self._finish_goodput()

    def _pump_iterations(self, sched, ledger) -> None:
        next_peek = time.monotonic() + 2.0
        while not self._stop.is_set():
            self._drain_queue()
            self._watchdog()
            if time.monotonic() >= next_peek:
                if ledger is not None:
                    # live /status: ship a mid-run peek of the open
                    # ledger (the finalized doc replaces it at pump exit)
                    self._ship_goodput(ledger.peek())
                if self._agg is not None:
                    # incident-plane serve detectors (queue depth,
                    # TTFT/TPOT p99) tick at the same cadence
                    self._agg.note_serve_signals(
                        queue_depth=sched.queued_count,
                        ttft_p99_s=sched.recent_ttft_p99(),
                        tpot_p99_s=sched.recent_tpot_p99())
                next_peek = time.monotonic() + 2.0
            plan = sched.plan()
            if plan is None:
                if self._draining and sched.idle():
                    return
                t_idle = time.monotonic()
                self._work.wait(0.02)
                self._work.clear()
                if ledger is not None:
                    ledger.add("queue_idle", time.monotonic() - t_idle)
                continue
            if self._profile_ctl is not None:
                # armed profile window rides the SAME broadcast as the
                # trace ids — every worker starts its capture on this
                # plan and the driver counts the window's steps
                pending = self._profile_ctl.take_pending()
                if pending is not None:
                    plan["profile"] = pending
            t_step = time.monotonic()
            try:
                futures = [w.call("serve_step", plan)
                           for w in self._workers]
                results = self._wait_all(futures, timeout=300)
                # rank 0 alone carries the tokens (worker.py lockstep
                # contract); all-None means the backend lost it — a
                # fleet failure like any other, so it must raise INSIDE
                # this try or the pump dies without failing the
                # in-flight requests
                result = next((r for r in results if r is not None), None)
                if result is None:
                    raise RuntimeError(
                        "no serve worker returned a step result "
                        "(rank 0's return value was lost)")
            except BaseException as e:   # noqa: BLE001 - fleet failure
                _log.error("serve step failed; failing %d live request(s)",
                           sched.active_count + sched.queued_count,
                           exc_info=True)
                self._error = e
                # black boxes FIRST: dump every rank's flight ring with
                # the serve cause while the evidence is fresh (the
                # elastic fit driver's death-classification discipline,
                # now on the serve pump too), then fail the waiters
                self.failure_report = self._dump_flights(e)
                sched.fail_all(e)
                return
            if ledger is not None:
                # attribution rule: a dispatch that decodes produced
                # tokens (useful); a prefill-only dispatch is context
                # build — measured, but not goodput.  A speculative
                # round splits out its draft/verify wall (worker-
                # measured) so the ledger shows what speculation costs;
                # the verify IS the token-producing target forward, so
                # it stays in the useful "decode" bucket.
                step_s = time.monotonic() - t_step
                timing = result.get("timing") or {}
                draft_s = float(timing.get("draft", 0.0))
                if plan.get("decode") is not None:
                    ledger.add("draft", min(draft_s, step_s))
                    ledger.note_step(max(0.0, step_s - draft_s))
                else:
                    ledger.add("prefill", step_s)
            sched.apply(plan, result)
            if self._profile_ctl is not None:
                self._profile_ctl.note_step()

    # -- goodput (telemetry/goodput.py) ------------------------------------

    def _make_goodput_ledger(self):
        """Open the serve-side wall-clock ledger when the plane is
        armed: every pump second lands in decode / prefill /
        queue_idle (residual → other; the router adds autoscale
        actuation at the fleet level)."""
        cfg = self.telemetry
        if self._agg is None or not cfg.resolved_goodput():
            return None
        from ray_lightning_tpu.telemetry import goodput as _goodput
        devices = self.num_workers * int(self.devices_per_worker or 1)
        return _goodput.GoodputLedger(
            "serve", device_tflops=cfg.resolved_goodput_tflops(),
            devices=devices).start()

    def _ship_goodput(self, doc: dict) -> None:
        if self._agg is None or not doc:
            return
        from ray_lightning_tpu.telemetry import goodput as _goodput
        try:
            self._agg.ingest_goodput(_goodput.goodput_item(0, doc))
        except Exception:
            _log.debug("serve goodput ingest failed", exc_info=True)

    def _finish_goodput(self) -> None:
        ledger = self._goodput_ledger
        if ledger is None:
            return
        self._goodput_ledger = None
        from ray_lightning_tpu.telemetry import goodput as _goodput
        self.goodput_doc = doc = ledger.finalize()
        self._ship_goodput(doc)
        _goodput.publish_metrics(doc)

    def goodput(self) -> Optional[dict]:
        """This replica's goodput doc: the finalized partition after
        the pump exits, a live peek while it runs, None when the plane
        is disarmed.  The fleet router aggregates these
        (serve/fleet/router.py)."""
        if self.goodput_doc is not None:
            return self.goodput_doc
        ledger = self._goodput_ledger
        return ledger.peek() if ledger is not None else None

    def _dump_flights(self, error: BaseException) -> dict:
        """Per-rank ``flight_<rank>.json`` dumps for a mid-serve fleet
        failure (telemetry/flight.py).  Never raises — this runs inside
        the pump's failure handling."""
        report: dict = {"cause": repr(error), "flight_paths": {}}
        if self._agg is None:
            return report
        try:
            self._agg.log_failure_diagnosis()
            self._agg.dump_flights(
                range(self.num_workers),
                cause=f"serve fleet failure: {error!r}")
            report["flight_paths"] = {
                int(r): p for r, p in self._agg.flight.dumped.items()}
        except Exception:
            _log.warning("serve flight dump failed", exc_info=True)
        return report

    def _drain_queue(self) -> None:
        backend = self._backend
        while True:
            item = backend.queue_get_nowait()
            if item is None:
                return
            _handle_queue_item(item)

    def _watchdog(self) -> None:
        if self._agg is not None:
            try:
                self._agg.watchdog_check()
            except Exception:
                _log.warning("serve watchdog error", exc_info=True)

    def _wait_all(self, futures, timeout: float) -> list:
        """Resolve every worker future, relaying queue traffic while
        waiting (the fit path's process_results discipline)."""
        deadline = time.monotonic() + timeout
        while not all(f.done() for f in futures):
            if self._backend is not None:
                self._drain_queue()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"serve worker call not done after {timeout}s")
            time.sleep(0.002)
        return [f.result() for f in futures]

    # -- drain / shutdown --------------------------------------------------

    def drain(self, timeout: Optional[float] = 300.0) -> None:
        """Graceful drain: stop admitting, finish every in-flight and
        queued request, stop the pump.  Idempotent."""
        self._draining = True
        self._work.set()
        if self._pump is not None and self._pump.is_alive():
            self._pump.join(timeout)
            if self._pump.is_alive():
                raise TimeoutError(f"drain incomplete after {timeout}s")

    def stats(self) -> dict:
        """Scheduler + worker evidence (trace counts, compile-cache
        hits) in one dict."""
        out = {"scheduler": self.scheduler.stats(),
               "setup": self._setup_info}
        gp = self.goodput()
        if gp:
            out["goodput"] = gp
        if self.failure_report is not None:
            out["failure"] = self.failure_report
        if self._started and self._workers:
            try:
                out["workers"] = self._wait_all(
                    [w.call("serve_stats") for w in self._workers],
                    timeout=60)
            except Exception:
                _log.warning("serve_stats failed", exc_info=True)
        return out

    def shutdown(self, graceful: bool = True) -> None:
        """Drain (when ``graceful``), tear down telemetry and the
        fleet.  The process-wide cluster backend stays up (it is shared
        with any co-resident trainer)."""
        if graceful and self._started and self._error is None:
            try:
                self.drain()
            except TimeoutError:
                _log.warning("graceful drain timed out; killing fleet")
        self._stop.set()
        self._work.set()
        if self._pump is not None and self._pump.is_alive():
            self._pump.join(10)
        if self._started:
            try:
                self._wait_all([w.call("teardown_serve")
                                for w in self._workers], timeout=30)
            except Exception:
                _log.warning("serve teardown failed", exc_info=True)
        self._kill_workers()
        if self._agg is not None:
            from ray_lightning_tpu import telemetry
            telemetry.set_active(None)
            if self.telemetry.metrics:
                # only tear down the process-wide registry when THIS
                # server enabled it — a fleet replica running with
                # metrics=False must not disable the FleetServer's
                # driver registry on shrink (serve/fleet/router.py)
                telemetry.flush_metrics()
                telemetry.disable_metrics()
            if self._metrics_server is not None:
                self._metrics_server.stop()
            self.telemetry_paths = self._agg.export()
            if self._metrics_server is not None:
                self.telemetry_paths["metrics_url"] = \
                    self._metrics_server.url
            self._agg = None
            self._metrics_server = None
            self._profile_ctl = None
        self._started = False

    def _kill_workers(self) -> None:
        for w in self._workers:
            try:
                w.kill()
            except Exception:
                pass
        self._workers = []

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(graceful=exc[0] is None)


__all__ = ["Server", "ServeSpec"]
