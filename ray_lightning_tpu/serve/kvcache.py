"""Slot-indexed, device-resident KV cache for continuous batching.

The cache is two arrays ``[n_layer, S, L, H, D]`` (keys / values): ``S``
batch slots x ``L`` max context, living on device for the whole life of
the serve fleet and sharded through the training strategies
(``ShardingStrategy.kv_cache_spec`` — slots ride the data axes like a
batch dim, heads ride ``tensor`` under SPMD).  In-flight request
insertion and eviction are SLOT INDEX operations:

- insert  = the bucket prefill program ``dynamic_update_slice``-writes a
  prompt's K/V block at its slot (core/steps.py build_prefill_step);
- advance = the decode program scatter-writes one position per slot
  (ops/attention.py cached_attention);
- evict   = the driver frees the slot index — NO device work.  Stale
  K/V beyond a slot's position bound are unreachable by construction
  (the per-slot position mask), so a freed slot is reusable the moment
  the next prefill overwrites its prefix.

Shapes are static whatever the live-request mix, so the decode loop
never re-traces — the property the serve acceptance pins with trace
counters (serve/engine.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class KVCacheSpec:
    """Host-side description of the device cache (picklable; shipped to
    workers inside the serve payload)."""

    n_layer: int
    slots: int
    max_seq_len: int
    n_head: int
    head_dim: int

    @property
    def shape(self) -> tuple[int, int, int, int, int]:
        return (self.n_layer, self.slots, self.max_seq_len, self.n_head,
                self.head_dim)

    def nbytes(self, itemsize: int = 2) -> int:
        """Device residency of BOTH cache arrays (k and v) at the given
        element size (bf16 default)."""
        return 2 * int(np.prod(self.shape, dtype=np.int64)) * itemsize

    @classmethod
    def from_capture(cls, kv_shapes, slots: int,
                     max_seq_len: int) -> "KVCacheSpec":
        """Derive the cache geometry from a prefill ``eval_shape``
        capture: ``kv_shapes`` is any per-layer K aval list with entries
        shaped ``[B, T, H, D]`` (core/steps.py _stacked_kv order)."""
        n_layer = len(kv_shapes)
        if n_layer == 0:
            raise ValueError("model captured no kv_cache entries; does "
                             "its attention sow the 'kv_cache' "
                             "collection? (ops/attention.py)")
        _, _, n_head, head_dim = kv_shapes[0].shape
        return cls(n_layer=n_layer, slots=slots, max_seq_len=max_seq_len,
                   n_head=int(n_head), head_dim=int(head_dim))


class SlotAllocator:
    """Driver-side free-list of cache slots (the host half of
    insert/evict; the device half is the index writes above)."""

    def __init__(self, slots: int):
        if slots < 1:
            raise ValueError(f"need >= 1 slot, got {slots}")
        self.slots = slots
        self._free = list(range(slots))
        self._used: set[int] = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._used)

    def acquire(self) -> "int | None":
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._used.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._used:
            raise ValueError(f"slot {slot} is not in use")
        self._used.remove(slot)
        self._free.append(slot)

    def in_use(self) -> tuple[int, ...]:
        return tuple(sorted(self._used))


__all__ = ["KVCacheSpec", "SlotAllocator"]
