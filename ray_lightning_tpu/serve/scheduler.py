"""Driver-side request queue + continuous-batching scheduler.

Admission control and batch formation for the serve plane.  The
scheduler owns every piece of host-side generation state — per-tenant
FIFOs, the slot free-list, each request's position cursor — so workers
stay stateless between steps (params + KV cache only): one plan object
broadcast to every worker fully determines the step, which is what
keeps a multi-host SPMD fleet in lockstep.

Scheduling policy:

- **Per-tenant quota**: a tenant never holds more than
  ``quota`` concurrent batch slots (unbounded by default).
- **Fair-share ordering**: when slots free up, the next admission goes
  to the queued tenant with the fewest active slots, ties broken by
  fewest total served tokens, then FIFO arrival — a deficit-style
  policy under which a chatty tenant cannot starve a quiet one.
- **Continuous batching**: ONE decode program advances every live
  slot a token, and at most ``max_prefills_per_step`` prompt prefills
  are injected per step (bounding decode-latency jitter for in-flight
  requests).  A request admitted at step k starts decoding at step
  k+1 (its first token comes out of the prefill itself) — which is why
  the worker dispatches the decode BEFORE the prefills: the decode's
  static shapes make it write a dummy position-0 K/V entry for every
  slot outside ``decode_slots``, and the admitting prefill must land
  after that write, not before (worker.py serve_step).

Invariants (pinned by tests/test_serve.py and serve/selfcheck.py):
slot indices are unique among live requests; per-tenant active count
never exceeds its quota; a submitted request is eventually completed
(no starvation) while the pump keeps stepping.

Trace plane (telemetry/tracing.py): every request carries a trace id
minted at submit; the plan broadcast propagates it to the workers
(prefill entries ``trace=``, decode a slot→trace map), and the
scheduler records the driver-side phases — a ``queue_wait`` span at
admission and a ``request`` summary span at completion/failure carrying
the latency attribution — so the aggregator reassembles one span tree
per request.  Failed/drained requests land in the TTFT/TPOT histograms
under ``status="failed"`` (``fail_all``), never silently unobserved.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from ray_lightning_tpu.serve.buckets import bucket_for, pad_to_bucket
from ray_lightning_tpu.serve.kvcache import SlotAllocator
from ray_lightning_tpu.telemetry import metrics as _metrics
from ray_lightning_tpu.telemetry import tracing as _tracing

#: histogram bounds for TTFT/TPOT (seconds): sub-ms CPU-mesh decodes up
#: to multi-second cold paths
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class ServeRequest:
    """One in-flight generation request (driver-side handle).

    ``result(timeout)`` blocks until the request completes and returns
    the generated token ids (numpy int32).  TTFT/TPOT timestamps are
    recorded here and fed to the metrics plane by the scheduler.
    """

    def __init__(self, req_id: int, tenant: str, tokens: np.ndarray,
                 max_new_tokens: int, eos_token: Optional[int]):
        self.id = req_id
        self.tenant = tenant
        self.tokens = tokens
        self.max_new_tokens = max_new_tokens
        self.eos_token = eos_token
        self.state = "queued"
        self.slot: Optional[int] = None
        self.bucket: Optional[int] = None
        self.generated: list[int] = []
        #: absolute position of the LAST generated token (the next
        #: decode step's input position)
        self.pos: Optional[int] = None
        #: distributed trace id (telemetry/tracing.py): rides the plan
        #: broadcast to the workers, whose prefill/decode spans carry it
        #: back, so the aggregator reassembles this request's span tree
        self.trace = _tracing.mint_trace_id()
        #: speculative-decode per-request state (serve/spec.py): the
        #: rolling window of per-round accepted counts the fallback
        #: watches, and the ``spec_off`` latch — once acceptance
        #: collapses below the floor this request takes only the
        #: verify's first (= plain-decode) token for its remaining life
        self.spec_off = False
        self._spec_window = None
        self.t_submit = time.monotonic()
        #: wall-clock twins of the monotonic stamps — the trace plane's
        #: synthetic driver spans must share the workers' wall timeline
        self.t_submit_wall = time.time()
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    # -- user surface -----------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not complete after {timeout}s")
        if self.error is not None:
            raise self.error
        return np.asarray(self.generated, dtype=np.int32)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        """Time-per-output-token over the decode phase (excludes the
        prefill-produced first token)."""
        if self.t_done is None or self.t_first is None \
                or len(self.generated) < 2:
            return None
        return (self.t_done - self.t_first) / (len(self.generated) - 1)

    @property
    def queue_wait_s(self) -> Optional[float]:
        """Submit→admission wait — the queue's share of TTFT (the
        per-tenant p99 the bench and /status report)."""
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def decode_s(self) -> Optional[float]:
        """First token→completion — the decode share of total latency."""
        if self.t_done is None or self.t_first is None:
            return None
        return self.t_done - self.t_first

    # -- scheduler internal ------------------------------------------------

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        self.state = "done" if error is None else "failed"
        self.t_done = time.monotonic()
        self._event.set()


@dataclass
class _Tenant:
    name: str
    quota: Optional[int] = None          # max concurrent slots
    queue: list = field(default_factory=list)
    active: int = 0
    served_tokens: int = 0
    # per-tenant speculative-decode accounting (acceptance_rate rides
    # the same per_tenant stats block quotas do)
    spec_drafted: int = 0
    spec_accepted: int = 0


class Scheduler:
    """Continuous-batching planner over ``slots`` KV-cache slots."""

    def __init__(self, buckets: Sequence[int], slots: int,
                 max_seq_len: int,
                 quotas: "dict[str, int] | int | None" = None,
                 max_prefills_per_step: int = 1,
                 default_max_new_tokens: int = 32,
                 eos_token: Optional[int] = None,
                 paged: Any = None,
                 spec: Any = None):
        self.buckets = tuple(buckets)
        self.max_seq_len = int(max_seq_len)
        self.allocator = SlotAllocator(slots)
        #: paged-KV prefix reuse (serve/fleet/pages.py): page free-list
        #: accounting, the prefix-hash index, and donor retention of
        #: finished slots.  None = pre-fleet behavior, byte-identical.
        self.pages = None
        if paged is not None and getattr(paged, "enabled", False):
            from ray_lightning_tpu.serve.fleet.pages import PagedKV
            self.pages = PagedKV(paged, slots, self.max_seq_len)
        #: speculative decoding (serve/spec.py SpecConfig): when set,
        #: decode steps are planned as draft→verify rounds and apply()
        #: folds multi-token results; the emitted stream stays EXACTLY
        #: greedy-parity (only the target's verify decides tokens)
        self.spec = spec \
            if spec is not None and getattr(spec, "enabled", False) \
            else None
        self._spec = {"drafted": 0, "accepted": 0, "corrected": 0,
                      "emitted": 0, "slot_steps": 0, "rounds": 0,
                      "fallbacks": 0}
        self.max_prefills_per_step = max(1, int(max_prefills_per_step))
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_token = eos_token
        self._default_quota: Optional[int] = (
            int(quotas) if isinstance(quotas, int) else None)
        self._quotas: dict[str, int] = (
            dict(quotas) if isinstance(quotas, dict) else {})
        self._tenants: dict[str, _Tenant] = {}
        self._by_slot: dict[int, ServeRequest] = {}
        #: ship-bound prefills' exported KV rows, keyed by request id
        #: (plan ``export_kv`` → apply stash → Server.export_kv pop);
        #: FIFO-capped so abandoned ships can't hold rows forever
        self._kv_outbox: dict[int, tuple] = {}
        self._ids = itertools.count()
        self._arrival = itertools.count()
        self._order: dict[int, int] = {}     # req id -> arrival seq
        self._lock = threading.Lock()
        self.completed = 0
        self.failed = 0
        self._occupancy_sum = 0.0
        self._decode_steps = 0
        # rolling latency tails (incident plane): the histograms above
        # are cumulative-forever, so a live p99 regression drowns in
        # history — these bounded deques carry only the recent window
        # the serve detectors watch (server.py note_serve_signals)
        from collections import deque
        self._recent_ttfts: "deque[float]" = deque(maxlen=128)
        self._recent_tpots: "deque[float]" = deque(maxlen=128)

    # -- admission ---------------------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(
                name, self._quotas.get(name, self._default_quota))
        return t

    def submit(self, tokens, tenant: str = "default",
               max_new_tokens: Optional[int] = None,
               ship_kv: bool = False) -> ServeRequest:
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        if len(tokens) == 0:
            raise ValueError("empty prompt")
        bucket = bucket_for(len(tokens), self.buckets)  # raises if too long
        want = max_new_tokens if max_new_tokens is not None \
            else self.default_max_new_tokens
        # the final produced token never writes K/V, so the precise cap
        # is context - prompt_len + 1 (kvcache.py position invariant)
        cap = self.max_seq_len - len(tokens) + 1
        req = ServeRequest(next(self._ids), tenant, tokens,
                           max(1, min(int(want), cap)), self.eos_token)
        req.bucket = bucket
        # disagg leg-1: the prefill step piggybacks a KV-row export
        # (plan's ``export_kv`` entry) into the kv outbox, so the
        # router's ship never races donor eviction for the rows
        req._ship_kv = bool(ship_kv)
        if self.spec is not None:
            from collections import deque
            req._spec_window = deque(maxlen=self.spec.window)
        with self._lock:
            self._order[req.id] = next(self._arrival)
            self._tenant(tenant).queue.append(req)
        self._gauge("rlt_serve_queue_depth_total", self.queued_count)
        return req

    # -- planning ----------------------------------------------------------

    @property
    def queued_count(self) -> int:
        with self._lock:
            return sum(len(t.queue) for t in self._tenants.values())

    @property
    def active_count(self) -> int:
        return len(self._by_slot)

    def idle(self) -> bool:
        return self.queued_count == 0 and self.active_count == 0

    def _admissible_tenants(self) -> list[_Tenant]:
        out = []
        for t in self._tenants.values():
            if not t.queue:
                continue
            if t.quota is not None and t.active >= t.quota:
                continue
            out.append(t)
        return out

    def plan(self) -> Optional[dict]:
        """One scheduler step: admissions (fair-share + quota) into free
        slots, then a decode over every already-live slot.  ``None``
        when there is nothing to do."""
        prefills = []
        with self._lock:
            budget = self.max_prefills_per_step
            while budget > 0:
                candidates = self._admissible_tenants()
                if not candidates:
                    break
                # fair share: fewest active slots, then fewest served
                # tokens, then FIFO arrival of the head request
                tenant = min(candidates, key=lambda t: (
                    t.active, t.served_tokens, self._order[t.queue[0].id]))
                req = tenant.queue[0]
                # prefix match BEFORE any donor eviction, so admission
                # pressure never evicts the one donor this request is
                # about to copy from (its LRU stamp refreshes here too)
                hit = self.pages.match(req.tokens) \
                    if self.pages is not None else None
                if self.allocator.free_count == 0:
                    # admission pressure evicts the least-recently-
                    # useful retained prefix donor (fleet/pages.py);
                    # without paging a full allocator ends admission
                    evicted = None
                    if self.pages is not None:
                        evicted = self.pages.evict_lru_donor(
                            exclude=hit[0] if hit is not None else None)
                        if evicted is None and hit is not None:
                            # the hit donor is the ONLY reclaimable
                            # slot: admission beats reuse
                            evicted = self.pages.evict_lru_donor()
                            if evicted is not None:
                                hit = None
                    if evicted is None:
                        break
                    self.allocator.release(evicted)
                tenant.queue.pop(0)
                slot = self.allocator.acquire()
                req.slot = slot
                req.state = "active"
                req.t_admit = time.monotonic()
                tenant.active += 1
                self._by_slot[slot] = req
                entry = {
                    "req": req.id, "slot": slot, "bucket": req.bucket,
                    "tokens": pad_to_bucket(req.tokens, req.bucket),
                    "length": int(len(req.tokens)),
                    # trace id: the driver→worker leg of the trace-
                    # context propagation (the worker's prefill span
                    # carries it back on the queue channel)
                    "trace": req.trace,
                }
                if self.spec is not None:
                    # prime the draft KV cache alongside the target's
                    # (worker.py runs engine.draft_prefill after the
                    # target prefill) so round one can draft immediately
                    entry["draft"] = True
                computed = len(req.tokens)
                reuse_src = None
                if self.pages is not None:
                    if hit is not None and hit[1] >= self.pages.page_size:
                        src, matched = hit
                        reuse_src = int(src)
                        entry["reuse"] = {"src": int(src),
                                          "matched": int(matched)}
                        computed = max(1, len(req.tokens) - matched)
                    if getattr(req, "_ship_kv", False):
                        # ship-bound prefill: the worker returns the
                        # slot's whole-page KV rows WITH the step
                        # result (no later export RPC, no donor-
                        # eviction race) — apply() stashes them in the
                        # kv outbox for the router's ship leg
                        pages = (len(req.tokens)
                                 // self.pages.page_size) \
                            * self.pages.page_size
                        if pages >= self.pages.page_size:
                            entry["export_kv"] = {
                                "bucket": int(bucket_for(
                                    pages, self.buckets)),
                                "matched": int(pages)}
                    self.pages.on_admit(slot, req.tokens, computed,
                                        src=reuse_src)
                    self._count("rlt_serve_prefill_tokens_total",
                                len(req.tokens), kind="requested")
                    self._count("rlt_serve_prefill_tokens_total",
                                computed, kind="computed")
                prefills.append(entry)
                budget -= 1
                # the queue-wait phase of this request's span tree +
                # its numeric twin (per-tenant labeled histogram)
                wait = req.queue_wait_s
                _tracing.record_request_span(
                    "queue_wait", req.t_submit_wall, time.time(),
                    trace=req.trace, tenant=req.tenant, req=req.id)
                self._observe("rlt_serve_queue_wait_seconds", wait,
                              tenant=req.tenant)
            # decode advances every slot that already HAS a first token
            # (slots prefilled this very step join the next decode)
            decode_slots = sorted(
                s for s, r in self._by_slot.items() if r.pos is not None)
        decode = None
        if decode_slots:
            S = self.allocator.slots
            tokens = np.zeros((S,), dtype=np.int32)
            # dummy decode writes for idle slots: position 0 normally
            # (overwritten by the slot's admitting prefill), but under
            # paging the LAST row — position 0 is the first page of
            # every retained prefix donor, and a dummy write there
            # would corrupt the donated K/V (fleet/pages.py docstring;
            # the last row is never registered, and a live slot
            # overwrites it before it can ever be attended)
            fill = self.max_seq_len - 1 if self.pages is not None else 0
            positions = np.full((S,), fill, dtype=np.int32)
            for s in decode_slots:
                r = self._by_slot[s]
                tokens[s] = r.generated[-1]
                positions[s] = r.pos
            decode = {"tokens": tokens, "positions": positions,
                      "slots": decode_slots,
                      # slot→trace map: ONE decode program advances many
                      # requests, so its worker span fans out to every
                      # live request's tree (aggregator._span_trace_ids)
                      "traces": {s: self._by_slot[s].trace
                                 for s in decode_slots}}
            # speculative round only while at least one live slot still
            # speculates — when EVERY request has fallen back the plain
            # decode program runs and the draft cost disappears
            if self.spec is not None and any(
                    not self._by_slot[s].spec_off for s in decode_slots):
                decode["spec"] = True
        if not prefills and decode is None:
            return None
        if decode is not None:
            self._occupancy_sum += (
                len(decode_slots) + len(prefills)) / self.allocator.slots
            self._decode_steps += 1
        self._gauge("rlt_serve_queue_depth_total", self.queued_count)
        self._gauge("rlt_serve_active_slots_total",
                    len(self._by_slot))
        return {"prefills": prefills, "decode": decode}

    # -- result application ------------------------------------------------

    def apply(self, plan: dict, result: dict) -> None:
        """Fold one step's worker result (``{"prefill": {slot: token},
        "decode": {slot: token}}``) back into request state: first
        tokens (TTFT), appended tokens, completions (slot eviction)."""
        now = time.monotonic()
        for p in plan["prefills"]:
            slot = p["slot"]
            req = self._by_slot[slot]
            exp = p.get("export_kv")
            if exp is not None:
                rows = (result.get("kv_export") or {}).get(slot)
                if rows is not None:
                    with self._lock:
                        self._kv_outbox[req.id] = (
                            rows[0], rows[1], exp["matched"])
                        while len(self._kv_outbox) > 64:
                            self._kv_outbox.pop(
                                next(iter(self._kv_outbox)))
            tok = int(result["prefill"][slot])
            req.t_first = now
            req.generated.append(tok)
            req.pos = len(req.tokens)       # the first token's position
            self._observe("rlt_serve_ttft_seconds", req.ttft_s,
                          status="ok")
            if req.ttft_s is not None:
                self._recent_ttfts.append(req.ttft_s)
            self._count("rlt_serve_tokens_total", 1, tenant=req.tenant)
            self._tenant(req.tenant).served_tokens += 1
            self._maybe_finish(req, tok)
        if plan.get("decode") is not None:
            for slot in plan["decode"]["slots"]:
                req = self._by_slot.get(slot)
                if req is None:      # finished by a racing eviction
                    continue
                res = result["decode"][slot]
                if isinstance(res, dict):
                    self._apply_spec(req, slot, res)
                    continue
                tok = int(res)
                req.generated.append(tok)
                req.pos += 1
                if self.pages is not None:
                    # lazy page charge as the decode tail grows
                    self.pages.on_advance(slot, req.pos)
                self._count("rlt_serve_tokens_total", 1,
                            tenant=req.tenant)
                self._tenant(req.tenant).served_tokens += 1
                self._maybe_finish(req, tok)
            if plan["decode"].get("spec") and self._spec["drafted"]:
                self._spec["rounds"] += 1
                self._gauge("rlt_spec_acceptance_rate",
                            self._spec["accepted"]
                            / self._spec["drafted"])

    def _apply_spec(self, req: ServeRequest, slot: int,
                    res: dict) -> None:
        """Fold one slot's draft→verify round.

        The worker returns the raw programs' outputs — ``draft`` (the
        k tokens the draft model proposed) and ``verify`` (the target's
        k+1 greedy argmaxes over [last_token, d1..dk]).  THE SCHEDULER
        decides acceptance: the longest prefix where draft and target
        agree, plus the target's one corrected token.  ``verify[0]`` is
        by construction exactly what the plain decode program would have
        produced (same query token, same position, same cache rows), and
        each later ``verify[j]`` conditions on ``d1..dj`` which equal
        the accepted stream — so the emitted tokens are token-level
        IDENTICAL to target-only greedy decode for ANY draft quality.

        KV soundness: verify wrote target rows for all k+1 positions;
        the rows past the accepted prefix hold rejected-draft garbage,
        but the per-query position mask hides them and the next round's
        verify overwrites them before they can ever be attended.

        A ``spec_off`` request (acceptance collapsed below
        ``min_accept``) rides the same batch but takes only
        ``verify[0]`` and charges no draft accounting."""
        d = [int(x) for x in res["draft"]]
        g = [int(x) for x in res["verify"]]
        k = len(d)
        m = 0
        while m < k and d[m] == g[m]:
            m += 1
        emit = g[:1] if req.spec_off else g[:m + 1]
        appended = 0
        for tok in emit:
            req.generated.append(tok)
            req.pos += 1
            appended += 1
            if self.pages is not None:
                self.pages.on_advance(slot, req.pos)
            self._count("rlt_serve_tokens_total", 1, tenant=req.tenant)
            self._tenant(req.tenant).served_tokens += 1
            self._maybe_finish(req, tok)
            if req.state != "active":
                break                # eos / max_new: drop the tail
        if req.spec_off:
            return
        # acceptance accounting: identity ``emitted == accepted +
        # corrected`` (serve/selfcheck.py); a truncated emission counts
        # only what actually reached the stream
        accepted = min(appended, m)
        self._spec["drafted"] += k
        self._spec["accepted"] += accepted
        self._spec["corrected"] += appended - accepted
        self._spec["emitted"] += appended
        self._spec["slot_steps"] += 1
        t = self._tenant(req.tenant)
        t.spec_drafted += k
        t.spec_accepted += accepted
        self._count("rlt_spec_drafted_total", k, tenant=req.tenant)
        self._count("rlt_spec_accepted_total", accepted,
                    tenant=req.tenant)
        # per-request fallback: rolling model-level agreement (m, not
        # the truncated count — acceptance measures draft quality)
        w = req._spec_window
        if w is None or self.spec.min_accept <= 0.0 \
                or req.state != "active":
            return
        w.append(m)
        if len(w) >= max(1, w.maxlen // 2) \
                and sum(w) / (len(w) * k) < self.spec.min_accept:
            req.spec_off = True
            self._spec["fallbacks"] += 1
            self._count("rlt_spec_fallbacks_total", 1,
                        tenant=req.tenant)

    def _maybe_finish(self, req: ServeRequest, last_token: int) -> None:
        hit_eos = (req.eos_token is not None
                   and last_token == req.eos_token)
        if len(req.generated) < req.max_new_tokens and not hit_eos:
            return
        with self._lock:
            self._by_slot.pop(req.slot, None)
            # under paging a finished slot with registered prefix pages
            # is RETAINED as a donor (allocator keeps it; admission
            # pressure evicts LRU donors in plan()) — the cross-request
            # half of "shared system prompts prefill once per replica"
            retained = self.pages.retain(req.slot) \
                if self.pages is not None else False
            if not retained:
                self.allocator.release(req.slot)
            self._tenant(req.tenant).active -= 1
            self.completed += 1
        req._finish()     # stamps t_done — tpot_s is defined only after
        self._observe("rlt_serve_tpot_seconds", req.tpot_s, status="ok")
        if req.tpot_s is not None:
            self._recent_tpots.append(req.tpot_s)
        self._count("rlt_serve_requests_total", 1, tenant=req.tenant,
                    status="ok")
        self._request_span(req, "ok")

    def _request_span(self, req: ServeRequest, status: str) -> None:
        """The request's driver-side summary span: whole submit→done
        life on the wall timeline, carrying the latency attribution the
        aggregator's tenant_breakdown reads (queue_s/ttft_s/tpot_s)."""
        _tracing.record_request_span(
            "request", req.t_submit_wall, time.time(),
            trace=req.trace, tenant=req.tenant, req=req.id,
            status=status, tokens=len(req.generated),
            queue_s=req.queue_wait_s, ttft_s=req.ttft_s,
            tpot_s=req.tpot_s)

    def fail_all(self, error: BaseException) -> None:
        """Propagate a fleet failure into every live/queued request so
        no caller blocks forever on ``result()``.

        Latency accounting (trace-plane satellite): failed and drained
        requests used to vanish from the TTFT/TPOT histograms entirely,
        biasing them optimistic — a fleet that fell over under load
        reported only the requests that finished before it did.  Every
        request failed here now lands in the histograms under a
        ``status="failed"`` label: time-to-failure for requests that
        never produced a token, the partial decode rate for those that
        did."""
        now = time.monotonic()
        with self._lock:
            live = list(self._by_slot.values())
            queued = [r for t in self._tenants.values() for r in t.queue]
            for t in self._tenants.values():
                t.queue.clear()
                t.active = 0
            self._by_slot.clear()
            self.allocator = SlotAllocator(self.allocator.slots)
            if self.pages is not None:
                self.pages.drop_all()
            self.failed += len(live) + len(queued)
        for r in live + queued:
            r._finish(error)
            # TTFT for a request that never got a first token = its
            # time-to-failure; a partially-decoded one keeps its real
            # TTFT and gets a failure-truncated TPOT
            ttft = r.ttft_s if r.t_first is not None \
                else now - r.t_submit
            self._observe("rlt_serve_ttft_seconds", ttft,
                          status="failed")
            if r.t_first is not None and len(r.generated) >= 2:
                self._observe(
                    "rlt_serve_tpot_seconds",
                    (r.t_done - r.t_first) / (len(r.generated) - 1),
                    status="failed")
            self._count("rlt_serve_requests_total", 1, tenant=r.tenant,
                        status="failed")
            self._request_span(r, "failed")

    def withdraw_queued(self) -> "list[ServeRequest]":
        """Pull every not-yet-admitted request out of the tenant queues
        WITHOUT finishing or failing it — the fleet router's shrink-
        drain and failover paths re-dispatch the withdrawn requests to
        a surviving replica (serve/fleet/router.py).  In-flight
        (admitted) requests are untouched: they hold KV state only this
        replica has."""
        with self._lock:
            out: list[ServeRequest] = []
            for t in self._tenants.values():
                out.extend(t.queue)
                t.queue.clear()
            for r in out:
                self._order.pop(r.id, None)
                r.state = "withdrawn"
        self._gauge("rlt_serve_queue_depth_total", 0)
        return out

    # -- KV-ship adoption (fleet disaggregation) ---------------------------
    #
    # A decode replica installs IMPORTED donor K/V rows (a prefill
    # replica computed them, the router shipped the pages) as a prefix
    # donor, so the very next admission of the matching prompt reuses
    # the shipped rows through the normal ``kv_copy`` + suffix path —
    # shipping plugs into prefix reuse rather than growing a second
    # install mechanism.  Three steps because registration must come
    # AFTER the engine's import lands on every worker: a prompt that
    # matched a registered-but-not-yet-installed donor would kv_copy
    # uninitialized rows (adopt → engine.import_kv → commit).

    def pop_kv_export(self, req_id: int) -> "tuple | None":
        """Claim a ship-bound prefill's piggybacked KV rows
        (``(k_rows, v_rows, matched_tokens)``), once."""
        with self._lock:
            return self._kv_outbox.pop(req_id, None)

    def adopt_imported(self, tokens) -> Optional[int]:
        """Acquire (only) a slot to host shipped rows.  ``None`` when
        paging is off or no slot can be freed — the router then falls
        back to a pooled-mode prefill on the decode replica."""
        if self.pages is None:
            return None
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        if len(tokens) < self.pages.page_size:
            return None              # nothing page-aligned to donate
        with self._lock:
            if self.allocator.free_count == 0:
                evicted = self.pages.evict_lru_donor()
                if evicted is None:
                    return None      # every slot live: no room to adopt
                self.allocator.release(evicted)
            return self.allocator.acquire()

    def adopt_commit(self, slot: int, tokens) -> None:
        """Register + retain the installed donor (rows are live on
        every worker).  Registers directly, NOT via on_admit: these
        rows were shipped, not prefilled — the prefix_reuse savings
        counters must not claim them as locally-avoided compute."""
        with self._lock:
            reg = self.pages.index.register(
                slot, tokens, limit=self.max_seq_len - 1)
            if reg == 0 or not self.pages.retain(slot):
                self.allocator.release(slot)     # unreachable guard
                return
            # remote-donor accounting: reuse hits copying from this
            # slot count as FEDERATED savings (the prefill happened on
            # another replica), not local prefix_reuse wins
            self.pages.mark_remote(slot)

    def adopt_abort(self, slot: int) -> None:
        """Give the slot back (the ship failed mid-install)."""
        with self._lock:
            self.pages.index.drop(slot)
            self.pages.pool.release(slot)
            self.allocator.release(slot)

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        pages = {"pages": self.pages.stats()} \
            if self.pages is not None else {}
        spec = {}
        if self.spec is not None:
            s = dict(self._spec)
            s["k"] = self.spec.k
            s["acceptance_rate"] = round(
                s["accepted"] / s["drafted"], 4) if s["drafted"] else 0.0
            # tokens emitted per target forward — the CPU-proxy win
            # metric (>1 means speculation amortized target compute)
            s["tokens_per_target_forward"] = round(
                s["emitted"] / s["slot_steps"], 4) \
                if s["slot_steps"] else 0.0
            spec = {"spec": s}
        return {
            **pages,
            **spec,
            "completed": self.completed,
            "failed": self.failed,
            "queued": self.queued_count,
            "active": self.active_count,
            "batch_occupancy": (
                self._occupancy_sum / self._decode_steps
                if self._decode_steps else 0.0),
            "decode_steps": self._decode_steps,
            "per_tenant": {
                name: {"active": t.active, "queued": len(t.queue),
                       "served_tokens": t.served_tokens,
                       "quota": t.quota,
                       **({"acceptance_rate": round(
                           t.spec_accepted / t.spec_drafted, 4)
                           if t.spec_drafted else 0.0}
                          if self.spec is not None else {})}
                for name, t in self._tenants.items()},
        }

    @staticmethod
    def _tail_p99(tail) -> Optional[float]:
        vals = sorted(tail)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    def recent_ttft_p99(self) -> Optional[float]:
        """p99 of the last ≤128 first-token latencies (None = no
        completed prefills yet) — the serve detectors' TTFT signal."""
        return self._tail_p99(self._recent_ttfts)

    def recent_tpot_p99(self) -> Optional[float]:
        """p99 of the last ≤128 per-token decode latencies."""
        return self._tail_p99(self._recent_tpots)

    # -- metrics plumbing (no-ops when the metrics plane is off) -----------

    @staticmethod
    def _count(name: str, value: float, **labels: Any) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.counter(name).inc(value, **labels)

    @staticmethod
    def _gauge(name: str, value: float) -> None:
        reg = _metrics.get_registry()
        if reg is not None:
            reg.gauge(name).set(value)

    @staticmethod
    def _observe(name: str, value: Optional[float],
                 **labels: Any) -> None:
        reg = _metrics.get_registry()
        if reg is not None and value is not None:
            reg.histogram(name, buckets=LATENCY_BUCKETS).observe(
                value, **labels)


__all__ = ["Scheduler", "ServeRequest", "LATENCY_BUCKETS"]
