"""Serve-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the comm/compile selfchecks: cheap, deterministic,
no pytest — validates the invariants that would otherwise only fail
deep inside a live fleet:

1. bucket resolution + padding (the static-shape contract);
2. scheduler invariants under a simulated multi-tenant run on a fake
   fleet: slot uniqueness, per-tenant quota, fair-share progress
   (no tenant starved), graceful completion of every request;
3. the decode program LOWERS on a CPU mesh (trace-level check of the
   KV-cache forward — no execution, no compile);
4. every serve metric name is Prometheus-clean (the PR 2 lint).
"""

from __future__ import annotations


def _check_buckets() -> None:
    from ray_lightning_tpu.serve.buckets import (bucket_for, pad_to_bucket,
                                                 resolve_buckets)
    bs = resolve_buckets(None, 300)
    assert bs[-1] == 300 and list(bs) == sorted(bs), bs
    assert resolve_buckets((16, 64), 64) == (16, 64)
    assert bucket_for(1, bs) == bs[0]
    assert bucket_for(33, (32, 64)) == 64
    for bad in (lambda: bucket_for(65, (32, 64)),
                lambda: resolve_buckets((128,), 64),
                lambda: resolve_buckets((), 64)):
        try:
            bad()
        except ValueError:
            pass
        else:
            raise AssertionError("expected ValueError")
    padded = pad_to_bucket([5, 6, 7], 8)
    assert padded.shape == (1, 8) and list(padded[0, :3]) == [5, 6, 7]
    print("serve selfcheck: bucket resolution + padding OK")


def _check_scheduler() -> None:
    import numpy as np
    from ray_lightning_tpu.serve.scheduler import Scheduler

    sched = Scheduler(buckets=(8, 16), slots=4, max_seq_len=32,
                      quotas={"greedy": 1}, max_prefills_per_step=2,
                      default_max_new_tokens=4)
    reqs = []
    for i in range(6):
        reqs.append(sched.submit(np.arange(1, 4 + i % 3), tenant="greedy"))
        reqs.append(sched.submit(np.arange(1, 5), tenant="quiet"))
    steps = 0
    while not sched.idle():
        steps += 1
        assert steps < 200, "scheduler failed to converge"
        plan = sched.plan()
        if plan is None:
            break
        # invariants on the live plan
        live = sched.allocator.in_use()
        assert len(live) == len(set(live)) <= 4
        greedy = sched.stats()["per_tenant"].get("greedy", {})
        assert greedy.get("active", 0) <= 1, "quota violated"
        result = {"prefill": {p["slot"]: 7 for p in plan["prefills"]},
                  "decode": {}}
        if plan["decode"] is not None:
            result["decode"] = {s: 9 for s in plan["decode"]["slots"]}
        sched.apply(plan, result)
    assert all(r.done() for r in reqs), "requests starved"
    assert sched.completed == len(reqs)
    st = sched.stats()
    assert st["per_tenant"]["quiet"]["served_tokens"] > 0
    assert 0 < st["batch_occupancy"] <= 1.0
    print(f"serve selfcheck: scheduler invariants OK "
          f"({sched.completed} requests in {steps} steps, occupancy "
          f"{st['batch_occupancy']:.2f})")


def _check_decode_lowers() -> None:
    import jax
    import numpy as np

    from ray_lightning_tpu.core.steps import (build_decode_step,
                                              build_prefill_step)
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule

    module = GPTLightningModule(GPTConfig(
        vocab_size=64, block_size=16, n_layer=2, n_head=2, n_embd=32,
        remat=False))
    model = module.configure_decode_model()
    aparams = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                             jax.ShapeDtypeStruct((1, 8), np.int32)
                             )["params"]
    S, L, H, D = 2, 16, 2, 16
    kv = jax.ShapeDtypeStruct((2, S, L, H, D), model.config.dtype)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, np.int32)  # noqa: E731
    jax.jit(build_decode_step(module)).lower(
        aparams, kv, kv, i32(S), i32(S))
    jax.jit(build_prefill_step(module, 8)).lower(
        aparams, kv, kv, i32(1, 8), i32(), i32())
    print("serve selfcheck: prefill/decode programs lower on a CPU mesh")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import validate_metric_name
    for name in ("rlt_serve_requests_total", "rlt_serve_tokens_total",
                 "rlt_serve_queue_depth_total",
                 "rlt_serve_active_slots_total",
                 "rlt_serve_ttft_seconds", "rlt_serve_tpot_seconds",
                 "rlt_serve_queue_wait_seconds",
                 "rlt_serve_traces_total",
                 "rlt_serve_prefill_seconds_total",
                 "rlt_serve_decode_seconds_total"):
        validate_metric_name(name)
    print("serve selfcheck: metric names Prometheus-clean")


def _main(argv: list) -> int:
    _check_buckets()
    _check_scheduler()
    _check_metric_names()
    _check_decode_lowers()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
