"""Serve-plane selfcheck for ``format.sh --check`` (CI gate).

Same contract as the comm/compile selfchecks: cheap, deterministic,
no pytest — validates the invariants that would otherwise only fail
deep inside a live fleet:

1. bucket resolution + padding (the static-shape contract);
2. scheduler invariants under a simulated multi-tenant run on a fake
   fleet: slot uniqueness, per-tenant quota, fair-share progress
   (no tenant starved), graceful completion of every request;
3. the decode program LOWERS on a CPU mesh (trace-level check of the
   KV-cache forward — no execution, no compile);
4. every serve metric name is Prometheus-clean (the PR 2 lint).
"""

from __future__ import annotations


def _check_buckets() -> None:
    from ray_lightning_tpu.serve.buckets import (bucket_for, pad_to_bucket,
                                                 resolve_buckets)
    bs = resolve_buckets(None, 300)
    assert bs[-1] == 300 and list(bs) == sorted(bs), bs
    assert resolve_buckets((16, 64), 64) == (16, 64)
    assert bucket_for(1, bs) == bs[0]
    assert bucket_for(33, (32, 64)) == 64
    for bad in (lambda: bucket_for(65, (32, 64)),
                lambda: resolve_buckets((128,), 64),
                lambda: resolve_buckets((), 64)):
        try:
            bad()
        except ValueError:
            pass
        else:
            raise AssertionError("expected ValueError")
    padded = pad_to_bucket([5, 6, 7], 8)
    assert padded.shape == (1, 8) and list(padded[0, :3]) == [5, 6, 7]
    print("serve selfcheck: bucket resolution + padding OK")


def _check_scheduler() -> None:
    import numpy as np
    from ray_lightning_tpu.serve.scheduler import Scheduler

    sched = Scheduler(buckets=(8, 16), slots=4, max_seq_len=32,
                      quotas={"greedy": 1}, max_prefills_per_step=2,
                      default_max_new_tokens=4)
    reqs = []
    for i in range(6):
        reqs.append(sched.submit(np.arange(1, 4 + i % 3), tenant="greedy"))
        reqs.append(sched.submit(np.arange(1, 5), tenant="quiet"))
    steps = 0
    while not sched.idle():
        steps += 1
        assert steps < 200, "scheduler failed to converge"
        plan = sched.plan()
        if plan is None:
            break
        # invariants on the live plan
        live = sched.allocator.in_use()
        assert len(live) == len(set(live)) <= 4
        greedy = sched.stats()["per_tenant"].get("greedy", {})
        assert greedy.get("active", 0) <= 1, "quota violated"
        result = {"prefill": {p["slot"]: 7 for p in plan["prefills"]},
                  "decode": {}}
        if plan["decode"] is not None:
            result["decode"] = {s: 9 for s in plan["decode"]["slots"]}
        sched.apply(plan, result)
    assert all(r.done() for r in reqs), "requests starved"
    assert sched.completed == len(reqs)
    st = sched.stats()
    assert st["per_tenant"]["quiet"]["served_tokens"] > 0
    assert 0 < st["batch_occupancy"] <= 1.0
    print(f"serve selfcheck: scheduler invariants OK "
          f"({sched.completed} requests in {steps} steps, occupancy "
          f"{st['batch_occupancy']:.2f})")


def _check_spec_fold() -> None:
    """Speculative-decode fold invariants, driven with fabricated
    draft/verify results (no model): the accounting identity
    ``emitted == accepted + corrected`` across ragged acceptance
    patterns (accept-0, accept-k, mid-prefix), the max_new truncation,
    and the rolling-window fallback to plain decode."""
    import numpy as np
    from ray_lightning_tpu.serve.scheduler import Scheduler
    from ray_lightning_tpu.serve.spec import SpecConfig

    spec = SpecConfig(enabled=True, k=3, window=4, min_accept=0.5)
    sched = Scheduler(buckets=(8, 16), slots=2, max_seq_len=32,
                      default_max_new_tokens=7, spec=spec)
    req = sched.submit(np.arange(1, 5))
    plan = sched.plan()
    assert plan["prefills"] and plan["prefills"][0]["draft"], plan
    slot = plan["prefills"][0]["slot"]
    sched.apply(plan, {"prefill": {slot: 7}, "decode": {}})

    def round_(draft, verify):
        plan = sched.plan()
        assert plan["decode"]["spec"] is True
        sched.apply(plan, {"prefill": {}, "decode": {
            slot: {"draft": list(draft), "verify": list(verify)}}})

    round_([10, 11, 12], [10, 11, 12, 13])    # accept-k: 4 emitted
    round_([20, 21, 22], [30, 31, 32, 33])    # accept-0: 1 corrected
    round_([40, 41, 42], [40, 50, 51, 52])    # mid-prefix: accept 1
    # 7 tokens total -> max_new reached mid-round (truncation leg)
    assert req.done() and list(req.generated) == \
        [7, 10, 11, 12, 13, 30, 40], list(req.generated)
    s = sched.stats()["spec"]
    assert s["emitted"] == s["accepted"] + s["corrected"] == 6, s
    assert s["accepted"] == 4 and s["corrected"] == 2, s
    assert s["drafted"] == 9 and s["slot_steps"] == 3, s
    assert s["tokens_per_target_forward"] == 2.0, s

    # fallback: acceptance collapses below min_accept -> spec off for
    # the request's remaining life, verify[:1] only
    req2 = sched.submit(np.arange(1, 5))
    plan = sched.plan()
    slot = plan["prefills"][0]["slot"]
    sched.apply(plan, {"prefill": {slot: 7}, "decode": {}})
    for i in range(2):       # window arms at window//2 = 2 entries
        assert not req2.spec_off, i
        round_([60 + i, 61, 62], [70 + i, 71, 72, 73])
    assert req2.spec_off, "acceptance floor did not trip"
    assert sched.stats()["spec"]["fallbacks"] == 1
    plan = sched.plan()
    assert plan["decode"].get("spec") is not True, plan["decode"]
    print("serve selfcheck: spec fold accounting + fallback OK")


def _check_spec_lowers() -> None:
    """The draft and verify programs LOWER on a CPU mesh (trace-level,
    no execution) — the program-count invariant's new members."""
    import jax
    import numpy as np

    from ray_lightning_tpu.core.steps import (build_draft_step,
                                              build_verify_step)
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule

    module = GPTLightningModule(GPTConfig(
        vocab_size=64, block_size=16, n_layer=2, n_head=2, n_embd=32,
        remat=False))
    module.setup_model()
    draft = module.configure_draft(layers=1)
    aparams = jax.eval_shape(
        module.configure_decode_model().init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, 8), np.int32))["params"]
    adraft = jax.eval_shape(draft.init, jax.random.PRNGKey(0),
                            jax.ShapeDtypeStruct((1, 8), np.int32)
                            )["params"]
    S, L, H, D, k = 2, 16, 2, 16, 3
    kv = jax.ShapeDtypeStruct((2, S, L, H, D), draft.config.dtype)
    dkv = jax.ShapeDtypeStruct((1, S, L, H, D), draft.config.dtype)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, np.int32)  # noqa: E731
    jax.jit(build_draft_step(module, k, model=draft)).lower(
        adraft, dkv, dkv, i32(S), i32(S))
    jax.jit(build_verify_step(module, k)).lower(
        aparams, kv, kv, i32(S, k + 1), i32(S, k + 1))
    print("serve selfcheck: draft/verify programs lower on a CPU mesh")


def _check_spec_cost_model() -> None:
    from ray_lightning_tpu.plan.cost import (expected_accepted,
                                             speculative_speedup)
    assert expected_accepted(1.0, 4) == 4.0
    assert expected_accepted(0.0, 4) == 0.0
    assert abs(expected_accepted(0.5, 2) - 0.75) < 1e-12
    assert speculative_speedup(0.9, 4, 0.25) > 1.0
    assert speculative_speedup(0.05, 4, 0.5) < 1.0
    print("serve selfcheck: speculative cost model OK")


def _check_decode_lowers() -> None:
    import jax
    import numpy as np

    from ray_lightning_tpu.core.steps import (build_decode_step,
                                              build_prefill_step)
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule

    module = GPTLightningModule(GPTConfig(
        vocab_size=64, block_size=16, n_layer=2, n_head=2, n_embd=32,
        remat=False))
    model = module.configure_decode_model()
    aparams = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                             jax.ShapeDtypeStruct((1, 8), np.int32)
                             )["params"]
    S, L, H, D = 2, 16, 2, 16
    kv = jax.ShapeDtypeStruct((2, S, L, H, D), model.config.dtype)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, np.int32)  # noqa: E731
    jax.jit(build_decode_step(module)).lower(
        aparams, kv, kv, i32(S), i32(S))
    jax.jit(build_prefill_step(module, 8)).lower(
        aparams, kv, kv, i32(1, 8), i32(), i32())
    print("serve selfcheck: prefill/decode programs lower on a CPU mesh")


def _check_metric_names() -> None:
    from ray_lightning_tpu.telemetry.metrics import validate_metric_name
    for name in ("rlt_serve_requests_total", "rlt_serve_tokens_total",
                 "rlt_serve_queue_depth_total",
                 "rlt_serve_active_slots_total",
                 "rlt_serve_ttft_seconds", "rlt_serve_tpot_seconds",
                 "rlt_serve_queue_wait_seconds",
                 "rlt_serve_traces_total",
                 "rlt_serve_prefill_seconds_total",
                 "rlt_serve_decode_seconds_total",
                 "rlt_spec_acceptance_rate", "rlt_spec_drafted_total",
                 "rlt_spec_accepted_total", "rlt_spec_fallbacks_total"):
        validate_metric_name(name)
    print("serve selfcheck: metric names Prometheus-clean")


def _main(argv: list) -> int:
    _check_buckets()
    _check_scheduler()
    _check_spec_fold()
    _check_spec_cost_model()
    _check_metric_names()
    _check_decode_lowers()
    _check_spec_lowers()
    return 0


if __name__ == "__main__":   # pragma: no cover - exercised via format.sh
    import sys
    sys.exit(_main(sys.argv[1:]))
