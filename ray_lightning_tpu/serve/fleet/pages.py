"""Paged KV accounting + prefix-hash reuse (the host half of the
fleet's "prefill once per replica" story).

The device cache stays the slot-contiguous ``[n_layer, S, L, H, D]``
pair (serve/kvcache.py) — preallocated like every static-shape array in
this framework — so "paging" here is NOT physical indirection but the
two host-side structures that make page-granular reuse sound:

- :class:`PagePool` — a free-list over the ``S * (L // page_size)``
  fixed-size pages backing the cache.  Live slots consume pages lazily
  as their position advances; a finished slot can be RETAINED as a
  prefix donor, keeping only its registered prefix pages on the books.
  The pool is what bounds retention: when every slot is held
  (live + donors) the scheduler evicts the least-recently-used donor to
  admit new work.  Invariant (fleet/selfcheck.py): ``free + allocated
  == total`` after every operation.

- :class:`PrefixIndex` — a hash table over token prefixes at page
  granularity.  A slot's prompt registers one entry per whole page
  (``hash(tokens[:k*page_size])``); a new prompt looks up its LONGEST
  page-aligned matching prefix, with an exact token comparison on the
  candidate so a hash collision can never alias two different prompts
  onto one K/V block.  A hit means the matched pages are copied
  device-side from the donor slot (engine ``kv_copy`` program) and only
  the suffix is computed — prefill tokens actually computed vs
  requested is the measured ``prefix_reuse`` savings number the bench
  reports.

Soundness of reuse: a K/V cache row is a pure per-token value —
``k/v = Dense(embed(token) + wpe[pos])`` — so identical (token,
position) prefixes have identical rows whatever bucket or slot computed
them.  Donor rows stay valid because (a) live slots only ever write at
their own advancing position, and (b) with paging enabled the scheduler
points idle slots' dummy decode writes at ``max_seq_len - 1`` (outside
every registered page; registration is capped below that row) instead
of position 0, which would corrupt the very first page of every
retained donor.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import os
from typing import Optional

import numpy as np


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name, "").strip()
    if raw in ("0", "false", "False"):
        return False
    if raw in ("1", "true", "True"):
        return True
    return default


@dataclasses.dataclass(frozen=True)
class PageConfig:
    """Paged-KV knobs, resolved like every other plane config.

    enabled: master switch — off keeps the serve plane byte-identical
        to the pre-fleet behavior (no copy/suffix programs built, dummy
        decode writes stay at position 0).
    page_size: tokens per page; prefix matching and donor retention
        happen at whole-page granularity.  Smaller pages match more,
        cost more index entries.
    """

    enabled: bool = False
    page_size: int = 16

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")

    @classmethod
    def resolve(cls, value) -> "PageConfig":
        """``Server(paged=...)`` → a config.  ``None`` defers to the
        ``RLT_SERVE_PAGED`` / ``RLT_SERVE_PAGE_SIZE`` env knobs (the
        worker_env round-trip, mirroring RLT_COMM*/RLT_ELASTIC*)."""
        if isinstance(value, cls):
            return value
        if isinstance(value, bool):
            return cls(enabled=value)
        if isinstance(value, int):
            return cls(enabled=True, page_size=value)
        if isinstance(value, dict):
            cfg = dict(value)
            cfg.setdefault("enabled", True)
            return cls(**cfg)
        if value is not None:
            raise TypeError(f"bad paged config: {value!r}")
        return cls(
            enabled=_env_flag("RLT_SERVE_PAGED", False),
            page_size=int(os.environ.get("RLT_SERVE_PAGE_SIZE", "16")
                          or 16),
        )

    def worker_env(self) -> dict:
        """Env mapping reproducing this config via :meth:`resolve` in a
        worker process (replica actors inherit it under both cluster
        backends)."""
        if not self.enabled:
            return {}
        return {"RLT_SERVE_PAGED": "1",
                "RLT_SERVE_PAGE_SIZE": str(self.page_size)}


def identity_page_table(slots: int, max_seq_len: int,
                        page_size: int) -> np.ndarray:
    """``[slots, pages_per_slot]`` int32 physical-page table for the
    slot-contiguous device cache: page ``p`` of slot ``s`` lives at
    physical page ``s * pages_per_slot + p`` of the
    ``[slots * pages_per_slot, page_size, C]`` page view.

    This is the table the paged flash-decode kernel
    (ops/flash_decode.py) walks in its KV BlockSpec index_map.  Today
    the mapping is the identity because the cache IS slot-contiguous
    (module docstring: paging is host accounting, not physical
    indirection) — but the kernel contract is already the indirect one,
    so physical page sharing later only changes this table, not the
    kernel.  Requires ``page_size`` to tile ``max_seq_len`` exactly
    (a ragged final page would alias rows of the next slot)."""
    if max_seq_len % page_size:
        raise ValueError(
            f"page_size {page_size} must tile max_seq_len "
            f"{max_seq_len} for the paged decode kernel")
    pages_per_slot = max_seq_len // page_size
    return (np.arange(slots, dtype=np.int32)[:, None] * pages_per_slot
            + np.arange(pages_per_slot, dtype=np.int32)[None, :])


class PagePool:
    """Free-list over the fixed-size pages backing the slot cache.

    Pages are accounting units (the arrays are preallocated); what the
    pool genuinely arbitrates is donor retention: retained prefix pages
    hold real cache rows hostage, and the free-list is what decides
    when a donor must be evicted to admit new work.
    """

    def __init__(self, slots: int, max_seq_len: int, page_size: int):
        if page_size < 1 or page_size > max_seq_len:
            raise ValueError(
                f"page_size {page_size} must be in [1, {max_seq_len}]")
        self.slots = int(slots)
        self.max_seq_len = int(max_seq_len)
        self.page_size = int(page_size)
        self.pages_per_slot = -(-max_seq_len // page_size)  # ceil
        self.total_pages = self.slots * self.pages_per_slot
        #: pages currently on the books per slot (live growth + donors)
        self._held: dict[int, int] = {}

    def _pages_for(self, length: int) -> int:
        return -(-max(0, int(length)) // self.page_size)

    @property
    def allocated(self) -> int:
        return sum(self._held.values())

    @property
    def free(self) -> int:
        return self.total_pages - self.allocated

    def note_written(self, slot: int, written_len: int) -> None:
        """Record that ``slot`` now holds K/V rows ``[0, written_len)``
        — page allocation is lazy, charged as the position advances."""
        need = min(self._pages_for(written_len), self.pages_per_slot)
        if need > self._held.get(slot, 0):
            self._held[slot] = need

    def shrink_to(self, slot: int, keep_len: int) -> int:
        """Keep only the pages covering ``[0, keep_len)`` (donor
        retention keeps the registered prefix, frees the decode tail).
        Returns pages freed."""
        keep = min(self._pages_for(keep_len), self.pages_per_slot)
        held = self._held.get(slot, 0)
        if keep <= 0:
            return self.release(slot)
        self._held[slot] = keep
        return max(0, held - keep)

    def release(self, slot: int) -> int:
        """Free every page the slot holds; returns pages freed."""
        return self._held.pop(slot, 0)

    def held(self, slot: int) -> int:
        return self._held.get(slot, 0)

    def check(self) -> None:
        """The structural invariant (fleet/selfcheck.py)."""
        assert 0 <= self.allocated <= self.total_pages, self._held
        assert self.free + self.allocated == self.total_pages
        for slot, n in self._held.items():
            assert 0 <= slot < self.slots and 0 < n <= self.pages_per_slot


def _prefix_hash(tokens: np.ndarray) -> bytes:
    return hashlib.blake2b(
        np.ascontiguousarray(tokens, dtype=np.int32).tobytes(),
        digest_size=16).digest()


class PrefixIndex:
    """Longest page-aligned prefix lookup with exact-token verification.

    One entry per registered slot; per-page hashes let lookup walk from
    the longest candidate down.  Collisions are harmless: every hash hit
    is verified against the stored tokens before it can donate.
    """

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        #: slot -> registered prefix tokens (np.int32, whole pages)
        self._tokens: dict[int, np.ndarray] = {}
        #: hash(prefix of k pages) -> set of slots registering it
        self._by_hash: dict[bytes, set] = {}
        self.hits = 0
        self.misses = 0

    def register(self, slot: int, tokens, limit: Optional[int] = None
                 ) -> int:
        """Register ``slot`` as a donor for its prompt's whole pages
        (capped at ``limit`` rows — the scheduler passes
        ``max_seq_len - 1`` so the dummy-write row is never donatable).
        Returns the registered length in tokens (0 = nothing to offer).
        """
        self.drop(slot)
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        n = len(tokens)
        if limit is not None:
            n = min(n, int(limit))
        n_pages = n // self.page_size
        if n_pages == 0:
            return 0
        reg = tokens[:n_pages * self.page_size].copy()
        self._tokens[slot] = reg
        for k in range(1, n_pages + 1):
            h = _prefix_hash(reg[:k * self.page_size])
            self._by_hash.setdefault(h, set()).add(slot)
        return len(reg)

    def drop(self, slot: int) -> None:
        reg = self._tokens.pop(slot, None)
        if reg is None:
            return
        for k in range(1, len(reg) // self.page_size + 1):
            h = _prefix_hash(reg[:k * self.page_size])
            slots = self._by_hash.get(h)
            if slots is not None:
                slots.discard(slot)
                if not slots:
                    del self._by_hash[h]

    def lookup(self, tokens, exclude: Optional[int] = None
               ) -> "tuple[int, int] | None":
        """Longest page-aligned matching prefix among registered slots:
        ``(donor_slot, matched_tokens)`` or ``None``.  The candidate's
        stored tokens are compared exactly — a hash collision can
        never alias."""
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        max_pages = len(tokens) // self.page_size
        for k in range(max_pages, 0, -1):
            prefix = tokens[:k * self.page_size]
            for slot in self._by_hash.get(_prefix_hash(prefix), ()):
                if slot == exclude:
                    continue
                reg = self._tokens.get(slot)
                if reg is not None and len(reg) >= len(prefix) \
                        and np.array_equal(reg[:len(prefix)], prefix):
                    self.hits += 1
                    return slot, len(prefix)
        self.misses += 1
        return None

    def registered(self) -> "tuple[int, ...]":
        return tuple(sorted(self._tokens))


class PagedKV:
    """The scheduler's paging facade: pool + index + donor LRU +
    the prefill-token savings counters."""

    def __init__(self, cfg: PageConfig, slots: int, max_seq_len: int):
        self.cfg = cfg
        self.page_size = cfg.page_size
        self.max_seq_len = int(max_seq_len)
        self.pool = PagePool(slots, max_seq_len, cfg.page_size)
        self.index = PrefixIndex(cfg.page_size)
        #: slots retained as donors after their request finished,
        #: in retention order (front = least recently useful)
        self._donors: dict[int, int] = {}
        #: donors a KV-ship is about to export: admission pressure must
        #: not evict them (the export would skip — or worse, fetch rows
        #: a re-admitted slot already overwrote).  COUNTED, not a set:
        #: a finish-time hold and a concurrent export of a prefix-
        #: sharing prompt may pin the same slot independently
        self._pinned: dict[int, int] = {}
        self._lru = itertools.count()
        self.tokens_requested = 0
        self.tokens_computed = 0
        self.reused_prefills = 0
        #: fleet federation (serve/fleet/federation.py): the router
        #: binds (replica id, directory) so donor retention advertises
        #: fleet-wide and donor eviction invalidates.  Only RETAINED
        #: donors advertise — they are pinnable for the export leg, so
        #: their rows can't be overwritten mid-fetch; live slots could.
        self._fed = None
        self._fed_rid: Optional[int] = None
        #: slots whose donor rows were IMPORTED over the KV-ship plane
        #: (adopt_commit) rather than prefilled here — the remote-donor
        #: accounting behind the fleet's federated_reuse_ratio
        self._remote: set = set()
        self.remote_imports = 0
        self.federated_tokens_reused = 0

    # -- admission ---------------------------------------------------------

    def match(self, tokens) -> "tuple[int, int] | None":
        """Donor lookup for an admitting prompt; refreshes the donor's
        LRU stamp on a hit."""
        hit = self.index.lookup(tokens)
        if hit is not None and hit[0] in self._donors:
            self._donors[hit[0]] = next(self._lru)
        return hit

    def on_admit(self, slot: int, tokens, computed: int,
                 src: Optional[int] = None) -> None:
        """Account an admission: the slot leaves donor state (if the
        allocator handed back a retained slot), registers as a fresh
        donor for its own prompt, and charges its prompt pages.
        ``src`` names the donor a reuse hit copied from — when that
        donor's rows were IMPORTED (a federated fetch or a disagg
        ship), the avoided compute counts as federated reuse."""
        if src is not None and src in self._remote:
            self.federated_tokens_reused += max(
                0, len(np.atleast_1d(tokens)) - int(computed))
        self._fed_drop(slot)
        self._donors.pop(slot, None)
        self._remote.discard(slot)
        # the final cache row is the paging dummy-write target; never
        # donate it (module docstring)
        self.index.register(slot, tokens, limit=self.max_seq_len - 1)
        self.pool.note_written(slot, len(np.atleast_1d(tokens)))
        self.tokens_requested += len(np.atleast_1d(tokens))
        self.tokens_computed += int(computed)
        if computed < len(np.atleast_1d(tokens)):
            self.reused_prefills += 1

    # -- decode progress ---------------------------------------------------

    def on_advance(self, slot: int, pos: int) -> None:
        self.pool.note_written(slot, pos + 1)

    # -- eviction / retention ----------------------------------------------

    def retain(self, slot: int) -> bool:
        """Called when ``slot``'s request finishes: keep it as a donor
        when it has registered pages to offer (True = the scheduler
        must NOT release the slot), else free everything."""
        reg = self.index._tokens.get(slot)
        if reg is None or len(reg) == 0:
            self.index.drop(slot)
            self.pool.release(slot)
            return False
        self.pool.shrink_to(slot, len(reg))
        self._donors[slot] = next(self._lru)
        if self._fed is not None:
            # retention IS the fleet advertisement: from here until
            # eviction these rows are pinnable, so a federated fetch
            # can never race an overwrite
            self._fed.register(self._fed_rid, slot, reg)
        return True

    def pin(self, slot: int) -> None:
        """Shield a donor from LRU eviction while a KV-ship leg holds
        it (pinned until the export fetches its rows, or the router
        releases the hold on a failed leg)."""
        if slot in self._donors:
            self._pinned[slot] = self._pinned.get(slot, 0) + 1

    def unpin(self, slot: int) -> None:
        n = self._pinned.get(slot)
        if n is not None:
            if n <= 1:
                self._pinned.pop(slot)
            else:
                self._pinned[slot] = n - 1

    def evict_lru_donor(self, exclude: Optional[int] = None
                        ) -> "int | None":
        """Free the least-recently-useful donor's slot (admission
        pressure); returns the slot to hand back to the allocator.
        ``exclude`` protects the donor the admission is ABOUT to copy
        from (scheduler plan order: match, then evict) — evicting the
        one donor you need defeats the cache exactly under the slot
        pressure that makes it valuable.  Pinned donors (a KV-ship in
        flight) never evict: admission waits for the ship to release
        them instead of starving the export."""
        candidates = [s for s in self._donors
                      if s != exclude and s not in self._pinned]
        if not candidates:
            return None
        slot = min(candidates, key=self._donors.get)
        self._donors.pop(slot)
        self._remote.discard(slot)
        self.index.drop(slot)
        self.pool.release(slot)
        self._fed_drop(slot)
        return slot

    def drop_all(self) -> None:
        """fail_all reset: every slot's pages and index entries go."""
        for slot in list(self.index.registered()):
            self.index.drop(slot)
        self._donors.clear()
        self._pinned.clear()
        self._remote.clear()
        self.pool._held.clear()
        if self._fed is not None:
            self._fed.invalidate_replica(self._fed_rid)

    @property
    def donor_count(self) -> int:
        return len(self._donors)

    # -- fleet federation hooks --------------------------------------------

    def bind_federation(self, rid: int, directory) -> None:
        """Router hook: advertise this replica's donor retentions to
        the fleet directory (and invalidate on eviction) from here on.
        """
        self._fed_rid = int(rid)
        self._fed = directory

    def _fed_drop(self, slot: int) -> None:
        if self._fed is not None:
            self._fed.invalidate(self._fed_rid, slot)

    def mark_remote(self, slot: int) -> None:
        """Scheduler hook (adopt_commit): this donor's rows arrived
        over the wire, not from a local prefill."""
        self._remote.add(slot)
        self.remote_imports += 1

    # -- evidence ----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "page_size": self.page_size,
            "pages_total": self.pool.total_pages,
            "pages_free": self.pool.free,
            "pages_allocated": self.pool.allocated,
            "donors": self.donor_count,
            "pinned_donors": len(self._pinned),
            "prefix_hits": self.index.hits,
            "prefix_misses": self.index.misses,
            "reused_prefills": self.reused_prefills,
            "prefill_tokens_requested": self.tokens_requested,
            "prefill_tokens_computed": self.tokens_computed,
            "prefix_reuse_ratio": round(
                1.0 - self.tokens_computed / self.tokens_requested, 4)
            if self.tokens_requested else 0.0,
            "remote_donors": len(self._remote & set(self._donors)),
            "remote_imports": self.remote_imports,
            "federated_tokens_reused": self.federated_tokens_reused,
        }


__all__ = ["PageConfig", "PagePool", "PrefixIndex", "PagedKV",
           "identity_page_table"]
